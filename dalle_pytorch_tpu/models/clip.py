"""CLIP: text & image encoders with a symmetric InfoNCE objective.

TPU-native equivalent of the reference `CLIP`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:274-350`): token/patch
embeddings + positional embeddings, non-causal transformer encoders, masked
mean pooling for text, L2-normalized latents, learnable temperature
(stored as log-space parameter whose exp scales similarities), and the
symmetric cross-entropy loss over the in-batch similarity matrix. Used by
the generation pipeline to rerank samples (`dalle_pytorch.py:569-571`).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import flax.linen as nn

from dalle_pytorch_tpu.models.transformer import Transformer
from dalle_pytorch_tpu.models.dalle import cross_entropy


class CLIP(nn.Module):
    dim_text: int = 512
    dim_image: int = 512
    dim_latent: int = 512
    num_text_tokens: int = 10000
    text_enc_depth: int = 6
    text_seq_len: int = 256
    text_heads: int = 8
    num_visual_tokens: int = 512
    visual_enc_depth: int = 6
    visual_heads: int = 8
    visual_image_size: int = 256
    visual_patch_size: int = 32
    channels: int = 3
    # layer executor for both encoders: "unrolled" | "scan" (one compiled
    # layer body; see models/transformer.py)
    executor: str = "unrolled"
    dtype: Any = jnp.float32

    def setup(self):
        assert self.visual_image_size % self.visual_patch_size == 0
        self.num_patches = (self.visual_image_size // self.visual_patch_size) ** 2

        self.text_emb = nn.Embed(self.num_text_tokens, self.dim_text, dtype=self.dtype)
        self.text_pos_emb = nn.Embed(self.text_seq_len, self.dim_text, dtype=self.dtype)
        self.text_transformer = Transformer(
            dim=self.dim_text,
            depth=self.text_enc_depth,
            seq_len=self.text_seq_len,
            causal=False,
            heads=self.text_heads,
            rotary_emb=False,
            executor=self.executor,
            dtype=self.dtype,
        )
        self.to_text_latent = nn.Dense(self.dim_latent, use_bias=False, dtype=self.dtype)

        self.to_visual_embedding = nn.Dense(self.dim_image, dtype=self.dtype)
        self.visual_pos_emb = nn.Embed(self.num_patches, self.dim_image, dtype=self.dtype)
        self.visual_transformer = Transformer(
            dim=self.dim_image,
            depth=self.visual_enc_depth,
            seq_len=self.num_patches,
            causal=False,
            heads=self.visual_heads,
            rotary_emb=False,
            executor=self.executor,
            dtype=self.dtype,
        )
        self.to_visual_latent = nn.Dense(self.dim_latent, use_bias=False, dtype=self.dtype)

        self.temperature = self.param("temperature", nn.initializers.ones, ())

    def _patches(self, image: jnp.ndarray) -> jnp.ndarray:
        """[B, H, W, C] -> [B, n_patches, p*p*C]."""
        p = self.visual_patch_size
        b, hh, ww, c = image.shape
        h, w = hh // p, ww // p
        x = image.reshape(b, h, p, w, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5)
        return x.reshape(b, h * w, p * p * c)

    def __call__(
        self,
        text: jnp.ndarray,
        image: jnp.ndarray,
        text_mask: Optional[jnp.ndarray] = None,
        return_loss: bool = False,
        deterministic: bool = True,
    ):
        b = text.shape[0]

        text_emb = self.text_emb(text) + self.text_pos_emb(jnp.arange(text.shape[1]))
        image_emb = self.to_visual_embedding(self._patches(image))
        image_emb = image_emb + self.visual_pos_emb(jnp.arange(image_emb.shape[1]))

        enc_text = self.text_transformer(
            text_emb, key_mask=text_mask, deterministic=deterministic
        )
        enc_image = self.visual_transformer(image_emb, deterministic=deterministic)

        if text_mask is not None:
            m = text_mask[..., None].astype(enc_text.dtype)
            text_latents = (enc_text * m).sum(1) / m.sum(1)
        else:
            text_latents = enc_text.mean(axis=1)
        image_latents = enc_image.mean(axis=1)

        text_latents = self.to_text_latent(text_latents)
        image_latents = self.to_visual_latent(image_latents)

        norm = lambda t: t / jnp.linalg.norm(t, axis=-1, keepdims=True)
        text_latents, image_latents = norm(text_latents), norm(image_latents)

        temp = jnp.exp(self.temperature)

        if not return_loss:
            return jnp.einsum("nd,nd->n", text_latents, image_latents) * temp

        sim = jnp.einsum("id,jd->ij", text_latents, image_latents) * temp
        labels = jnp.arange(b)
        loss = (cross_entropy(sim, labels) + cross_entropy(sim.T, labels)) / 2
        return loss


def clip_scores(
    clip: CLIP,
    variables,
    text: jnp.ndarray,
    images: jnp.ndarray,
    text_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Per-pair CLIP similarity of (text[i], images[i]) — the quantity the
    reference reranks generations with (`dalle_pytorch.py:569-571`)."""
    return clip.apply(variables, text, images, text_mask=text_mask, return_loss=False)


def rerank(
    clip: CLIP,
    variables,
    text: jnp.ndarray,
    images: jnp.ndarray,
    text_mask: Optional[jnp.ndarray] = None,
):
    """Sort generated images (and scores) by descending CLIP similarity.

    `text` is broadcast against images if a single prompt row is given.
    Returns (sorted_images, sorted_scores, order).
    """
    if text.shape[0] == 1 and images.shape[0] > 1:
        text = jnp.repeat(text, images.shape[0], axis=0)
        if text_mask is not None:
            text_mask = jnp.repeat(text_mask, images.shape[0], axis=0)
    scores = clip_scores(clip, variables, text, images, text_mask)
    order = jnp.argsort(-scores)
    return images[order], scores[order], order
