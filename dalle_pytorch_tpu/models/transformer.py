"""Transformer block assembly for DALLE/CLIP.

TPU-native re-design of the reference transformer
(`/root/reference/dalle_pytorch/transformer.py:206-353`). Feature parity:

  * per-layer attention-type cycling over
    {full, sparse, axial_row, axial_col, conv_like} (`transformer.py:238-266`)
    — every variant realized as dense attention + static mask (ops/masks.py);
  * cross-layer weight sharing via shared_attn_ids / shared_ff_ids
    (`transformer.py:242-279`) — flax module reuse shares parameters;
  * LayerScale with depth-dependent init (`transformer.py:76-90`);
  * PreNorm with optional sandwich output norm (`transformer.py:94-104`);
  * GEGLU feed-forward (`transformer.py:108-124`);
  * token-shift before attention and FF (`transformer.py:128-202`), as a
    pure function on the fixed-shape sequence;
  * dual rotary embeddings (1-D text + 2-D axial pixel with sentinel
    positions, `transformer.py:306-330`), precomputed host-side;
  * `reverse_model=True` runs layers in reversed order — the fork's
    inverse-mapping trick (`reversible.py:141-144`);
  * reversible mode, two executors selected by `reversible_impl`:
      - "remat": `jax.remat` per layer (recompute in backward — the memory
        behavior `reversible.py:57-127` buys, cost O(depth) residuals);
      - "revnet": a TRUE RevNet executor via `nn.custom_vjp` matching the
        reference's `ReversibleBlock`/`_ReversibleFunction` math
        (`reversible.py:57-127`): channels duplicated into (x1, x2) streams
        (`reversible.py:158,165`), y1 = x1 + attn(x2), y2 = x2 + ff(y1),
        output = mean of streams; the backward RECONSTRUCTS each block's
        inputs from its outputs (x2 = y2 − g(y1), x1 = y1 − f(x2)) so
        activation memory is O(1) in depth. The reference's CUDA RNG
        state capture (`reversible.py:32-53`) is unnecessary here: the
        revnet path requires deterministic execution (dropout rate 0),
        which JAX guarantees under explicit PRNG keys.

Layer executors (orthogonal to the reversible memory modes):
  * "unrolled" (default): layers unrolled in Python (static depth) — one
    big fusable graph, supports every feature (type cycling, sharing,
    cached decode, revnet);
  * "scan": homogeneous stacks run as `nn.scan` over depth-stacked
    parameters — the HLO contains ONE layer body instead of `depth`
    copies, so programs compile ~depth× faster (load-bearing here: the
    tunneled TPU backend has repeatedly died mid-compile on the unrolled
    flagship program) at identical runtime math. Attn-type cycling runs
    as dense attention with per-layer pattern masks scanned over depth;
    no cross-layer sharing. KV-cached decode is native (the depth-stacked
    cache rides the layer scan as scanned input and output), pattern
    masks included — each layer's traced mask row-slices at the decode
    position like the unrolled executor's static masks.
"""

from __future__ import annotations

import math
from itertools import cycle, islice
from typing import Any, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from dalle_pytorch_tpu.models.attention import Attention
from dalle_pytorch_tpu.ops.masks import (
    axial_static_mask,
    conv_like_mask,
    block_sparse_layout,
    block_layout_to_token_mask,
)
from dalle_pytorch_tpu.ops.rotary import build_dalle_rotary
from dalle_pytorch_tpu.ops.shift import (
    shift_tokens_dalle,
    shift_ring_from_prefill,
    shift_ring_from_prefill_at,
    shift_token_step,
)


def resolve_remat_policy(name: "Optional[str]"):
    """`jax.checkpoint_policies` member by name, or None (save nothing).
    Single resolution point for all three executors (scan, unrolled
    remat, pipeline) so their activation-memory behavior cannot drift."""
    return getattr(jax.checkpoint_policies, name) if name else None


def layerscale_init(layer_index: int) -> float:
    """LayerScale init epsilon by 1-based layer index (`transformer.py:79-84`)."""
    if layer_index <= 18:
        return 0.1
    if layer_index <= 24:
        return 1e-5
    return 1e-6


class DivideMax(nn.Module):
    """Divide by the (detached) max along an axis (`transformer.py:31-38`)."""

    axis: int = -1

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        maxes = jax.lax.stop_gradient(jnp.max(x, axis=self.axis, keepdims=True))
        return x / maxes


class FeedForward(nn.Module):
    """GEGLU feed-forward (`transformer.py:108-124`)."""

    dim: int
    mult: float = 4.0
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        hidden = int(self.dim * self.mult)
        x = nn.Dense(hidden * 2, dtype=self.dtype)(x)
        x, gates = jnp.split(x, 2, axis=-1)
        x = x * nn.gelu(gates)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        return nn.Dense(self.dim, dtype=self.dtype)(x)


def _build_static_mask(
    attn_type: str,
    seq_len: int,
    image_fmap_size: Optional[int],
    layer_ind: int,
    sparse_block: int = 16,
    sparse_text_len: Optional[int] = None,
) -> Optional[np.ndarray]:
    if attn_type == "full":
        return None
    assert image_fmap_size is not None, f"attn_type {attn_type} needs image_fmap_size"
    if attn_type == "axial_row":
        return axial_static_mask(seq_len, image_fmap_size, axis=0)
    if attn_type == "axial_col":
        return axial_static_mask(seq_len, image_fmap_size, axis=1)
    if attn_type == "conv_like":
        return conv_like_mask(seq_len, image_fmap_size)
    if attn_type == "sparse":
        # VariableSparsityConfig semantics (`attention.py:349-365`): block 16,
        # seq//block//4 random blocks, text blocks global. Padded to a block
        # multiple; layer index seeds the random blocks so layers differ.
        padded = sparse_block * math.ceil((seq_len + 1) / sparse_block)
        text_len = sparse_text_len if sparse_text_len is not None else (
            seq_len + 1 - image_fmap_size**2
        )
        layout = block_sparse_layout(
            padded,
            block=sparse_block,
            num_random_blocks=max(padded // sparse_block // 4, 1),
            global_block_indices=tuple(range(math.ceil(text_len / sparse_block))),
            causal=True,
            seed=layer_ind,
        )
        return block_layout_to_token_mask(layout, sparse_block, causal=True)
    raise ValueError(f'attention type "{attn_type}" is not valid')


def shift_with_ring(h, ring, pos, text_len, fmap, ring_end=None):
    """Token-shift dispatch shared by both executors' cached paths.

    ring None: pure batch shift (uncached). Prefill (n > 1, necessarily
    from position 0): batch shift + build the ring from trailing tokens
    — or, when `ring_end` ([B] per-row positions) is set, from each
    row's OWN trailing window below ring_end (the decode-resume path:
    one teacher-forced forward restores per-row mid-decode ring state).
    Single-token decode: streaming shift at traced position `pos`.
    Returns (shifted h, new ring or None).
    """
    if ring is None:
        return shift_tokens_dalle(h, text_len, fmap), None
    if h.shape[1] > 1:
        shifted = shift_tokens_dalle(h, text_len, fmap)
        if ring_end is not None:
            return shifted, shift_ring_from_prefill_at(h, fmap, ring_end)
        return shifted, shift_ring_from_prefill(h, fmap)
    return shift_token_step(h, ring, pos, text_len, fmap)


class _ScanBlock(nn.Module):
    """One (attn, ff) residual pair in scannable form.

    Math-identical to `Transformer._layer` for the uncached, uniform
    full-attention case; LayerScale vectors arrive as scanned-over inputs
    (they are per-layer constants at init, so they live as one stacked
    parameter on the owning Transformer instead of inside the body).
    """

    dim: int
    seq_len: int
    causal: bool
    heads: int
    dim_head: int
    ff_mult: float
    attn_dropout: float
    ff_dropout: float
    stable: bool
    sandwich_norm: bool
    shift_tokens: bool
    text_len: int
    image_fmap_size: Optional[int]
    attn_impl: str
    sp_mesh: Any
    decode_mesh: Any
    decode_heads_axis: str
    decode_sparse_block: Optional[int]
    deterministic: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, attn_scale, ff_scale, pattern_idx, pattern_table,
                 cache, key_mask, rotary):
        # pattern_idx is the scanned per-layer index into the broadcast
        # table of unique [S, S] pattern masks; None = uniform full attention
        pattern_mask = (
            None if pattern_table is None else pattern_table[pattern_idx]
        )
        cached = cache is not None
        pos = cache["attn"]["index"] if cached else None
        # per-row resume window (decode_resume injects it; absent on the
        # ordinary prefill/decode paths and dropped from the new cache)
        ring_end = cache.get("ring_end") if cached else None

        def shift(h, ring):
            if not self.shift_tokens:
                return h, None
            return shift_with_ring(
                h, ring, pos, self.text_len, self.image_fmap_size,
                ring_end=ring_end,
            )

        h = nn.LayerNorm(dtype=self.dtype, name="norm_attn")(x)
        h, ring_attn = shift(h, cache.get("shift_attn") if cached else None)
        h, attn_cache = Attention(
            dim=self.dim,
            seq_len=self.seq_len,
            heads=self.heads,
            dim_head=self.dim_head,
            causal=self.causal,
            dropout=self.attn_dropout,
            stable=self.stable,
            static_mask=None,
            attn_impl=self.attn_impl,
            sp_mesh=self.sp_mesh,
            decode_mesh=self.decode_mesh,
            decode_heads_axis=self.decode_heads_axis,
            decode_sparse_block=self.decode_sparse_block,
            dtype=self.dtype,
            name="attn",
        )(h, key_mask=key_mask, rotary=rotary,
          cache=cache["attn"] if cached else None,
          deterministic=self.deterministic, mask_array=pattern_mask)
        if self.sandwich_norm:
            h = nn.LayerNorm(dtype=self.dtype, name="norm_attn_out")(h)
        x = x + h * attn_scale.astype(h.dtype)

        h = nn.LayerNorm(dtype=self.dtype, name="norm_ff")(x)
        h, ring_ff = shift(h, cache.get("shift_ff") if cached else None)
        h = FeedForward(
            dim=self.dim, mult=self.ff_mult, dropout=self.ff_dropout,
            dtype=self.dtype, name="ff",
        )(h, deterministic=self.deterministic)
        if self.sandwich_norm:
            h = nn.LayerNorm(dtype=self.dtype, name="norm_ff_out")(h)
        x = x + h * ff_scale.astype(h.dtype)

        if not cached:
            return x, None
        new_cache = {"attn": attn_cache}
        if self.shift_tokens:
            new_cache["shift_attn"] = ring_attn
            new_cache["shift_ff"] = ring_ff
        return x, new_cache


class _ScanStack(nn.Module):
    """Depth-stacked `_ScanBlock` driven by `nn.scan`.

    `reverse` (the reference fork's `reverse_model`) flips the iteration —
    both directions share the same "layers" parameter collection, so a
    checkpoint is direction-agnostic exactly like the unrolled executor.
    """

    depth: int
    block_kwargs: Any  # dict of _ScanBlock constructor args (static)
    remat: bool
    remat_policy: Optional[str]

    @nn.compact
    def __call__(self, x, attn_scales, ff_scales, pattern_idx, pattern_table,
                 key_mask, rotary, cache=None, reverse: bool = False,
                 deterministic: bool = True):
        body = _ScanBlock
        if self.remat and cache is None:
            # prevent_cse=False is safe (and recommended) under scan
            body = nn.remat(
                body,
                policy=resolve_remat_policy(self.remat_policy),
                prevent_cse=False,
            )
        # attn-type cycling: each layer picks its pattern mask from the
        # broadcast table of UNIQUE masks via a scanned [depth] index;
        # None (uniform full attention) broadcasts through. The decode
        # cache (depth-stacked leaves) is scanned in AND collected back
        # out as the scan's per-layer output.
        idx_axis = nn.broadcast if pattern_idx is None else 0
        cache_axis = nn.broadcast if cache is None else 0
        scanned = nn.scan(
            body,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(0, 0, idx_axis, nn.broadcast, cache_axis, nn.broadcast,
                     nn.broadcast),
            length=self.depth,
            reverse=reverse,
        )
        stack = scanned(
            deterministic=deterministic, name="layers", **self.block_kwargs
        )
        x, new_cache = stack(
            x, attn_scales, ff_scales, pattern_idx, pattern_table, cache,
            key_mask, rotary,
        )
        if cache is not None:
            return x, new_cache
        return x


class Transformer(nn.Module):
    """Causal (or bidirectional) transformer stack with DALL-E features."""

    dim: int
    depth: int
    seq_len: int
    causal: bool = True
    heads: int = 8
    dim_head: int = 64
    ff_mult: float = 4.0
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    attn_types: Optional[Sequence[str]] = None
    image_fmap_size: Optional[int] = None
    sparse_attn: bool = False  # accepted for reference-parity; unused there too
    stable: bool = False
    sandwich_norm: bool = False
    shift_tokens: bool = False
    rotary_emb: bool = True
    shared_attn_ids: Optional[Sequence[int]] = None
    shared_ff_ids: Optional[Sequence[int]] = None
    reversible: bool = False
    reversible_impl: str = "remat"  # "remat" | "revnet" | "revnet_naive" (test)
    # jax.checkpoint policy name for the remat executor (e.g.
    # "dots_with_no_batch_dims_saveable" keeps matmul outputs and only
    # recomputes cheap elementwise work in the backward — much faster than
    # full recompute for a modest memory cost). None = save nothing.
    remat_policy: Optional[str] = None
    attn_impl: str = "auto"  # "dense" | "flash" | "ring" | "auto"
    sp_mesh: Any = None  # Mesh with "sp" axis for attn_impl="ring"
    decode_mesh: Any = None  # serving mesh for sharded flash decode
    decode_heads_axis: str = "tp"  # mesh axis the kernel splits heads over
    # decode-time policy-sparse KV tile width (None = DECODE_SPARSE_BLOCK
    # in models/attention.py); static config the serving engine clones in
    # with --decode_sparsity=policy — the bitmap itself stays traced data
    decode_sparse_block: Optional[int] = None
    # "unrolled" | "scan" — see module docstring. "scan" compiles one layer
    # body instead of `depth` copies; masked attn types run as dense with
    # depth-stacked scanned pattern masks; cached decode is native,
    # pattern masks included. No shared ids, no revnet.
    executor: str = "unrolled"
    dtype: Any = jnp.float32

    def _scan_supported(self) -> Optional[str]:
        """None if the scan executor can run this config, else the reason."""
        if self.attn_types and any(t != "full" for t in self.attn_types):
            # masked attn types run as dense + per-layer pattern masks
            # scanned over depth; flash/lib_flash need host-side masks for
            # block skipping, so they cannot take the scanned (traced) ones
            if self.attn_impl in ("flash", "lib_flash"):
                return (
                    f'attn_impl="{self.attn_impl}" with masked attn_types '
                    "(scanned pattern masks are traced; use dense/auto)"
                )
        if self.shared_attn_ids or self.shared_ff_ids:
            return "cross-layer weight sharing"
        if self.reversible and self.reversible_impl != "remat":
            return "revnet reversible executor"
        if self.attn_impl == "ring" or self.sp_mesh is not None:
            # shard_map inside nn.scan is unvalidated; keep the guard with
            # the executor rather than only in training/pipeline.py.
            return "ring attention / sp mesh"
        return None

    def setup(self):
        if self.shift_tokens and self.image_fmap_size is None:
            # executor-independent invariant (shift_tokens_dalle needs the
            # image geometry); checked here so both executors fail at bind
            # time with the same clear message instead of a mid-trace
            # assert/TypeError deep in the layer body
            raise ValueError("shift_tokens=True requires image_fmap_size")
        if self.executor == "scan":
            why = self._scan_supported()
            if why is not None:
                raise ValueError(
                    f'executor="scan" does not support {why}; use the '
                    'default unrolled executor'
                )
            self._setup_scan()
            return
        assert self.executor == "unrolled", f"unknown executor {self.executor!r}"
        depth = self.depth
        attn_types = tuple(self.attn_types) if self.attn_types else ("full",)
        type_per_layer = list(islice(cycle(attn_types), depth))
        attn_ids = list(islice(cycle(self.shared_attn_ids or range(depth)), depth))
        ff_ids = list(islice(cycle(self.shared_ff_ids or range(depth)), depth))

        shared_attn, shared_attn_type = {}, {}
        shared_ff = {}
        attn_layers, ff_layers = [], []
        for ind in range(depth):
            attn_type, attn_id, ff_id = type_per_layer[ind], attn_ids[ind], ff_ids[ind]
            if attn_id in shared_attn:
                if shared_attn_type[attn_id] != attn_type:
                    raise ValueError(
                        "attn_types do not match shared_attn_ids "
                        f"(ind = {ind}, attn_type = {attn_type!r}, "
                        f"reused_attn_type = {shared_attn_type[attn_id]!r})"
                    )
                attn = shared_attn[attn_id]
            else:
                attn = Attention(
                    dim=self.dim,
                    seq_len=self.seq_len,
                    heads=self.heads,
                    dim_head=self.dim_head,
                    causal=self.causal,
                    dropout=self.attn_dropout,
                    stable=self.stable,
                    static_mask=_build_static_mask(
                        attn_type, self.seq_len, self.image_fmap_size, ind
                    ),
                    attn_impl=self.attn_impl,
                    sp_mesh=self.sp_mesh,
                    decode_mesh=self.decode_mesh,
                    decode_heads_axis=self.decode_heads_axis,
                    decode_sparse_block=self.decode_sparse_block,
                    dtype=self.dtype,
                    name=f"attn_{attn_id}",
                )
                shared_attn[attn_id] = attn
                shared_attn_type[attn_id] = attn_type
            attn_layers.append(attn)

            if ff_id in shared_ff:
                ff = shared_ff[ff_id]
            else:
                ff = FeedForward(
                    dim=self.dim,
                    mult=self.ff_mult,
                    dropout=self.ff_dropout,
                    dtype=self.dtype,
                    name=f"ff_{ff_id}",
                )
                shared_ff[ff_id] = ff
            ff_layers.append(ff)

        self.attn_layers = attn_layers
        self.ff_layers = ff_layers
        self.attn_norms = [nn.LayerNorm(dtype=self.dtype) for _ in range(depth)]
        self.ff_norms = [nn.LayerNorm(dtype=self.dtype) for _ in range(depth)]
        if self.sandwich_norm:
            self.attn_norms_out = [nn.LayerNorm(dtype=self.dtype) for _ in range(depth)]
            self.ff_norms_out = [nn.LayerNorm(dtype=self.dtype) for _ in range(depth)]
        self.attn_scales = [
            self.param(
                f"attn_scale_{i}",
                lambda key, shape, i=i: jnp.full(shape, layerscale_init(i + 1)),
                (1, 1, self.dim),
            )
            for i in range(depth)
        ]
        self.ff_scales = [
            self.param(
                f"ff_scale_{i}",
                lambda key, shape, i=i: jnp.full(shape, layerscale_init(i + 1)),
                (1, 1, self.dim),
            )
            for i in range(depth)
        ]

        self.rotary_table = self._build_rotary_table()
        self.text_len = self._derived_text_len()

    def _derived_text_len(self) -> int:
        return (
            self.seq_len - self.image_fmap_size**2 + 1
            if self.image_fmap_size is not None
            else self.seq_len
        )

    def _build_rotary_table(self):
        if not self.rotary_emb:
            return None
        assert self.image_fmap_size is not None
        return build_dalle_rotary(
            self.seq_len - self.image_fmap_size**2 + 1,
            self.image_fmap_size,
            self.dim_head,
        )

    def _setup_scan(self):
        """Scan-executor setup: one stacked parameter collection."""
        depth, dim = self.depth, self.dim
        self.rotary_table = self._build_rotary_table()
        self.text_len = self._derived_text_len()

        # attn-type cycling: per-layer pattern masks served from a table of
        # UNIQUE masks plus a scanned per-layer index — cycling repeats the
        # same few [S, S] patterns (sparse is per-layer-seeded, so it stays
        # per-layer), and a depth-stacked copy of each would cost
        # depth/n_types more device memory for no information. Builders may
        # return [S+1, S+1] or block-padded sizes; crop uniformly to [S, S].
        self.scan_pattern_table, self.scan_pattern_idx = (
            self._build_pattern_table()
        )

        def stacked_scale_init(key, shape):
            del key  # deterministic depth-dependent init (layerscale_init)
            return jnp.stack(
                [jnp.full(shape[1:], layerscale_init(i + 1)) for i in range(shape[0])]
            )

        self.attn_scales_stacked = self.param(
            "attn_scale_stack", stacked_scale_init, (depth, 1, 1, dim)
        )
        self.ff_scales_stacked = self.param(
            "ff_scale_stack", stacked_scale_init, (depth, 1, 1, dim)
        )
        self.scan_stack = _ScanStack(
            depth=depth,
            remat=self.reversible,
            remat_policy=self.remat_policy,
            block_kwargs=self._scan_block_kwargs(),
        )

    def _build_pattern_table(self):
        """(unique-mask table [K, S, S], per-layer index [depth]) for the
        attn-type cycle, or (None, None) for uniform full attention.
        Pure config math (usable unbound — the pipeline executor rebuilds
        it outside this module's scope)."""
        attn_types = tuple(self.attn_types) if self.attn_types else ("full",)
        type_per_layer = list(islice(cycle(attn_types), self.depth))
        if not any(t != "full" for t in type_per_layer):
            return None, None
        S = self.seq_len
        table, index_of, idx = [], {}, []
        for ind, t in enumerate(type_per_layer):
            m = _build_static_mask(t, S, self.image_fmap_size, ind)
            if m is None:
                m = np.ones((S, S), dtype=bool)
            else:
                m = np.asarray(m)[:S, :S]
            key = m.tobytes()
            if key not in index_of:
                index_of[key] = len(table)
                table.append(m)
            idx.append(index_of[key])
        return (
            jnp.asarray(np.stack(table)),
            jnp.asarray(np.array(idx, np.int32)),
        )

    def _scan_block_kwargs(self) -> dict:
        """_ScanBlock constructor args for this config — pure config math,
        shared by the scan executor and `pipeline_trunk_apply` so the two
        can never drift."""
        return dict(
            dim=self.dim,
            seq_len=self.seq_len,
            causal=self.causal,
            heads=self.heads,
            dim_head=self.dim_head,
            ff_mult=self.ff_mult,
            attn_dropout=self.attn_dropout,
            ff_dropout=self.ff_dropout,
            stable=self.stable,
            sandwich_norm=self.sandwich_norm,
            shift_tokens=self.shift_tokens,
            text_len=self._derived_text_len(),
            image_fmap_size=self.image_fmap_size,
            attn_impl=self.attn_impl,
            sp_mesh=self.sp_mesh,
            decode_mesh=self.decode_mesh,
            decode_heads_axis=self.decode_heads_axis,
            decode_sparse_block=self.decode_sparse_block,
            dtype=self.dtype,
        )

    def _shift(self, h: jnp.ndarray, ring, pos, ring_end=None):
        """Token-shift h; in cached mode also maintain the ring buffer
        (see `shift_with_ring` — shared with the scan executor)."""
        assert self.image_fmap_size is not None
        return shift_with_ring(
            h, ring, pos, self.text_len, self.image_fmap_size,
            ring_end=ring_end,
        )

    def _half_attn(self, i, x, key_mask, layer_cache, deterministic=True):
        """Attention half-block f (norm → shift → attn → [sandwich] → scale),
        the composition the reference wraps as `f` in `ReversibleBlock`
        (`reversible.py:57-63`, built at `transformer.py:291-294`).
        Returns (residual_branch, new_attn_cache, new_shift_ring)."""
        cached = layer_cache is not None
        pos = layer_cache["attn"]["index"] if cached else None
        h = self.attn_norms[i](x)
        ring = None
        if self.shift_tokens:
            h, ring = self._shift(
                h, layer_cache.get("shift_attn") if cached else None, pos,
                ring_end=layer_cache.get("ring_end") if cached else None,
            )
        h, attn_cache = self.attn_layers[i](
            h,
            key_mask=key_mask,
            rotary=self.rotary_table,
            cache=layer_cache["attn"] if cached else None,
            deterministic=deterministic,
        )
        if self.sandwich_norm:
            h = self.attn_norms_out[i](h)
        return h * self.attn_scales[i].astype(h.dtype), attn_cache, ring

    def _half_ff(self, i, x, layer_cache, pos, deterministic=True):
        """Feed-forward half-block g (norm → shift → ff → [sandwich] → scale).
        `pos` is the pre-update decode position (for the streaming shift).
        Returns (residual_branch, new_shift_ring)."""
        cached = layer_cache is not None
        h = self.ff_norms[i](x)
        ring = None
        if self.shift_tokens:
            h, ring = self._shift(
                h, layer_cache.get("shift_ff") if cached else None, pos,
                ring_end=layer_cache.get("ring_end") if cached else None,
            )
        h = self.ff_layers[i](h, deterministic=deterministic)
        if self.sandwich_norm:
            h = self.ff_norms_out[i](h)
        return h * self.ff_scales[i].astype(h.dtype), ring

    def _rev_f(self, x: jnp.ndarray, i: int, deterministic: bool = True):
        return self._half_attn(i, x, None, None, deterministic)[0]

    def _rev_g(self, x: jnp.ndarray, i: int, deterministic: bool = True):
        return self._half_ff(i, x, None, None, deterministic)[0]

    def _revnet(self, x: jnp.ndarray, order: Tuple[int, ...]):
        """True reversible executor (`reversible.py:57-127` semantics).

        Forward runs the (f, g) couplings; the custom backward reconstructs
        activations block-by-block from the outputs, so nothing between
        layer boundaries is kept live — the JAX analogue of
        `_ReversibleFunction.backward` (`reversible.py:121-127`).
        """

        def fn(mdl, x1, x2):
            for i in order:
                x1 = x1 + mdl._rev_f(x2, i)
                x2 = x2 + mdl._rev_g(x1, i)
            return x1, x2

        def fwd(mdl, x1, x2):
            y1, y2 = fn(mdl, x1, x2)
            variables = {"params": mdl.variables["params"]}
            return (y1, y2), (y1, y2, variables)

        mdl_def = self.clone(parent=None)

        def bwd(residuals, tangents):
            y1, y2, variables = residuals
            dy1, dy2 = tangents

            def f_pure(v, h, i):
                return mdl_def.apply(v, h, i, method=Transformer._rev_f)

            def g_pure(v, h, i):
                return mdl_def.apply(v, h, i, method=Transformer._rev_g)

            params_t = jax.tree_util.tree_map(jnp.zeros_like, variables)
            for i in reversed(order):
                g_out, g_vjp = jax.vjp(lambda v, h: g_pure(v, h, i), variables, y1)
                x2 = y2 - g_out
                dv_g, dy1_add = g_vjp(dy2)
                dy1 = dy1 + dy1_add
                f_out, f_vjp = jax.vjp(lambda v, h: f_pure(v, h, i), variables, x2)
                x1 = y1 - f_out
                dv_f, dx2_add = f_vjp(dy1)
                dy2 = dy2 + dx2_add
                params_t = jax.tree_util.tree_map(
                    lambda a, b, c: a + b + c, params_t, dv_g, dv_f
                )
                y1, y2 = x1, x2
            return (params_t, dy1, dy2)

        if self.reversible_impl == "revnet_naive":
            # autodiff-through-forward variant: same function, plain VJP.
            # Exists so tests can check the custom backward against autodiff.
            y1, y2 = fn(self, x, x)
        else:
            rev = nn.custom_vjp(fn, forward_fn=fwd, backward_fn=bwd)
            y1, y2 = rev(self, x, x)
        # channel-duplication mean-out (`reversible.py:158,165`)
        return (y1 + y2) / 2

    def _layer(
        self,
        i: int,
        x: jnp.ndarray,
        key_mask,
        layer_cache,
        deterministic: bool,
    ):
        """One (attn, ff) residual pair; returns (x, updated layer cache)."""
        cached = layer_cache is not None
        pos = layer_cache["attn"]["index"] if cached else None

        h, attn_cache, ring_attn = self._half_attn(
            i, x, key_mask, layer_cache, deterministic
        )
        x = x + h
        h, ring_ff = self._half_ff(i, x, layer_cache, pos, deterministic)
        x = x + h

        if not cached:
            return x, None
        new_cache = {"attn": attn_cache}
        if self.shift_tokens:
            new_cache["shift_attn"] = ring_attn
            new_cache["shift_ff"] = ring_ff
        return x, new_cache

    def __call__(
        self,
        x: jnp.ndarray,
        key_mask: Optional[jnp.ndarray] = None,
        reverse_model: bool = False,
        cache: Optional[dict] = None,
        deterministic: bool = True,
    ):
        if self.executor == "scan":
            return self.scan_stack(
                x,
                self.attn_scales_stacked,
                self.ff_scales_stacked,
                self.scan_pattern_idx,
                self.scan_pattern_table,
                key_mask,
                self.rotary_table,
                cache=cache,
                reverse=reverse_model,
                deterministic=deterministic,
            )
        order = range(self.depth - 1, -1, -1) if reverse_model else range(self.depth)
        if self.reversible and self.reversible_impl != "remat":
            if cache is not None:
                # cached decode of the SAME two-stream function the revnet
                # trains: (x1, x2) streams advance through cached halves.
                x1 = x2 = x
                new_cache = {}
                for i in order:
                    lc = cache[f"layer_{i}"]
                    pos = lc["attn"]["index"]
                    h, attn_cache, ring_a = self._half_attn(
                        i, x2, key_mask, lc, deterministic
                    )
                    x1 = x1 + h
                    h, ring_f = self._half_ff(i, x1, lc, pos, deterministic)
                    x2 = x2 + h
                    layer_new = {"attn": attn_cache}
                    if self.shift_tokens:
                        layer_new["shift_attn"] = ring_a
                        layer_new["shift_ff"] = ring_f
                    new_cache[f"layer_{i}"] = layer_new
                return (x1 + x2) / 2, new_cache
            assert key_mask is None, "revnet executor has no key-mask path"
            assert deterministic or (self.attn_dropout == 0 and self.ff_dropout == 0), (
                "revnet executor requires deterministic execution (no dropout); "
                "use reversible_impl='remat' for dropout training"
            )
            return self._revnet(x, tuple(order))
        new_cache = {} if cache is not None else None
        for i in order:
            if self.reversible and cache is None:
                # activation rematerialization: recompute the layer in the
                # backward pass instead of saving activations — the memory
                # behavior the reference's ReversibleSequence buys
                # (`reversible.py:57-127`), via flax's lifted remat.
                def layer_fn(mdl, y, i=i):
                    return mdl._layer(i, y, key_mask, None, deterministic)[0]

                x = nn.remat(
                    layer_fn, policy=resolve_remat_policy(self.remat_policy)
                )(self, x)
            else:
                x, layer_cache = self._layer(
                    i, x, key_mask, cache[f"layer_{i}"] if cache else None, deterministic
                )
                if layer_cache:
                    new_cache[f"layer_{i}"] = layer_cache
        if cache is not None:
            return x, new_cache
        return x

    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32) -> dict:
        """Fixed-shape decode cache pytree (KV + token-shift rings)."""
        return make_decode_cache(
            depth=self.depth,
            batch=batch,
            max_len=max_len,
            heads=self.heads,
            dim_head=self.dim_head,
            dim=self.dim,
            image_fmap_size=self.image_fmap_size,
            shift_tokens=self.shift_tokens,
            dtype=dtype,
            executor=self.executor,
        )


def scan_params_to_unrolled(tparams: dict, depth: int) -> dict:
    """Convert a scan-executor Transformer param subtree to the unrolled
    layout (e.g. to run the cached decode path on a scan-trained model).

    `tparams` is the subtree under ".../transformer" of a scan-executor
    model; returns the equivalent unrolled-executor subtree.
    """
    layers = tparams["scan_stack"]["layers"]

    def slice_i(tree, i):
        return jax.tree_util.tree_map(lambda a: a[i], tree)

    out = {}
    for i in range(depth):
        out[f"attn_{i}"] = slice_i(layers["attn"], i)
        out[f"ff_{i}"] = slice_i(layers["ff"], i)
        out[f"attn_norms_{i}"] = slice_i(layers["norm_attn"], i)
        out[f"ff_norms_{i}"] = slice_i(layers["norm_ff"], i)
        if "norm_attn_out" in layers:
            out[f"attn_norms_out_{i}"] = slice_i(layers["norm_attn_out"], i)
            out[f"ff_norms_out_{i}"] = slice_i(layers["norm_ff_out"], i)
        out[f"attn_scale_{i}"] = tparams["attn_scale_stack"][i]
        out[f"ff_scale_{i}"] = tparams["ff_scale_stack"][i]
    return out


def unrolled_params_to_scan(tparams: dict, depth: int) -> dict:
    """Inverse of `scan_params_to_unrolled` (uniform-stack configs only)."""

    def stack(fmt):
        trees = [tparams[fmt.format(i)] for i in range(depth)]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)

    layers = {
        "attn": stack("attn_{}"),
        "ff": stack("ff_{}"),
        "norm_attn": stack("attn_norms_{}"),
        "norm_ff": stack("ff_norms_{}"),
    }
    if "attn_norms_out_0" in tparams:
        layers["norm_attn_out"] = stack("attn_norms_out_{}")
        layers["norm_ff_out"] = stack("ff_norms_out_{}")
    return {
        "scan_stack": {"layers": layers},
        "attn_scale_stack": stack("attn_scale_{}"),
        "ff_scale_stack": stack("ff_scale_{}"),
    }


def make_pipeline_trunk(transformer: "Transformer", mesh, n_micro: int):
    """Build `fn(tparams, x, key_mask=None)` running this Transformer
    config's trunk pipeline-parallel over a 'pp' mesh
    (parallel/gpipe.py GPipe schedule).

    `tparams` is the Transformer's own parameter tree in the scan layout
    ([depth, ...] leaves — the trained/checkpointed layout; convert
    unrolled checkpoints with `unrolled_params_to_scan`). Numerically
    equal to `transformer.apply` for the uncached deterministic case —
    including the attn-type cycle (per-layer pattern-mask indices ride
    with each stage's layer slice). Restrictions mirror the scan
    executor's (`_scan_supported`) plus: no reverse pass, no dropout
    (deterministic inference/eval or an externally rematerialized
    training forward).

    The block module is constructed HERE, at make time — flax intercepts
    module construction inside a parent module's scope, so building the
    returned closure outside any `apply` lets it serve as a
    `DALLE(..., trunk_fn=...)` override inside the model's own apply.

    The reference has no pipeline parallelism to cite; this is the
    TPU-native depth-scaling axis on top of its reversibility story
    (`/root/reference/dalle_pytorch/reversible.py`).
    """
    from dalle_pytorch_tpu.parallel.gpipe import gpipe_apply

    assert transformer.executor == "scan", "pipeline runs the scan layout"
    reason = transformer._scan_supported()
    assert reason is None, f"unsupported config for pipelining: {reason}"

    block = _ScanBlock(
        deterministic=True, **transformer._scan_block_kwargs()
    )
    rotary = transformer._build_rotary_table()
    # attn-type cycling: the per-layer index into the unique-mask table is
    # depth-leading, so it rides WITH each stage's layer slice; the small
    # table itself is closed over (replicated), same as the scan executor
    pattern_table, pattern_idx = transformer._build_pattern_table()

    def run(tparams: dict, x: jnp.ndarray,
            key_mask: Optional[jnp.ndarray] = None):
        pp_params = {
            "block": tparams["scan_stack"]["layers"],
            "s_attn": tparams["attn_scale_stack"],
            "s_ff": tparams["ff_scale_stack"],
        }
        if pattern_idx is not None:
            pp_params["pidx"] = pattern_idx

        def call_block(lp, h, km):
            pidx = lp["pidx"] if pattern_idx is not None else None
            y, _ = block.apply(
                {"params": lp["block"]}, h, lp["s_attn"], lp["s_ff"],
                pidx, pattern_table, None, km, rotary,
            )
            return y

        if transformer.reversible:
            # honor the config's activation-memory setting: per-layer
            # rematerialization (same policy the scan executor wraps via
            # nn.remat) — values unchanged, backward recomputes
            call_block = jax.checkpoint(
                call_block,
                policy=resolve_remat_policy(transformer.remat_policy),
                prevent_cse=False,
            )

        if key_mask is None:
            return gpipe_apply(
                mesh, pp_params, lambda lp, h: call_block(lp, h, None),
                x, n_micro,
            )

        # key_mask is per-example, so it must ride the microbatch
        # schedule (each stage masks the microbatch it is processing)
        return gpipe_apply(
            mesh, pp_params, call_block, x, n_micro, aux=key_mask
        )

    return run


def pipeline_trunk_apply(
    transformer: "Transformer",
    tparams: dict,
    mesh,
    x: jnp.ndarray,
    n_micro: int,
    key_mask: Optional[jnp.ndarray] = None,
):
    """One-shot convenience over `make_pipeline_trunk` (standalone use,
    outside any flax module scope)."""
    return make_pipeline_trunk(transformer, mesh, n_micro)(
        tparams, x, key_mask
    )


def make_decode_cache(
    depth: int,
    batch: int,
    max_len: int,
    heads: int,
    dim_head: int,
    dim: int,
    image_fmap_size: Optional[int] = None,
    shift_tokens: bool = False,
    dtype=jnp.float32,
    executor: str = "unrolled",
    per_row: bool = False,
    kv_dtype=None,
) -> dict:
    """Decode cache pytree for a Transformer of this geometry.

    Standalone (not a module method) so model owners like DALLE can build
    it from config without binding parameters. The unrolled executor
    takes per-layer dicts ("layer_{i}"); the scan executor takes the same
    leaves depth-stacked along axis 0 (they ride the layer scan as
    scanned inputs/outputs).

    `per_row=True` sizes the `index` leaves [batch] (scan: [depth, batch])
    instead of scalar, putting each batch row at its OWN sequence position —
    the continuous-batching slot cache, where rows are admitted at token
    boundaries rather than in lockstep (`models/dalle.py:init_slot_state`).

    `kv_dtype="int8"` stores K/V quantized with symmetric per-(position,
    head) fp32 scales in sibling `k_scale`/`v_scale` leaves ([B, H, L];
    scan: [depth, B, H, L]) — dequantized inside the attention read
    (`ops/pallas_decode.py`), never materialized back to fp. Everything
    else (shift rings, index) stays in `dtype`.
    """
    idx_shape = (batch,) if per_row else ()
    kv_dt, scaled = _kv_store_dtype(dtype, kv_dtype)
    if executor == "scan":
        attn = {
            "k": jnp.zeros((depth, batch, heads, max_len, dim_head), kv_dt),
            "v": jnp.zeros((depth, batch, heads, max_len, dim_head), kv_dt),
            "index": jnp.zeros((depth,) + idx_shape, jnp.int32),
        }
        if scaled:
            attn["k_scale"] = jnp.zeros(
                (depth, batch, heads, max_len), jnp.float32
            )
            attn["v_scale"] = jnp.zeros(
                (depth, batch, heads, max_len), jnp.float32
            )
        cache = {"attn": attn}
        if shift_tokens:
            assert image_fmap_size is not None
            cache["shift_attn"] = jnp.zeros(
                (depth, batch, image_fmap_size, dim), dtype
            )
            cache["shift_ff"] = jnp.zeros(
                (depth, batch, image_fmap_size, dim), dtype
            )
        return cache
    cache = {}
    for i in range(depth):
        attn = {
            "k": jnp.zeros((batch, heads, max_len, dim_head), kv_dt),
            "v": jnp.zeros((batch, heads, max_len, dim_head), kv_dt),
            "index": jnp.zeros(idx_shape, jnp.int32),
        }
        if scaled:
            attn["k_scale"] = jnp.zeros((batch, heads, max_len), jnp.float32)
            attn["v_scale"] = jnp.zeros((batch, heads, max_len), jnp.float32)
        layer = {"attn": attn}
        if shift_tokens:
            assert image_fmap_size is not None
            layer["shift_attn"] = jnp.zeros((batch, image_fmap_size, dim), dtype)
            layer["shift_ff"] = jnp.zeros((batch, image_fmap_size, dim), dtype)
        cache[f"layer_{i}"] = layer
    return cache


def _kv_store_dtype(dtype, kv_dtype):
    """(storage dtype, has-scale-leaves) for a KV cache request.

    `kv_dtype=None` keeps the historical behavior (K/V stored at the
    cache `dtype`, no scale leaves) so every default tree stays
    byte-identical to pre-quantization builds.
    """
    if kv_dtype is None:
        return dtype, False
    assert str(kv_dtype) == "int8", f"unsupported kv_dtype: {kv_dtype!r}"
    return jnp.int8, True


def make_paged_decode_cache(
    depth: int,
    batch: int,
    n_pages: int,
    page_size: int,
    heads: int,
    dim_head: int,
    dim: int,
    image_fmap_size: Optional[int] = None,
    shift_tokens: bool = False,
    dtype=jnp.float32,
    executor: str = "unrolled",
    kv_dtype=None,
) -> dict:
    """Block-paged decode cache: K/V live in a physical page pool
    [n_pages, heads, page_size, dim_head] shared by all `batch` rows
    instead of per-row [max_len] lanes; a host-side page table (passed as
    a traced argument per dispatch, NOT stored here) maps each row's
    logical blocks to pages. Same tree keys as `make_decode_cache` so the
    scatter/gather model ops tree-map across both layouts; shift rings and
    the per-row `index` stay row-indexed (they are small — paging them
    would buy nothing).

    `kv_dtype="int8"` pairs the int8 pool with fp32 `k_scale`/`v_scale`
    pools [n_pages, heads, page_size] (scan: +depth) addressed by the
    SAME page table.
    """
    kv_dt, scaled = _kv_store_dtype(dtype, kv_dtype)
    if executor == "scan":
        attn = {
            "k": jnp.zeros(
                (depth, n_pages, heads, page_size, dim_head), kv_dt
            ),
            "v": jnp.zeros(
                (depth, n_pages, heads, page_size, dim_head), kv_dt
            ),
            "index": jnp.zeros((depth, batch), jnp.int32),
        }
        if scaled:
            attn["k_scale"] = jnp.zeros(
                (depth, n_pages, heads, page_size), jnp.float32
            )
            attn["v_scale"] = jnp.zeros(
                (depth, n_pages, heads, page_size), jnp.float32
            )
        cache = {"attn": attn}
        if shift_tokens:
            assert image_fmap_size is not None
            cache["shift_attn"] = jnp.zeros(
                (depth, batch, image_fmap_size, dim), dtype
            )
            cache["shift_ff"] = jnp.zeros(
                (depth, batch, image_fmap_size, dim), dtype
            )
        return cache
    cache = {}
    for i in range(depth):
        attn = {
            "k": jnp.zeros((n_pages, heads, page_size, dim_head), kv_dt),
            "v": jnp.zeros((n_pages, heads, page_size, dim_head), kv_dt),
            "index": jnp.zeros((batch,), jnp.int32),
        }
        if scaled:
            attn["k_scale"] = jnp.zeros(
                (n_pages, heads, page_size), jnp.float32
            )
            attn["v_scale"] = jnp.zeros(
                (n_pages, heads, page_size), jnp.float32
            )
        layer = {"attn": attn}
        if shift_tokens:
            assert image_fmap_size is not None
            layer["shift_attn"] = jnp.zeros((batch, image_fmap_size, dim), dtype)
            layer["shift_ff"] = jnp.zeros((batch, image_fmap_size, dim), dtype)
        cache[f"layer_{i}"] = layer
    return cache


def set_decode_cache_index(cache: dict, pos: jnp.ndarray, executor: str) -> dict:
    """Overwrite every layer's cache `index` with `pos`.

    Layers always advance in lockstep, so the per-layer indices are copies
    of one logical position; the continuous-batching chunk loop keeps that
    position as explicit per-slot state (`img_pos`) and stamps it into the
    cache before each step — which is also how retired/inactive slots are
    kept frozen (their position simply never advances).
    """
    if executor == "scan":
        depth = cache["attn"]["index"].shape[0]
        idx = jnp.broadcast_to(pos, (depth,) + pos.shape).astype(jnp.int32)
        return {**cache, "attn": {**cache["attn"], "index": idx}}
    out = {}
    for name, layer in cache.items():
        out[name] = {
            **layer,
            "attn": {**layer["attn"], "index": pos.astype(jnp.int32)},
        }
    return out
