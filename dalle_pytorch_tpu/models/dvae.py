"""Discrete VAE: conv encoder -> gumbel-softmax codebook -> deconv decoder.

TPU-native re-design of the reference `DiscreteVAE`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:89-270`):

  * NHWC layout throughout (TPU conv-native), bf16-friendly;
  * gumbel-softmax sampling with optional hard straight-through and ReinMax
    (reference `:236-246`), RNG via explicit flax rng collection "gumbel";
  * MSE / smooth-L1 reconstruction loss + KL(q || uniform) with batch-mean
    reduction (reference `:254-265`);
  * per-channel input normalization (reference `:187-195`);
  * `get_codebook_indices` = argmax over encoder logits (reference
    `:197-202`), `decode` = codebook lookup -> decoder CNN (reference
    `:204-214`).

Architecture parity: `num_layers` stride-2 4x4 convs (ReLU) in the encoder
with `num_resnet_blocks` residual blocks appended, mirrored decoder with
resblocks prepended behind a 1x1 codebook->hidden projection, final 1x1
heads (reference `:135-165`).
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from dalle_pytorch_tpu.ops.gumbel import gumbel_softmax


def smooth_l1_loss(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    diff = jnp.abs(pred - target)
    return jnp.mean(jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5))


def mse_loss(pred: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((pred - target) ** 2)


class ResBlock(nn.Module):
    chan: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        h = nn.Conv(self.chan, (3, 3), padding=1, dtype=self.dtype)(x)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (3, 3), padding=1, dtype=self.dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(self.chan, (1, 1), dtype=self.dtype)(h)
        return h + x


class DiscreteVAE(nn.Module):
    image_size: int = 256
    num_tokens: int = 512
    codebook_dim: int = 512
    num_layers: int = 3
    num_resnet_blocks: int = 0
    hidden_dim: int = 64
    channels: int = 3
    smooth_l1_loss: bool = False
    temperature: float = 0.9
    straight_through: bool = False
    reinmax: bool = False
    kl_div_loss_weight: float = 0.0
    normalization: Optional[Tuple[Sequence[float], Sequence[float]]] = (
        (0.5, 0.5, 0.5),
        (0.5, 0.5, 0.5),
    )
    dtype: Any = jnp.float32

    @property
    def fmap_size(self) -> int:
        return self.image_size // (2**self.num_layers)

    def setup(self):
        assert math.log2(self.image_size).is_integer(), "image size must be a power of 2"
        assert self.num_layers >= 1, "num_layers must be >= 1"
        has_res = self.num_resnet_blocks > 0

        self.codebook = nn.Embed(self.num_tokens, self.codebook_dim, dtype=self.dtype)

        enc = []
        for _ in range(self.num_layers):
            enc.append(
                nn.Conv(self.hidden_dim, (4, 4), strides=2, padding=1, dtype=self.dtype)
            )
        self.enc_convs = enc
        self.enc_res = [
            ResBlock(self.hidden_dim, dtype=self.dtype)
            for _ in range(self.num_resnet_blocks)
        ]
        self.enc_head = nn.Conv(self.num_tokens, (1, 1), dtype=self.dtype)

        self.dec_proj = (
            nn.Conv(self.hidden_dim, (1, 1), dtype=self.dtype) if has_res else None
        )
        self.dec_res = [
            ResBlock(self.hidden_dim, dtype=self.dtype)
            for _ in range(self.num_resnet_blocks)
        ]
        dec = []
        for _ in range(self.num_layers):
            dec.append(
                nn.ConvTranspose(
                    self.hidden_dim, (4, 4), strides=(2, 2), padding="SAME", dtype=self.dtype
                )
            )
        self.dec_convs = dec
        self.dec_head = nn.Conv(self.channels, (1, 1), dtype=self.dtype)

    def norm(self, images: jnp.ndarray) -> jnp.ndarray:
        if self.normalization is None:
            return images
        means = jnp.asarray(self.normalization[0][: self.channels], images.dtype)
        stds = jnp.asarray(self.normalization[1][: self.channels], images.dtype)
        return (images - means) / stds

    def encode_logits(self, img: jnp.ndarray) -> jnp.ndarray:
        """img: [B, H, W, C] -> token logits [B, h, w, num_tokens]."""
        assert img.shape[1] == self.image_size and img.shape[2] == self.image_size, (
            f"input must have the correct image size {self.image_size}, "
            f"got {img.shape[1]}x{img.shape[2]}"
        )
        x = self.norm(img)
        for conv in self.enc_convs:
            x = nn.relu(conv(x))
        for blk in self.enc_res:
            x = blk(x)
        return self.enc_head(x)

    def decode_embeds(self, emb: jnp.ndarray) -> jnp.ndarray:
        """emb: [B, h, w, codebook_dim] -> image [B, H, W, C]."""
        x = emb
        if self.dec_proj is not None:
            x = self.dec_proj(x)
        for blk in self.dec_res:
            x = blk(x)
        for conv in self.dec_convs:
            x = nn.relu(conv(x))
        return self.dec_head(x)

    def get_codebook_indices(self, images: jnp.ndarray) -> jnp.ndarray:
        """[B, H, W, C] -> [B, h*w] int32 codebook indices (frozen encode)."""
        logits = self.encode_logits(images)
        b = logits.shape[0]
        return jnp.argmax(logits, axis=-1).reshape(b, -1).astype(jnp.int32)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        """[B, n] codebook indices -> [B, H, W, C] image."""
        emb = self.codebook(img_seq)
        b, n, d = emb.shape
        hw = int(math.isqrt(n))
        return self.decode_embeds(emb.reshape(b, hw, hw, d))

    def __call__(
        self,
        img: jnp.ndarray,
        return_loss: bool = False,
        return_recons: bool = False,
        return_logits: bool = False,
        temp: Optional[float] = None,
    ):
        assert img.shape[1] == self.image_size and img.shape[2] == self.image_size, (
            f"input must have the correct image size {self.image_size}"
        )
        logits = self.encode_logits(img)
        if return_logits:
            return logits

        temp = self.temperature if temp is None else temp
        rng = self.make_rng("gumbel")
        one_hot = gumbel_softmax(
            rng,
            logits,
            tau=temp,
            hard=self.straight_through,
            reinmax=self.straight_through and self.reinmax,
            axis=-1,
        )
        sampled = jnp.einsum(
            "bhwn,nd->bhwd", one_hot, self.codebook.embedding.astype(one_hot.dtype)
        )
        out = self.decode_embeds(sampled)

        if not return_loss:
            return out

        img_n = self.norm(img)
        loss_fn = smooth_l1_loss if self.smooth_l1_loss else mse_loss
        recon_loss = loss_fn(img_n.astype(jnp.float32), out.astype(jnp.float32))

        # KL(q || uniform), summed over positions+tokens, mean over batch
        b, h, w, n = logits.shape
        log_qy = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        log_uniform = -jnp.log(jnp.asarray(float(self.num_tokens)))
        kl_div = jnp.sum(jnp.exp(log_qy) * (log_qy - log_uniform)) / b

        loss = recon_loss + kl_div * self.kl_div_loss_weight
        if not return_recons:
            return loss
        return loss, out
