"""DALL-E: joint text+image autoregressive token transformer.

TPU-native re-design of the reference `DALLE`
(`/root/reference/dalle_pytorch/dalle_pytorch.py:354-707`). Functional
differences from the reference's torch design, on purpose:

  * the frozen VAE is NOT owned by this module. JAX separates parameters
    from code, so the train/generate pipelines compose
    `vae.get_codebook_indices` / `vae.decode` (under `stop_gradient`) with
    this module explicitly — the better TPU pattern is precomputing image
    tokens offline anyway. The constructor takes the VAE's geometry
    (`num_image_tokens`, `image_fmap_size`) instead of the model.
  * generation is a `lax.scan` over positions (see `generate_images`), not
    a Python loop.

Semantics preserved (with reference lines):
  * per-position unique padding tokens for text (`:389,606-609`): token id 0
    at text position p becomes id num_text_tokens_base + p; the embedding
    table is extended by text_seq_len ids;
  * <bos> = id 0 prepended (`:612`), sequence truncated to
    text_seq_len + image_seq_len (`:644-646`);
  * text/image logits range masks and the fork's inverse-rotated mask
    (`:450-464,662-675`);
  * classifier-free-guidance null conditioning: zero out text ids with
    probability null_cond_prob (`:600-604`), two-forward blend at sampling
    (`:575-585`);
  * "stable" tricks: 0.1x + 0.9 stop_grad(x) input anchor (`:648-650`) and
    DivideMax output norm (`:657-658`);
  * split text/image cross-entropy with configurable coefficients
    (`:693-706`), including the fork's inverse (image->text) objective and
    its 3-token sequence-accuracy metric (`:697-699`). For the inverse mode
    the reference splits the loss at `text_seq_len`, which equals the
    image/text boundary only when image_seq_len == text_seq_len (the fork's
    experimental configs); we split at the actual boundary `image_seq_len`.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from dalle_pytorch_tpu.models.transformer import Transformer, DivideMax, make_decode_cache
from dalle_pytorch_tpu.ops.sampling import top_k_filter, gumbel_sample

NEG_MASK_VALUE = -float(np.finfo(np.float32).max)


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Mean softmax cross-entropy with integer labels, fp32 accumulation."""
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


class AxialPositionalEmbedding(nn.Module):
    """Row+col additive positional embedding over a 2-D grid, flattened.

    Equivalent of the reference's AxialPositionalEmbedding dependency
    (`dalle_pytorch.py:392`).
    """

    dim: int
    row: int
    col: int

    @nn.compact
    def __call__(self, n: int) -> jnp.ndarray:
        rows = self.param("rows", nn.initializers.normal(1.0), (self.row, 1, self.dim))
        cols = self.param("cols", nn.initializers.normal(1.0), (1, self.col, self.dim))
        pos = (rows + cols).reshape(self.row * self.col, self.dim)
        return pos[:n]


class DALLE(nn.Module):
    dim: int
    depth: int
    num_image_tokens: int
    image_fmap_size: int
    num_text_tokens: int = 10000  # base count, before unique-pad extension
    text_seq_len: int = 256
    heads: int = 8
    dim_head: int = 64
    reversible: bool = False
    reversible_impl: str = "remat"
    remat_policy: Optional[str] = None  # jax.checkpoint_policies name
    attn_dropout: float = 0.0
    ff_dropout: float = 0.0
    attn_types: Optional[Sequence[str]] = None
    loss_img_weight: float = 7.0  # upstream knob; default img_loss_coeff
    stable: bool = False
    sandwich_norm: bool = False
    shift_tokens: bool = True
    rotary_emb: bool = True
    shared_attn_ids: Optional[Sequence[int]] = None
    shared_ff_ids: Optional[Sequence[int]] = None
    share_input_output_emb: bool = False
    # fork's multi-objective coefficients (`config/config.yaml:21-24`).
    # img_loss_coeff=None defaults to loss_img_weight, making the upstream
    # knob `(loss_text + w*loss_img)/(w+1)` (`dalle_pytorch.py:702-706`) work.
    text_loss_coeff: float = 1.0
    img_loss_coeff: Optional[float] = None
    text_loss_coeff_inv: float = 7.0
    img_loss_coeff_inv: float = 1.0
    attn_impl: str = "auto"  # "dense" | "flash" | "ring" | "auto"
    sp_mesh: Any = None  # Mesh with "sp" axis for attn_impl="ring"
    # serving mesh handed down to the cached flash-decode dispatch
    # (models/attention.py): set by the sharded continuous engine so the
    # Pallas kernel splits per head over `decode_heads_axis` — the same
    # axis the engine's KV-cache shardings use
    decode_mesh: Any = None
    decode_heads_axis: str = "tp"
    # decode-time policy-sparse KV tile width (None = DECODE_SPARSE_BLOCK,
    # models/attention.py): the serving engine clones the model with it
    # under --decode_sparsity=policy so kernel tile boundaries and the
    # host-derived block bitmaps agree; the bitmaps themselves ride the
    # cache pytree as traced data (policy flips never recompile)
    decode_sparse_block: Optional[int] = None
    # KV-cache storage dtype for the serving/decode caches: None keeps
    # K/V at `dtype` (bit-identical legacy behavior); "int8" stores
    # quantized pages with per-(position, head) fp32 scales, dequantized
    # inside the decode kernels (ops/pallas_decode.py)
    kv_dtype: Any = None
    # layer executor: "unrolled" | "scan" (one compiled layer body,
    # ~depth× smaller program; see models/transformer.py docstring)
    executor: str = "unrolled"
    # vocab-chunked CE for the forward objective: avoids materializing
    # [B, N, total_tokens] logits (ops/losses.py)
    fused_ce: bool = False
    dtype: Any = jnp.float32

    @property
    def total_text_tokens(self) -> int:
        return self.num_text_tokens + self.text_seq_len

    @property
    def image_seq_len(self) -> int:
        return self.image_fmap_size**2

    @property
    def total_seq_len(self) -> int:
        return self.text_seq_len + self.image_seq_len

    @property
    def total_tokens(self) -> int:
        return self.total_text_tokens + self.num_image_tokens

    def transformer_kwargs(self) -> dict:
        """Trunk Transformer constructor args — pure config math, usable
        on an UNBOUND DALLE too (e.g. to rebuild the trunk module for
        `pipeline_trunk_apply` outside this module's apply)."""
        return dict(
            dim=self.dim,
            depth=self.depth,
            seq_len=self.total_seq_len,
            causal=True,
            heads=self.heads,
            dim_head=self.dim_head,
            attn_dropout=self.attn_dropout,
            ff_dropout=self.ff_dropout,
            attn_types=self.attn_types,
            image_fmap_size=self.image_fmap_size,
            stable=self.stable,
            sandwich_norm=self.sandwich_norm,
            shift_tokens=self.shift_tokens,
            rotary_emb=self.rotary_emb,
            shared_attn_ids=self.shared_attn_ids,
            shared_ff_ids=self.shared_ff_ids,
            reversible=self.reversible,
            reversible_impl=self.reversible_impl,
            remat_policy=self.remat_policy,
            attn_impl=self.attn_impl,
            sp_mesh=self.sp_mesh,
            decode_mesh=self.decode_mesh,
            decode_heads_axis=self.decode_heads_axis,
            decode_sparse_block=self.decode_sparse_block,
            executor=self.executor,
            dtype=self.dtype,
        )

    def setup(self):
        self.text_emb = nn.Embed(self.total_text_tokens, self.dim, dtype=self.dtype)
        self.image_emb = nn.Embed(self.num_image_tokens, self.dim, dtype=self.dtype)

        if not self.rotary_emb:
            self.text_pos_emb = nn.Embed(self.text_seq_len + 1, self.dim, dtype=self.dtype)
            self.image_pos_emb = AxialPositionalEmbedding(
                self.dim, self.image_fmap_size, self.image_fmap_size
            )

        self.transformer = Transformer(**self.transformer_kwargs())

        if self.stable:
            self.norm_by_max = DivideMax(axis=-1)

        self.logits_norm = nn.LayerNorm(dtype=self.dtype)
        if not self.share_input_output_emb:
            self.logits_dense = nn.Dense(self.total_tokens, dtype=self.dtype)
        else:
            self.logits_bias = self.param(
                "logits_bias", nn.initializers.zeros, (self.total_tokens,)
            )

        # logits-range masks (reference `:450-464`) are computed on the fly
        # from iotas in _logits_blocked — a [total_seq, total_tokens] bool
        # constant would bake ~20MB into the executable for nothing.

    def _logits_blocked(self, seq_len: int, inverse: bool) -> jnp.ndarray:
        """[seq_len, total_tokens] bool, True = BLOCKED (reference `:450-464`).

        Text positions may only emit text-vocab ids and image positions
        image-vocab ids; `inverse` rotates the rows by text_seq_len since
        the image occupies the front of the sequence (`:463`).
        """
        rows = jnp.arange(seq_len)
        if inverse:
            rows = (rows + self.text_seq_len) % self.total_seq_len
        vocab = jnp.arange(self.total_tokens)[None, :]
        is_text_row = (rows < self.text_seq_len)[:, None]
        is_text_vocab = vocab < self.total_text_tokens
        return is_text_row != is_text_vocab

    def to_logits(self, out: jnp.ndarray) -> jnp.ndarray:
        if self.stable:
            out = self.norm_by_max(out)
        out = self.logits_norm(out)
        if self.share_input_output_emb:
            kernel, bias = self._logits_kernel()
            return out @ kernel.astype(out.dtype) + bias.astype(out.dtype)
        return self.logits_dense(out)

    def embed_text(self, text: jnp.ndarray, null_cond_prob: float = 0.0):
        """Unique-pad remap + <bos>; returns (padded_ids [B, T+1], embeddings)."""
        b = text.shape[0]
        assert text.shape[-1] == self.text_seq_len, (
            f"text length {text.shape[-1]} != text_seq_len {self.text_seq_len}"
        )
        if null_cond_prob > 0:
            rng = self.make_rng("null_cond")
            null = jax.random.uniform(rng, (b, 1)) < null_cond_prob
            text = jnp.where(null, 0, text)

        text_range = jnp.arange(self.text_seq_len) + (
            self.total_text_tokens - self.text_seq_len
        )
        text = jnp.where(text == 0, text_range, text)
        text = jnp.pad(text, ((0, 0), (1, 0)))  # <bos> = 0

        tokens = self.text_emb(text)
        if not self.rotary_emb:
            tokens = tokens + self.text_pos_emb(jnp.arange(text.shape[1]))
        return text, tokens

    def _fused_forward_loss(self, out, text, image, seq_len):
        """Forward-mode split CE via the vocab-chunked kernel — identical
        numerics to the dense path (tests/test_dalle.py parity), ~20 GB
        less HBM traffic per flagship step (BASELINE.md)."""
        from dalle_pytorch_tpu.ops.losses import chunked_masked_ce, split_weighted_mean

        h, kernel, bias, offsetted_image = self._fused_head(out, image)
        labels = jnp.concatenate([text[:, 1:], offsetted_image], axis=1)
        split = self.text_seq_len
        row_is_text = jnp.arange(seq_len) < self.text_seq_len
        per_pos = chunked_masked_ce(
            h, kernel, bias, labels,
            row_is_text=row_is_text,
            num_text_vocab=self.total_text_tokens,
        )
        ct = self.text_loss_coeff
        ci = self.loss_img_weight if self.img_loss_coeff is None else self.img_loss_coeff
        loss = split_weighted_mean(per_pos, split, ct, ci)
        return loss, None

    def _logits_kernel(self):
        """(kernel [D, V], bias [V] or None) of the logits head, shared by
        both fused-CE paths."""
        if self.share_input_output_emb:
            kernel = jnp.concatenate(
                [self.text_emb.embedding, self.image_emb.embedding], axis=0
            ).T
            return kernel, self.logits_bias
        p = self.variables["params"]["logits_dense"]
        return p["kernel"], p.get("bias")

    def _fused_head(self, out, image):
        """Shared fused-CE prologue: normalized head input + logits kernel
        + vocab-offset image labels. Keeping it in one place keeps the two
        objectives' numerics in lockstep with the dense path."""
        assert image is not None, "when training, image must be supplied"
        if self.stable:
            out = self.norm_by_max(out)
        h = self.logits_norm(out)
        kernel, bias = self._logits_kernel()
        return h, kernel, bias, image + self.total_text_tokens

    def _fused_inverse_loss(self, out, text, image, seq_len):
        """Inverse-mode (image->text) split CE via the vocab-chunked kernel.

        Numerics match the dense inverse path (tests/test_dalle.py parity):
        image-first row layout, the fork's drop-last-image-position quirk
        (`:686-687`), inverse loss coefficients, and the 3-token sequence
        accuracy — the argmax needs real logits, but only for THREE text
        positions, so a tiny [B, 3, V] dense block replaces the full
        [B, N, V] materialization."""
        from dalle_pytorch_tpu.ops.losses import chunked_masked_ce, split_weighted_mean

        h, kernel, bias, offsetted_image = self._fused_head(out, image)
        labels = jnp.concatenate([offsetted_image[:, 1:], text], axis=1)
        split = self.image_seq_len
        # image-first layout: rows >= image_seq_len are text rows
        row_is_text = jnp.arange(seq_len) >= split
        per_pos = chunked_masked_ce(
            h, kernel, bias, labels,
            row_is_text=row_is_text,
            num_text_vocab=self.total_text_tokens,
        )
        ci, ct = self.img_loss_coeff_inv, self.text_loss_coeff_inv
        loss = split_weighted_mean(per_pos, split, ci, ct, drop_last_of_first=True)

        # 3-token sequence accuracy (`:697-699`) on dense logits for rows
        # [split, split+3) only — text rows, where every image-vocab column
        # is blocked anyway, so only the text-vocab kernel slice is needed
        h3 = h[:, split : split + 3]
        logits3 = jnp.einsum(
            "bnd,dv->bnv", h3,
            kernel[:, : self.total_text_tokens].astype(h3.dtype),
            preferred_element_type=jnp.float32,
        )
        if bias is not None:
            logits3 = logits3 + bias[: self.total_text_tokens].astype(jnp.float32)
        pred3 = jnp.argmax(logits3, axis=-1)
        accuracy = jnp.mean(
            jnp.all(
                pred3 == labels[:, split : split + 3], axis=-1
            ).astype(jnp.float32)
        )
        return loss, accuracy

    def __call__(
        self,
        text: jnp.ndarray,
        image: Optional[jnp.ndarray] = None,
        return_loss: bool = False,
        inverse_mapping: bool = False,
        reverse_model: bool = False,
        null_cond_prob: float = 0.0,
        deterministic: bool = True,
        trunk_fn=None,
    ):
        """text: [B, text_seq_len] int ids; image: [B, <=image_seq_len] codebook ids.

        Raw-pixel image input is handled by the pipeline (frozen VAE encode)
        before this call — see module docstring.

        `trunk_fn` (optional) substitutes the transformer trunk:
        embeddings -> trunk_fn(tokens) -> head. Used to run the trunk
        under a different executor from OUTSIDE the module — e.g.
        pipeline-parallel via `transformer.make_pipeline_trunk` (build
        the closure OUTSIDE apply; flax intercepts module construction
        inside a parent scope) with the trunk params sharded over a pp
        mesh (see tests/test_gpipe.py). Deterministic forward only.
        """
        text, tokens = self.embed_text(text, null_cond_prob)

        if image is not None and image.shape[1] > 0:
            image_emb = self.image_emb(image)
            if not self.rotary_emb:
                image_emb = image_emb + self.image_pos_emb(image_emb.shape[1])
            if inverse_mapping:
                tokens = jnp.concatenate([image_emb, tokens], axis=1)
            else:
                tokens = jnp.concatenate([tokens, image_emb], axis=1)

        seq_len = tokens.shape[1]
        if seq_len > self.total_seq_len:  # drop the final token's input slot
            tokens = tokens[:, : self.total_seq_len]
            seq_len = self.total_seq_len

        if self.stable:
            alpha = 0.1
            tokens = tokens * alpha + jax.lax.stop_gradient(tokens) * (1 - alpha)

        if trunk_fn is not None:
            assert not reverse_model, "trunk_fn callers own the layer order"
            # loud, like the reverse_model assert: the pipeline block is
            # hard-wired deterministic, so dropout would silently vanish.
            # This is a DESIGN CONSTRAINT of the pp trunk (documented at
            # make_pipeline_trunk): train with attn_dropout=ff_dropout=0
            # under pp, or use dp/fsdp/tp for dropout training.
            assert deterministic, (
                "trunk_fn (pipeline parallelism) supports deterministic "
                "execution only — set attn_dropout=ff_dropout=0, or train "
                "under dp/fsdp/tp instead"
            )
            out = trunk_fn(tokens)
        else:
            out = self.transformer(
                tokens, reverse_model=reverse_model, deterministic=deterministic
            )

        if return_loss and self.fused_ce and not self.is_initializing():
            # vocab-chunked CE: never materializes [B, N, V] logits
            # (ops/losses.py); init takes the dense path. The inverse
            # objective's 3-token accuracy argmax uses a [B, 3, V] dense
            # block instead of full logits.
            if inverse_mapping:
                return self._fused_inverse_loss(out, text, image, seq_len)
            return self._fused_forward_loss(out, text, image, seq_len)

        logits = self.to_logits(out)

        lmask = self._logits_blocked(seq_len, inverse_mapping)[None]
        logits = jnp.where(lmask, NEG_MASK_VALUE, logits.astype(jnp.float32))

        if not return_loss:
            return logits

        assert image is not None, "when training, image must be supplied"
        offsetted_image = image + self.total_text_tokens

        if inverse_mapping:
            # image first, then text: labels rotate image forward one step and
            # append the full bos-padded text (`:686-687`)
            labels = jnp.concatenate([offsetted_image[:, 1:], text], axis=1)
            split = self.image_seq_len  # see module docstring re: fork's quirk
            loss_text = cross_entropy(logits[:, split:], labels[:, split:])
            loss_img = cross_entropy(logits[:, : split - 1], labels[:, : split - 1])
            pred3 = jnp.argmax(logits[:, split : split + 3], axis=-1)
            accuracy = jnp.mean(
                jnp.all(pred3 == labels[:, split : split + 3], axis=-1).astype(jnp.float32)
            )
            ct, ci = self.text_loss_coeff_inv, self.img_loss_coeff_inv
            loss = (ct * loss_text + ci * loss_img) / (ct + ci)
        else:
            labels = jnp.concatenate([text[:, 1:], offsetted_image], axis=1)
            split = self.text_seq_len
            loss_text = cross_entropy(logits[:, :split], labels[:, :split])
            loss_img = cross_entropy(logits[:, split:], labels[:, split:])
            ct = self.text_loss_coeff
            ci = self.loss_img_weight if self.img_loss_coeff is None else self.img_loss_coeff
            loss = (ct * loss_text + ci * loss_img) / (ct + ci)
            accuracy = None

        return loss, accuracy

    # ------------------------------------------------ cached decode methods

    def decode_prefill(self, text: jnp.ndarray, cache: dict):
        """Run the text prefix (bos + text) through the transformer, filling
        the decode cache. Returns (last-position logits [B, V], cache) — the
        logits for image slot 0."""
        _, tokens = self.embed_text(text, null_cond_prob=0.0)
        out, cache = self.transformer(tokens, cache=cache)
        logits = self.to_logits(out[:, -1:])  # only the last row is needed
        return logits[:, 0].astype(jnp.float32), cache

    def decode_image_step(self, img_token: jnp.ndarray, image_pos, cache: dict):
        """Feed one sampled image token (grid index `image_pos`, traced —
        a scalar for lockstep decode or [B] for per-row slot positions);
        returns (next-position logits [B, V], cache)."""
        emb = self.image_emb(img_token[:, None].astype(jnp.int32))
        if not self.rotary_emb:
            table = self.image_pos_emb(self.image_seq_len)
            clipped = jnp.clip(image_pos, 0, self.image_seq_len - 1)
            if jnp.ndim(image_pos) == 1:
                row = jax.vmap(
                    lambda p: jax.lax.dynamic_slice_in_dim(table, p, 1, axis=0)
                )(clipped)  # [B, 1, dim]
                emb = emb + row
            else:
                row = jax.lax.dynamic_slice_in_dim(table, clipped, 1, axis=0)
                emb = emb + row[None]
        out, cache = self.transformer(emb, cache=cache)
        return self.to_logits(out)[:, 0].astype(jnp.float32), cache

    def decode_resume(self, text: jnp.ndarray, image_tokens: jnp.ndarray,
                      image_pos, cache: dict):
        """Teacher-forced re-prefill of prompt + generated image prefix in
        ONE cached forward — the decode-state migration fast path: a row
        resuming at position k pays one parallel prefill instead of k
        sequential decode steps.

        `image_tokens` is the [B, image_seq_len] generated-token buffer
        (zeros beyond each row's prefix), `image_pos` [B] the per-row
        resume positions k. The forward runs the SAME per-position math
        as the incremental path — embeddings as `decode_image_step`,
        batch token-shift (value-equal to the streaming ring shift),
        causal cached attention from position 0 — over the fixed length
        text_len + image_seq_len - 1 (the last image token's K/V is never
        read: decode at the final position attends only below it). K/V
        beyond a row's k is garbage from the zero padding; decode never
        reads past the stamped index and overwrites those positions as it
        advances, the same stale-content argument the slot reuse and
        paging paths already rely on. Shift rings are rebuilt per row at
        the window BELOW text_len + k (`shift_ring_from_prefill_at` via
        the cache's `ring_end` leaf, stripped from the result). Returns
        (pending logits for each row's position k [B, V], cache) — for
        k = 0 this degenerates to exactly `decode_prefill`.
        """
        _, tokens = self.embed_text(text, null_cond_prob=0.0)
        text_len = tokens.shape[1]  # text_seq_len + 1 (<bos>)
        # image tokens 0..image_seq_len-2, embedded exactly as
        # decode_image_step embeds token j at grid position j
        img_tok = image_tokens[:, : self.image_seq_len - 1].astype(jnp.int32)
        img = self.image_emb(img_tok)
        if not self.rotary_emb:
            img = img + self.image_pos_emb(self.image_seq_len)[
                None, : self.image_seq_len - 1
            ]
        seq = jnp.concatenate([tokens, img.astype(tokens.dtype)], axis=1)
        image_pos = jnp.asarray(image_pos, jnp.int32)
        cache = dict(cache)
        ring_end = text_len + image_pos  # [B] global resume positions
        cache = _with_ring_end(cache, ring_end, self.executor, self.depth)
        out, cache = self.transformer(seq, cache=cache)
        # pending logits for per-row position k live at global position
        # text_len - 1 + k (the output of feeding token k-1; k = 0 reads
        # the last text position, exactly decode_prefill's slot-0 logits)
        sel = jax.vmap(
            lambda o, p: jax.lax.dynamic_slice_in_dim(o, p, 1, axis=0)
        )(out, text_len - 1 + image_pos)  # [B, 1, dim]
        row = self.to_logits(sel)[:, 0].astype(jnp.float32)
        return row, cache


def init_decode_cache(model: DALLE, batch: int, dtype=None) -> dict:
    """Fixed-shape decode cache for `generate_images_cached`.

    Sized total_seq_len + 1 so the scan can uniformly feed every sampled
    token (the final write lands in the spare slot and its logits are
    discarded)."""
    return make_decode_cache(
        depth=model.depth,
        batch=batch,
        max_len=model.total_seq_len + 1,
        heads=model.heads,
        dim_head=model.dim_head,
        dim=model.dim,
        image_fmap_size=model.image_fmap_size,
        shift_tokens=model.shift_tokens,
        dtype=model.dtype if dtype is None else dtype,
        executor=model.executor,
        kv_dtype=getattr(model, "kv_dtype", None),
    )


def _primed_image_tokens(
    model: DALLE,
    batch: int,
    init_image_tokens: Optional[jnp.ndarray],
    num_init_img_tokens: Optional[int],
):
    """Image-token buffer with the optional priming prefix written in.

    The reference primes generation with the first 43.75% of a source
    image's tokens by default (`dalle_pytorch.py:537-546`). Returns
    (tokens [B, image_seq_len], primed_len).
    """
    image_seq_len = model.image_seq_len
    img_tokens = jnp.zeros((batch, image_seq_len), dtype=jnp.int32)
    primed = 0
    if init_image_tokens is not None:
        primed = (
            int(0.4375 * image_seq_len)
            if num_init_img_tokens is None
            else num_init_img_tokens
        )
        assert primed < image_seq_len
        img_tokens = img_tokens.at[:, :primed].set(init_image_tokens[:, :primed])
    return img_tokens, primed


@functools.lru_cache(maxsize=32)
def _jitted_sampler(fn_builder, model, static_key):
    """One compiled sampler per (entry point, model, sampling params).

    Without this, every `generate_images*` call dispatches its prefill and
    setup ops eagerly — one backend round trip per op, which dominates
    wall time on remote/tunneled devices (BASELINE.md measurement notes).

    A builder may carry `_donate_argnums` (the continuous-batching slot
    ops donate their state argument: the caller always replaces its state
    with the return value, and without donation every chunk/prefill/release
    dispatch would keep TWO copies of the whole slot KV cache alive and
    pay a full-cache copy).
    """
    return jax.jit(
        fn_builder(model, static_key),
        donate_argnums=getattr(fn_builder, "_donate_argnums", ()),
    )


_warned_eager_sampler = False


def _jit_sample(fn_builder, model, static_key, *args):
    try:
        jitted = _jitted_sampler(fn_builder, model, static_key)
    except TypeError:  # unhashable model field (list attn_types, custom mesh)
        global _warned_eager_sampler
        if not _warned_eager_sampler:
            _warned_eager_sampler = True
            import warnings

            warnings.warn(
                "DALLE model is unhashable (list-valued field or custom "
                "sp_mesh?) — sampling falls back to EAGER dispatch, which "
                "is drastically slower on remote devices. Use tuples for "
                "attn_types/shared_*_ids to get the jit-cached sampler.",
                stacklevel=3,
            )
        return fn_builder(model, static_key)(*args)
    return jitted(*args)


def generate_images_cached(
    model: DALLE,
    variables,
    rng: jax.Array,
    text: jnp.ndarray,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    cond_scale: float = 1.0,
    init_image_tokens: Optional[jnp.ndarray] = None,
    num_init_img_tokens: Optional[int] = None,
    vae=None,
    vae_params=None,
):
    """KV-cached autoregressive sampling: O(seq) attention per generated
    token instead of `generate_images`' full re-forward (the reference's
    `use_cache=True` path, `dalle_pytorch.py:652-653`, `attention.py:71-76`).

    Prefills the text prefix once, then `lax.scan`s single-token decode
    steps against the fixed-shape cache (KV + token-shift rings).
    Classifier-free guidance (cond_scale != 1) stacks a null-text stream
    along the batch axis — one model call serves both — and blends logits
    per step (`dalle_pytorch.py:575-585`). The whole pipeline (prefill +
    decode scan) runs as ONE jitted program, cached per model/params.

    Pass a `DiscreteVAE` module + its params as `vae`/`vae_params` to
    fuse the pixel decode into the SAME program — returns (tokens,
    pixels) from one dispatch. On synchronous-dispatch backends (the
    tunneled TPU, ~1 s per round trip) this halves the per-batch host
    overhead vs sampling then decoding in two dispatches.
    """
    static_key = (filter_thres, temperature, cond_scale, num_init_img_tokens,
                  vae)
    if init_image_tokens is None and vae is None:
        return _jit_sample(
            _cached_sampler_builder, model, static_key, variables, rng, text
        )
    return _jit_sample(
        _cached_sampler_builder, model, static_key,
        variables, rng, text, init_image_tokens, vae_params,
    )


def _cached_sampler_builder(model, key):
    filter_thres, temperature, cond_scale, num_init, vae = key

    def fn(variables, rng, text, init_image_tokens=None, vae_params=None):
        toks = _generate_images_cached_impl(
            model, variables, rng, text,
            filter_thres=filter_thres, temperature=temperature,
            cond_scale=cond_scale,
            init_image_tokens=init_image_tokens,
            num_init_img_tokens=num_init,
        )
        if vae is None:
            return toks
        pixels = vae.apply(
            {"params": vae_params}, toks, method=type(vae).decode
        )
        return toks, pixels

    return fn


def _generate_images_cached_impl(
    model: DALLE,
    variables,
    rng: jax.Array,
    text: jnp.ndarray,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    cond_scale: float = 1.0,
    init_image_tokens: Optional[jnp.ndarray] = None,
    num_init_img_tokens: Optional[int] = None,
):
    b = text.shape[0]
    image_seq_len = model.image_seq_len
    use_null = cond_scale != 1.0
    img_tokens, primed = _primed_image_tokens(
        model, b, init_image_tokens, num_init_img_tokens
    )

    def blend(row):
        if not use_null:
            return row
        cond, null = row[:b], row[b:]
        return null + (cond - null) * cond_scale

    if use_null:
        # null conditioning == all-pad text (`:602-604`), stacked on batch
        text = jnp.concatenate([text, jnp.zeros_like(text)], axis=0)
    row, cache = model.apply(
        variables,
        text,
        init_decode_cache(model, text.shape[0]),
        method=DALLE.decode_prefill,
    )

    # image-range logits mask (rows text_seq_len.. of `_logits_mask` are all
    # identical: only image-vocab ids are allowed)
    blocked = jnp.asarray(
        np.arange(model.total_tokens) < model.total_text_tokens
    )[None]

    def step(carry, i):
        img_tokens, cache, row, rng = carry
        rng, sample_rng = jax.random.split(rng)
        masked = jnp.where(blocked, NEG_MASK_VALUE, blend(row))
        filtered = top_k_filter(masked, thres=filter_thres)
        sample = gumbel_sample(sample_rng, filtered, temperature=temperature)
        sample = (sample - model.total_text_tokens).astype(jnp.int32)
        prev = jax.lax.dynamic_index_in_dim(img_tokens, i, axis=1, keepdims=False)
        new = jnp.where(i < primed, prev, sample)
        img_tokens = jax.lax.dynamic_update_slice(img_tokens, new[:, None], (0, i))
        feed = jnp.concatenate([new, new], axis=0) if use_null else new
        row, cache = model.apply(
            variables, feed, i, cache, method=DALLE.decode_image_step
        )
        return (img_tokens, cache, row, rng), None

    carry = (img_tokens, cache, row, rng)
    (img_tokens, _, _, _), _ = jax.lax.scan(step, carry, jnp.arange(image_seq_len))
    return img_tokens


def generate_images_cached_batched(
    model: DALLE,
    variables,
    text: jnp.ndarray,
    seeds: jnp.ndarray,
    temperatures: jnp.ndarray,
    keep_k: jnp.ndarray,
    cond_scale: float = 1.0,
    vae=None,
    vae_params=None,
):
    """KV-cached sampling with PER-SAMPLE sampling parameters.

    The serving engine's decode path: one compiled program per
    (model, batch shape, cond_scale), with each batch row carrying its own
    traced `seeds[i]` / `temperatures[i]` / `keep_k[i]` so heterogeneous
    requests coalesce into one fixed-shape dispatch
    (`dalle_pytorch_tpu/serving/engine.py` pads partial batches up to the
    nearest compiled shape and discards the padded rows).

    Row i's RNG stream is derived ONLY from (seeds[i], decode step) — never
    from batch composition or row position — so a request produces
    identical tokens whichever micro-batch it lands in (pinned by
    tests/test_serving_e2e.py). `keep_k` counts logits to KEEP over the
    full vocab row (the engine converts the CLI's fractional `top_k`
    threshold with the same `max(int((1-thres)*V), 1)` rule as
    `top_k_filter`). Like the static-parameter sampler, pass `vae`/
    `vae_params` to fuse pixel decode into the same program.
    """
    static_key = (cond_scale, vae)
    return _jit_sample(
        _batched_sampler_builder, model, static_key,
        variables, text,
        jnp.asarray(seeds, jnp.int32),
        jnp.asarray(temperatures, jnp.float32),
        jnp.asarray(keep_k, jnp.int32),
        vae_params,
    )


def _batched_sampler_builder(model, key):
    cond_scale, vae = key

    def fn(variables, text, seeds, temperatures, keep_k, vae_params=None):
        toks = _generate_images_cached_batched_impl(
            model, variables, text, seeds, temperatures, keep_k,
            cond_scale=cond_scale,
        )
        if vae is None:
            return toks
        pixels = vae.apply(
            {"params": vae_params}, toks, method=type(vae).decode
        )
        return toks, pixels

    return fn


def _generate_images_cached_batched_impl(
    model: DALLE,
    variables,
    text: jnp.ndarray,
    seeds: jnp.ndarray,
    temperatures: jnp.ndarray,
    keep_k: jnp.ndarray,
    cond_scale: float = 1.0,
):
    from dalle_pytorch_tpu.ops.sampling import (
        top_k_filter_per_row, gumbel_sample_per_row, per_row_step_keys,
    )

    b = text.shape[0]
    image_seq_len = model.image_seq_len
    use_null = cond_scale != 1.0
    img_tokens = jnp.zeros((b, image_seq_len), dtype=jnp.int32)

    def blend(row):
        if not use_null:
            return row
        cond, null = row[:b], row[b:]
        return null + (cond - null) * cond_scale

    if use_null:
        text = jnp.concatenate([text, jnp.zeros_like(text)], axis=0)
    row, cache = model.apply(
        variables,
        text,
        init_decode_cache(model, text.shape[0]),
        method=DALLE.decode_prefill,
    )

    blocked = jnp.asarray(
        np.arange(model.total_tokens) < model.total_text_tokens
    )[None]

    def step(carry, i):
        img_tokens, cache, row = carry
        masked = jnp.where(blocked, NEG_MASK_VALUE, blend(row))
        filtered = top_k_filter_per_row(masked, keep_k)
        # (seed, image position) keyed RNG — shared derivation with the
        # continuous-batching chunk decode (ops/sampling.py), so the two
        # engines sample bit-identical streams per row
        step_keys = per_row_step_keys(seeds, jnp.full((b,), i, jnp.int32))
        sample = gumbel_sample_per_row(step_keys, filtered, temperatures)
        sample = (sample - model.total_text_tokens).astype(jnp.int32)
        img_tokens = jax.lax.dynamic_update_slice(img_tokens, sample[:, None], (0, i))
        feed = jnp.concatenate([sample, sample], axis=0) if use_null else sample
        row, cache = model.apply(
            variables, feed, i, cache, method=DALLE.decode_image_step
        )
        return (img_tokens, cache, row), None

    carry = (img_tokens, cache, row)
    (img_tokens, _, _), _ = jax.lax.scan(step, carry, jnp.arange(image_seq_len))
    return img_tokens


# ------------------------------------------------ continuous batching (slots)
#
# The micro-batch sampler above flushes a batch and runs the ENTIRE
# image_seq_len decode scan before anything else can touch the device; a
# request arriving just after a flush waits a whole pass for its first
# token. The slot API below instead keeps ONE persistent fixed-shape decode
# state of `max_batch` cache slots, advanced in chunks of K tokens by one
# jitted step; a host-side allocator (serving/engine.py) admits new prompts
# into free slots (batched prefill-into-slots) and retires finished rows at
# chunk boundaries — vLLM-style token-boundary admission, with the same
# fixed-shape-compilation discipline as the rest of the serving stack
# (three compiled slot programs: prefill at batch `prefill_batch`, chunk
# at max_batch, slot release — R pending admissions cost
# ceil(R / prefill_batch) dispatches, not R).
#
# Per-row state threaded through the stack: per-slot cache `index`
# (models/attention.py per-row cached path), per-slot token-shift ring
# positions (ops/shift.py), per-slot image position / active mask /
# seed / temperature / top-k here. RNG is keyed by (seed, image position)
# via ops/sampling.py:per_row_step_keys — the same derivation the
# micro-batch sampler uses — so a request's tokens are bit-identical
# whether served alone, padded, or admitted mid-flight (pinned by
# tests/test_continuous.py).


def init_slot_state(model: DALLE, max_batch: int, dtype=None) -> dict:
    """Persistent decode state for `max_batch` cache slots.

    Free slots hold zeros; `prefill_into_slots` overwrites admitted slots
    wholesale (including every cache position, so no state leaks between
    the consecutive occupants of a slot), and `active` gates which rows
    advance in `decode_image_chunk`.
    """
    s = int(max_batch)
    return {
        "cache": make_decode_cache(
            depth=model.depth,
            batch=s,
            max_len=model.total_seq_len + 1,
            heads=model.heads,
            dim_head=model.dim_head,
            dim=model.dim,
            image_fmap_size=model.image_fmap_size,
            shift_tokens=model.shift_tokens,
            dtype=model.dtype if dtype is None else dtype,
            executor=model.executor,
            per_row=True,
            kv_dtype=getattr(model, "kv_dtype", None),
        ),
        # pending next-position logits per slot (what the next sample
        # draws from; written by prefill, refreshed every decode step)
        "row": jnp.zeros((s, model.total_tokens), jnp.float32),
        "img_tokens": jnp.zeros((s, model.image_seq_len), jnp.int32),
        "img_pos": jnp.zeros((s,), jnp.int32),
        "active": jnp.zeros((s,), jnp.bool_),
        "seeds": jnp.zeros((s,), jnp.int32),
        "temps": jnp.ones((s,), jnp.float32),
        "keep_k": jnp.ones((s,), jnp.int32),
    }


def prefill_into_slots(
    model: DALLE,
    variables,
    state: dict,
    texts: jnp.ndarray,
    slots,
    seeds,
    temperatures,
    keep_ks,
    block_bitmap=None,
):
    """Admit up to R prompts into their cache slots in ONE donated dispatch.

    `texts` is [R, text_seq_len]; `slots`/`seeds`/`temperatures`/`keep_ks`
    are [R] (traced data — ONE compiled program per prefill batch size R
    regardless of which slots are filled). Runs the text prefill at batch R
    — the same `decode_prefill` the micro-batch sampler runs, so per-row
    numerics match the lockstep path bit-for-bit (batch-composition
    invariance is already the serving stack's contract) — and scatters each
    resulting K/V row (+ token-shift rings, pending logits, per-slot
    sampling params) into its slot of the persistent state.

    Fewer than R real prompts: pad by REPEATING a real (slot, prompt) pair —
    the duplicate rows re-write the same slot with identical content, so
    padding costs compute but never correctness (the same trade the
    micro-batch engine makes with its padded batch rungs). Duplicate slots
    among the real rows are the caller's bug.

    `state` is DONATED: its buffers are invalid after the call — always
    replace your reference with the return value (as the slot ops below
    all do). This keeps exactly one slot cache alive instead of two.

    `block_bitmap` ([depth, R, nb] int32) arms decode-sparsity for the
    prefill forward too: masked layers route through the block-sparse
    flash kernel instead of the dense pattern path (text-prefix tiles are
    always live, and text rows under the shipped policies are exactly
    causal). Selects the "sparse"-keyed compiled program.
    """
    texts = jnp.asarray(texts, jnp.int32)
    prefill_batch = int(texts.shape[0])
    args = (
        variables, state, texts,
        jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
        jnp.asarray(temperatures, jnp.float32), jnp.asarray(keep_ks, jnp.int32),
    )
    if block_bitmap is None:
        return _jit_sample(
            _prefill_slots_builder, model, (prefill_batch,), *args
        )
    return _jit_sample(
        _prefill_slots_builder, model, (prefill_batch, "sparse"),
        *args, jnp.asarray(block_bitmap, jnp.int32),
    )


def _prefill_slots_builder(model, key):
    prefill_batch = key[0]
    sparse = "sparse" in key
    batch_axis = 1 if model.executor == "scan" else 0

    def fn(variables, state, texts, slots, seeds, temperatures, keep_ks,
           *sparse_args):
        cache0 = init_decode_cache(model, prefill_batch)
        if sparse:
            (block_bitmap,) = sparse_args
            cache0 = _with_block_bitmap(
                cache0, block_bitmap, model.executor, model.depth
            )
        rows, cache_r = model.apply(
            variables,
            texts,
            cache0,
            method=DALLE.decode_prefill,
        )
        if sparse:
            # the persistent slot cache carries no bitmap leaves — strip
            # the round-tripped ones before the structural scatter below
            cache_r = _without_block_bitmap(cache_r, model.executor)

        def write(path, s_leaf, p_leaf):
            # `index` leaves are not scattered: the chunk step stamps every
            # layer's index from the per-slot `img_pos` (single source of
            # truth for position — see set_decode_cache_index)
            if getattr(path[-1], "key", None) == "index":
                return s_leaf
            out = s_leaf
            for r in range(prefill_batch):
                p_row = jax.lax.dynamic_slice_in_dim(
                    p_leaf, r, 1, axis=batch_axis
                )
                out = jax.lax.dynamic_update_slice_in_dim(
                    out, p_row.astype(out.dtype), slots[r], axis=batch_axis
                )
            return out

        new_cache = jax.tree_util.tree_map_with_path(
            write, state["cache"], cache_r
        )
        out = dict(state)
        out["cache"] = new_cache
        row_buf = state["row"]
        tok_buf = state["img_tokens"]
        zero_row = jnp.zeros((1, model.image_seq_len), jnp.int32)
        for r in range(prefill_batch):
            row_buf = jax.lax.dynamic_update_slice(
                row_buf, rows[r : r + 1].astype(row_buf.dtype), (slots[r], 0)
            )
            tok_buf = jax.lax.dynamic_update_slice(
                tok_buf, zero_row, (slots[r], 0)
            )
        out["row"] = row_buf
        out["img_tokens"] = tok_buf
        # scatter-with-duplicates is safe here: padded rows repeat a real
        # (slot, value) pair, so whichever duplicate lands last is identical
        out["img_pos"] = state["img_pos"].at[slots].set(0)
        out["active"] = state["active"].at[slots].set(True)
        out["seeds"] = state["seeds"].at[slots].set(seeds)
        out["temps"] = state["temps"].at[slots].set(temperatures)
        out["keep_k"] = state["keep_k"].at[slots].set(keep_ks)
        return out

    return fn


_prefill_slots_builder._donate_argnums = (1,)  # state


def resume_into_slots(
    model: DALLE,
    variables,
    state: dict,
    texts: jnp.ndarray,
    img_tokens: jnp.ndarray,
    img_pos,
    slots,
    seeds,
    temperatures,
    keep_ks,
):
    """Admit up to R MID-DECODE rows into their cache slots in ONE
    donated dispatch (decode-state migration, serving/migrate.py).

    Like `prefill_into_slots`, but each row arrives with a generated
    image prefix: `img_tokens` [R, image_seq_len] (zeros beyond the
    prefix) and `img_pos` [R] resume positions. `DALLE.decode_resume`
    re-prefills prompt + prefix in one teacher-forced forward — K/V,
    shift rings (per-row window), pending logits and position all land
    exactly where the incremental decode would have left them, so the
    next chunk dispatch continues from position k instead of 0. Padding,
    donation and scatter semantics match `prefill_into_slots`.
    """
    texts = jnp.asarray(texts, jnp.int32)
    prefill_batch = int(texts.shape[0])
    return _jit_sample(
        _resume_slots_builder, model, (prefill_batch,),
        variables, state, texts,
        jnp.asarray(img_tokens, jnp.int32), jnp.asarray(img_pos, jnp.int32),
        jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
        jnp.asarray(temperatures, jnp.float32), jnp.asarray(keep_ks, jnp.int32),
    )


def _resume_slots_builder(model, key):
    (prefill_batch,) = key
    batch_axis = 1 if model.executor == "scan" else 0

    def fn(variables, state, texts, img_tokens, img_pos, slots, seeds,
           temperatures, keep_ks):
        rows, cache_r = model.apply(
            variables,
            texts,
            img_tokens,
            img_pos,
            init_decode_cache(model, prefill_batch),
            method=DALLE.decode_resume,
        )

        def write(path, s_leaf, p_leaf):
            # `index` leaves are not scattered: the chunk step stamps
            # every layer's index from the per-slot `img_pos`
            if getattr(path[-1], "key", None) == "index":
                return s_leaf
            out = s_leaf
            for r in range(prefill_batch):
                p_row = jax.lax.dynamic_slice_in_dim(
                    p_leaf, r, 1, axis=batch_axis
                )
                out = jax.lax.dynamic_update_slice_in_dim(
                    out, p_row.astype(out.dtype), slots[r], axis=batch_axis
                )
            return out

        new_cache = jax.tree_util.tree_map_with_path(
            write, state["cache"], cache_r
        )
        out = dict(state)
        out["cache"] = new_cache
        row_buf = state["row"]
        tok_buf = state["img_tokens"]
        for r in range(prefill_batch):
            row_buf = jax.lax.dynamic_update_slice(
                row_buf, rows[r : r + 1].astype(row_buf.dtype), (slots[r], 0)
            )
            tok_buf = jax.lax.dynamic_update_slice(
                tok_buf, img_tokens[r : r + 1], (slots[r], 0)
            )
        out["row"] = row_buf
        out["img_tokens"] = tok_buf
        out["img_pos"] = state["img_pos"].at[slots].set(img_pos)
        out["active"] = state["active"].at[slots].set(True)
        out["seeds"] = state["seeds"].at[slots].set(seeds)
        out["temps"] = state["temps"].at[slots].set(temperatures)
        out["keep_k"] = state["keep_k"].at[slots].set(keep_ks)
        return out

    return fn


_resume_slots_builder._donate_argnums = (1,)  # state


def release_slots(model: DALLE, state: dict, mask) -> dict:
    """Deactivate the slots where `mask` is True (jitted, fixed shape;
    `state` is donated — replace your reference with the return value)."""
    return _jit_sample(
        _release_builder, model, (), state, jnp.asarray(mask, jnp.bool_)
    )


def _release_builder(model, key):
    del model, key

    def fn(state, mask):
        return {**state, "active": state["active"] & ~mask}

    return fn


_release_builder._donate_argnums = (0,)  # state


def decode_image_chunk(
    model: DALLE, variables, state: dict, chunk: int, block_bitmap=None
):
    """Advance every live slot by up to `chunk` tokens (one jitted program
    per (model, chunk)).

    Each of the `chunk` steps samples one token per live row from its
    pending logits — per-row (seed, image-position) RNG, per-row
    temperature/top-k — writes it at the row's own image position, and
    feeds it back through the transformer at the row's own cache position.
    Rows that hit `image_seq_len` mid-chunk freeze (their cache, tokens,
    and position stop advancing) until the host retires them at the chunk
    boundary; inactive slots compute along as padding but persist nothing.

    `state` is DONATED (see `prefill_into_slots`) — replace your reference
    with the return value.

    `block_bitmap` ([depth, max_batch, nb] int32) arms decode-time policy
    sparsity: injected into every layer's attention cache for the scan
    (models/attention.py routes masked rows through the block-sparse
    flash kernel) and stripped from the result. Traced data — re-deriving
    it every chunk never recompiles; its presence selects a separate
    compiled program (the "sparse" static-key marker), warmed like any
    other rung.
    """
    if block_bitmap is None:
        return _jit_sample(
            _chunk_builder, model, (int(chunk),), variables, state
        )
    return _jit_sample(
        _chunk_builder, model, (int(chunk), "sparse"),
        variables, state, jnp.asarray(block_bitmap, jnp.int32),
    )


def _chunk_builder(model, key):
    chunk = key[0]
    return _make_chunk_fn(model, chunk, paged=False, sparse="sparse" in key)


def _make_chunk_fn(model, chunk, paged, sparse=False):
    """One chunk program body, shared by the slotted and paged layouts so
    the decode semantics (sampling, liveness gating, position threading)
    cannot drift between them — only the cache plumbing differs: the paged
    variant takes the host-built page table as an extra traced argument,
    injects it into every layer's attention cache for the duration of the
    scan, and strips it from the result (the table is host state, not part
    of the donated device state)."""
    from dalle_pytorch_tpu.models.transformer import set_decode_cache_index
    from dalle_pytorch_tpu.ops.sampling import (
        gumbel_sample_per_row, per_row_step_keys, top_k_filter_per_row,
    )

    text_len = model.text_seq_len + 1  # <bos> + text prefix
    image_seq_len = model.image_seq_len
    blocked = jnp.asarray(
        np.arange(model.total_tokens) < model.total_text_tokens
    )[None]

    def run(variables, state, cache0):
        active = state["active"]
        seeds = state["seeds"]
        temps = state["temps"]
        keep_k = state["keep_k"]

        def step(carry, _):
            cache, row, img_tokens, img_pos = carry
            live = active & (img_pos < image_seq_len)

            masked = jnp.where(blocked, NEG_MASK_VALUE, row)
            filtered = top_k_filter_per_row(masked, keep_k)
            keys = per_row_step_keys(seeds, img_pos)
            sample = gumbel_sample_per_row(keys, filtered, temps)
            sample = (sample - model.total_text_tokens).astype(jnp.int32)

            written = jax.vmap(
                lambda r, t, p: jax.lax.dynamic_update_slice(r, t[None], (p,))
            )(img_tokens, sample, jnp.clip(img_pos, 0, image_seq_len - 1))
            img_tokens = jnp.where(live[:, None], written, img_tokens)

            # stamp every layer's cache index from the per-slot position,
            # then run one decode step at per-row positions
            cache = set_decode_cache_index(
                cache, img_pos + text_len, model.executor
            )
            new_row, cache = model.apply(
                variables, sample, img_pos, cache,
                method=DALLE.decode_image_step,
            )
            row = jnp.where(live[:, None], new_row, row)
            img_pos = jnp.where(live, img_pos + 1, img_pos)
            return (cache, row, img_tokens, img_pos), None

        carry = (
            cache0, state["row"], state["img_tokens"], state["img_pos"],
        )
        return jax.lax.scan(step, carry, None, length=chunk)[0]

    if paged:
        def fn(variables, state, page_table, *sparse_args):
            cache0 = _with_page_table(
                state["cache"], page_table, model.executor, model.depth
            )
            if sparse:
                (block_bitmap,) = sparse_args
                cache0 = _with_block_bitmap(
                    cache0, block_bitmap, model.executor, model.depth
                )
            cache, row, img_tokens, img_pos = run(variables, state, cache0)
            if sparse:
                cache = _without_block_bitmap(cache, model.executor)
            return {
                **state,
                "cache": _without_page_table(cache, model.executor),
                "row": row,
                "img_tokens": img_tokens,
                "img_pos": img_pos,
            }
    else:
        def fn(variables, state, *sparse_args):
            cache0 = state["cache"]
            if sparse:
                (block_bitmap,) = sparse_args
                cache0 = _with_block_bitmap(
                    cache0, block_bitmap, model.executor, model.depth
                )
            cache, row, img_tokens, img_pos = run(variables, state, cache0)
            if sparse:
                cache = _without_block_bitmap(cache, model.executor)
            return {
                **state,
                "cache": cache,
                "row": row,
                "img_tokens": img_tokens,
                "img_pos": img_pos,
            }

    return fn


_chunk_builder._donate_argnums = (1,)  # state


# ------------------------------------------------- paged KV cache (blocks)
#
# The slotted state above pins max_batch * (total_seq_len + 1) cache
# positions whether or not a row holds tokens — HBM spent on worst-case
# padding bounds concurrency. The paged ops below move K/V into a pool of
# fixed-size pages plus host-owned per-row page tables
# (serving/paging.py): admission maps pages, identical caption prefixes
# SHARE immutable prefill pages (content-hash prefix cache; a repeat
# prompt admits with zero transformer dispatches via its cached sidecar),
# and released rows return pages to the pool. The page table is a traced
# argument to every dispatch — ONE compiled program regardless of which
# pages are mapped — and the state stays donated exactly like the slotted
# ops. models/attention.py reads the paged cache either through a gathered
# contiguous view (bit-for-bit identical to the slotted path — the parity
# contract tests/test_paging.py pins) or the paged Pallas kernel
# (ops/pallas_decode.py).


def _with_page_table(cache, page_table, executor, depth):
    """Inject the [B, n_pages] table into every layer's attention cache
    (depth-stacked for the scan executor, which slices it per layer)."""
    pt = jnp.asarray(page_table, jnp.int32)
    if executor == "scan":
        ptd = jnp.broadcast_to(pt, (depth,) + pt.shape)
        return {**cache, "attn": {**cache["attn"], "page_table": ptd}}
    return {
        name: {**layer, "attn": {**layer["attn"], "page_table": pt}}
        for name, layer in cache.items()
    }


def _with_block_bitmap(cache, bitmaps, executor, depth):
    """Inject the per-layer decode-sparsity bitmaps [depth, B, nb] into
    every layer's attention cache (same smuggling idiom as
    `_with_page_table`; the scan executor slices its depth-stacked leaf
    per layer). nb = ceil(max_len / decode_sparse_block); nonzero =
    KV tile may be read. TRACED data — the serving policy re-derives the
    table every chunk from each row's position without recompiling."""
    bm = jnp.asarray(bitmaps, jnp.int32)
    if executor == "scan":
        return {**cache, "attn": {**cache["attn"], "block_bitmap": bm}}
    return {
        name: {
            **layer,
            "attn": {
                **layer["attn"],
                "block_bitmap": bm[int(name.split("_")[-1])],
            },
        }
        for name, layer in cache.items()
    }


def _without_block_bitmap(cache, executor):
    """Strip the bitmap leaves (attention round-trips them for nn.scan
    carry-structure parity) so the persistent donated state keeps its
    bitmap-free shape — the policy table is host state, like the page
    table."""
    if executor == "scan":
        attn = {k: v for k, v in cache["attn"].items() if k != "block_bitmap"}
        return {**cache, "attn": attn}
    return {
        name: {
            **layer,
            "attn": {
                k: v for k, v in layer["attn"].items() if k != "block_bitmap"
            },
        }
        for name, layer in cache.items()
    }


def _with_ring_end(cache, ring_end, executor, depth):
    """Inject the per-row resume window `ring_end` [B] into a decode
    cache so `shift_with_ring` rebuilds rings per row (decode_resume).
    Same smuggling idiom as `_with_page_table`; the transformer's output
    cache is rebuilt without the leaf, so nothing strips it."""
    re_ = jnp.asarray(ring_end, jnp.int32)
    if executor == "scan":
        return {**cache, "ring_end": jnp.broadcast_to(re_, (depth,) + re_.shape)}
    return {name: {**layer, "ring_end": re_} for name, layer in cache.items()}


def _without_page_table(cache, executor):
    if executor == "scan":
        attn = {k: v for k, v in cache["attn"].items() if k != "page_table"}
        return {**cache, "attn": attn}
    return {
        name: {
            **layer,
            "attn": {
                k: v for k, v in layer["attn"].items() if k != "page_table"
            },
        }
        for name, layer in cache.items()
    }


def init_paged_slot_state(
    model: DALLE, max_batch: int, n_pages: int, page_size: int, dtype=None
) -> dict:
    """Persistent paged decode state: same per-row control state as
    `init_slot_state`, with K/V in a page pool instead of per-slot lanes.
    Page 0 is the serving layer's reserved garbage page (never allocated),
    so the pool must be sized n_pages >= usable pages + 1."""
    from dalle_pytorch_tpu.models.transformer import make_paged_decode_cache

    s = int(max_batch)
    return {
        "cache": make_paged_decode_cache(
            depth=model.depth,
            batch=s,
            n_pages=int(n_pages),
            page_size=int(page_size),
            heads=model.heads,
            dim_head=model.dim_head,
            dim=model.dim,
            image_fmap_size=model.image_fmap_size,
            shift_tokens=model.shift_tokens,
            dtype=model.dtype if dtype is None else dtype,
            executor=model.executor,
            kv_dtype=getattr(model, "kv_dtype", None),
        ),
        "row": jnp.zeros((s, model.total_tokens), jnp.float32),
        "img_tokens": jnp.zeros((s, model.image_seq_len), jnp.int32),
        "img_pos": jnp.zeros((s,), jnp.int32),
        "active": jnp.zeros((s,), jnp.bool_),
        "seeds": jnp.zeros((s,), jnp.int32),
        "temps": jnp.ones((s,), jnp.float32),
        "keep_k": jnp.ones((s,), jnp.int32),
    }


def _extract_rings(cache_r, executor):
    """Row-major token-shift-ring sidecar from a fresh prefill cache: the
    part of a prefix's post-prefill state that is NOT page-addressable
    (plus the pending logits, captured separately). Empty dict when the
    model doesn't shift tokens."""
    if executor == "scan":
        return {
            name: jnp.moveaxis(cache_r[name], 1, 0)  # [R, depth, fmap, dim]
            for name in ("shift_attn", "shift_ff")
            if name in cache_r
        }
    out = {}
    for lname, layer in cache_r.items():
        rings = {
            n: layer[n] for n in ("shift_attn", "shift_ff") if n in layer
        }
        if rings:
            out[lname] = rings
    return out


def prefill_into_slots_paged(
    model: DALLE,
    variables,
    state: dict,
    texts: jnp.ndarray,
    slots,
    seeds,
    temperatures,
    keep_ks,
    page_rows,
    partial_dst,
    page_size: int,
    block_bitmap=None,
):
    """Paged-layout batched admission: the same batch-R text prefill as
    `prefill_into_slots`, scattered into PAGES instead of slot lanes.

    `page_rows` is [R, n_text_pages] — the physical page for each of row
    r's text blocks (host-allocated; shared prefix blocks may point at
    pages other rows/the prefix cache also map, in which case this dispatch
    rewrites them with bit-identical content — prefill K/V is a
    deterministic, batch-composition-invariant function of the text).
    `partial_dst` is [R]: an EXTRA destination page for each row's last
    text block — the prefix cache's immutable snapshot of the divergence
    block, which the row goes on to mutate in its own copy while the cache
    keeps this one (copy-on-write at registration time). Page 0 (garbage)
    disables the extra write for rows the host isn't registering.

    Returns (state, sidecar): `state` donated/replaced as usual; `sidecar`
    is {"row": [R, V] pending logits, "rings": row-major shift rings} —
    everything a later full-prefix admission needs to skip the transformer
    entirely (`admit_cached_prefix`).
    """
    texts = jnp.asarray(texts, jnp.int32)
    prefill_batch = int(texts.shape[0])
    page_rows = jnp.asarray(page_rows, jnp.int32)
    n_text_pages = int(page_rows.shape[1])
    args = (
        variables, state, texts,
        jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
        jnp.asarray(temperatures, jnp.float32), jnp.asarray(keep_ks, jnp.int32),
        page_rows, jnp.asarray(partial_dst, jnp.int32),
    )
    if block_bitmap is None:
        return _jit_sample(
            _prefill_slots_paged_builder, model,
            (prefill_batch, int(page_size), n_text_pages), *args,
        )
    return _jit_sample(
        _prefill_slots_paged_builder, model,
        (prefill_batch, int(page_size), n_text_pages, "sparse"),
        *args, jnp.asarray(block_bitmap, jnp.int32),
    )


def _prefill_slots_paged_builder(model, key):
    prefill_batch, page_size, n_text_pages = key[:3]
    sparse = "sparse" in key
    batch_axis = 1 if model.executor == "scan" else 0

    def block_of(p_leaf, r, j, last_axis=False):
        """Row r's K/V slice for text block j, zero-padded to page_size
        past the prefill cache's end (static shapes throughout).
        `last_axis` addresses scale leaves ([.., H, max_len]; the
        sequence axis is LAST, there is no head-dim axis after it)."""
        row_kv = p_leaf[:, r] if batch_axis == 1 else p_leaf[r]
        seq_ax = row_kv.ndim - (1 if last_axis else 2)
        max_len = row_kv.shape[seq_ax]
        lo = j * page_size
        hi = min(lo + page_size, max_len)
        blk = jax.lax.slice_in_dim(row_kv, lo, hi, axis=seq_ax)
        if hi - lo < page_size:
            pad = [(0, 0)] * row_kv.ndim
            pad[seq_ax] = (0, page_size - (hi - lo))
            blk = jnp.pad(blk, pad)
        return blk

    def fn(variables, state, texts, slots, seeds, temperatures, keep_ks,
           page_rows, partial_dst, *sparse_args):
        cache0 = init_decode_cache(model, prefill_batch)
        if sparse:
            (block_bitmap,) = sparse_args
            cache0 = _with_block_bitmap(
                cache0, block_bitmap, model.executor, model.depth
            )
        rows, cache_r = model.apply(
            variables,
            texts,
            cache0,
            method=DALLE.decode_prefill,
        )
        if sparse:
            cache_r = _without_block_bitmap(cache_r, model.executor)

        def write(path, s_leaf, p_leaf):
            key_ = getattr(path[-1], "key", None)
            if key_ == "index":
                # stamped from per-slot img_pos every chunk step
                return s_leaf
            if key_ in ("k", "v", "k_scale", "v_scale"):
                last_axis = key_.endswith("_scale")

                def put(out, blk, page):
                    if batch_axis == 1:
                        idx = (0, page) + (0,) * (out.ndim - 2)
                        return jax.lax.dynamic_update_slice(
                            out, blk[:, None], idx
                        )
                    idx = (page,) + (0,) * (out.ndim - 1)
                    return jax.lax.dynamic_update_slice(out, blk[None], idx)

                out = s_leaf
                for r in range(prefill_batch):
                    for j in range(n_text_pages):
                        blk = block_of(p_leaf, r, j, last_axis).astype(
                            out.dtype
                        )
                        out = put(out, blk, page_rows[r, j])
                    # prefix-cache snapshot of the divergence block (page 0
                    # = not registering; the garbage page absorbs it)
                    blk = block_of(
                        p_leaf, r, n_text_pages - 1, last_axis
                    ).astype(out.dtype)
                    out = put(out, blk, partial_dst[r])
                return out
            # shift rings: per-slot row scatter, same as the slotted path
            out = s_leaf
            for r in range(prefill_batch):
                p_row = jax.lax.dynamic_slice_in_dim(
                    p_leaf, r, 1, axis=batch_axis
                )
                out = jax.lax.dynamic_update_slice_in_dim(
                    out, p_row.astype(out.dtype), slots[r], axis=batch_axis
                )
            return out

        new_cache = jax.tree_util.tree_map_with_path(
            write, state["cache"], cache_r
        )
        out = dict(state)
        out["cache"] = new_cache
        row_buf = state["row"]
        tok_buf = state["img_tokens"]
        zero_row = jnp.zeros((1, model.image_seq_len), jnp.int32)
        for r in range(prefill_batch):
            row_buf = jax.lax.dynamic_update_slice(
                row_buf, rows[r : r + 1].astype(row_buf.dtype), (slots[r], 0)
            )
            tok_buf = jax.lax.dynamic_update_slice(
                tok_buf, zero_row, (slots[r], 0)
            )
        out["row"] = row_buf
        out["img_tokens"] = tok_buf
        out["img_pos"] = state["img_pos"].at[slots].set(0)
        out["active"] = state["active"].at[slots].set(True)
        out["seeds"] = state["seeds"].at[slots].set(seeds)
        out["temps"] = state["temps"].at[slots].set(temperatures)
        out["keep_k"] = state["keep_k"].at[slots].set(keep_ks)
        sidecar = {
            "row": rows.astype(jnp.float32),
            "rings": _extract_rings(cache_r, model.executor),
        }
        return out, sidecar

    return fn


_prefill_slots_paged_builder._donate_argnums = (1,)  # state


def resume_into_slots_paged(
    model: DALLE,
    variables,
    state: dict,
    texts: jnp.ndarray,
    img_tokens: jnp.ndarray,
    img_pos,
    slots,
    seeds,
    temperatures,
    keep_ks,
    page_rows,
    page_size: int,
):
    """Paged-layout mid-decode admission: the same teacher-forced
    re-prefill as `resume_into_slots`, scattered into PAGES.

    `page_rows` is [R, pages_per_row]: the physical page for each of row
    r's blocks — real pages up to the block covering the row's resume
    position, the garbage page beyond (the fixed-shape scatter writes
    every block; writes past the prefix land in the garbage page exactly
    like released rows' stale writes, and `ensure` maps real pages ahead
    of decode as usual). Resume rows never share prefix-cache pages: the
    dispatch rewrites every mapped page, and a row's own mid-decode K/V
    must not overwrite content other rows map (the host allocates fresh
    pages — `PagedKVManager.admit_resume`).
    """
    texts = jnp.asarray(texts, jnp.int32)
    prefill_batch = int(texts.shape[0])
    page_rows = jnp.asarray(page_rows, jnp.int32)
    n_pages_row = int(page_rows.shape[1])
    return _jit_sample(
        _resume_slots_paged_builder, model,
        (prefill_batch, int(page_size), n_pages_row),
        variables, state, texts,
        jnp.asarray(img_tokens, jnp.int32), jnp.asarray(img_pos, jnp.int32),
        jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
        jnp.asarray(temperatures, jnp.float32), jnp.asarray(keep_ks, jnp.int32),
        page_rows,
    )


def _resume_slots_paged_builder(model, key):
    prefill_batch, page_size, n_pages_row = key
    batch_axis = 1 if model.executor == "scan" else 0

    def block_of(p_leaf, r, j, last_axis=False):
        """Row r's K/V slice for block j, zero-padded to page_size past
        the resume cache's end (static shapes throughout). `last_axis`
        addresses scale leaves (sequence axis LAST)."""
        row_kv = p_leaf[:, r] if batch_axis == 1 else p_leaf[r]
        seq_ax = row_kv.ndim - (1 if last_axis else 2)
        max_len = row_kv.shape[seq_ax]
        lo = j * page_size
        hi = min(lo + page_size, max_len)
        if hi <= lo:
            shape = list(row_kv.shape)
            shape[seq_ax] = page_size
            return jnp.zeros(shape, row_kv.dtype)
        blk = jax.lax.slice_in_dim(row_kv, lo, hi, axis=seq_ax)
        if hi - lo < page_size:
            pad = [(0, 0)] * row_kv.ndim
            pad[seq_ax] = (0, page_size - (hi - lo))
            blk = jnp.pad(blk, pad)
        return blk

    def fn(variables, state, texts, img_tokens, img_pos, slots, seeds,
           temperatures, keep_ks, page_rows):
        rows, cache_r = model.apply(
            variables,
            texts,
            img_tokens,
            img_pos,
            init_decode_cache(model, prefill_batch),
            method=DALLE.decode_resume,
        )

        def write(path, s_leaf, p_leaf):
            key_ = getattr(path[-1], "key", None)
            if key_ == "index":
                return s_leaf
            if key_ in ("k", "v", "k_scale", "v_scale"):
                last_axis = key_.endswith("_scale")
                out = s_leaf
                for r in range(prefill_batch):
                    for j in range(n_pages_row):
                        blk = block_of(p_leaf, r, j, last_axis).astype(
                            out.dtype
                        )
                        if batch_axis == 1:
                            idx = (0, page_rows[r, j]) + (0,) * (out.ndim - 2)
                            out = jax.lax.dynamic_update_slice(
                                out, blk[:, None], idx
                            )
                        else:
                            idx = (page_rows[r, j],) + (0,) * (out.ndim - 1)
                            out = jax.lax.dynamic_update_slice(
                                out, blk[None], idx
                            )
                return out
            # shift rings: per-slot row scatter, same as the slotted path
            out = s_leaf
            for r in range(prefill_batch):
                p_row = jax.lax.dynamic_slice_in_dim(
                    p_leaf, r, 1, axis=batch_axis
                )
                out = jax.lax.dynamic_update_slice_in_dim(
                    out, p_row.astype(out.dtype), slots[r], axis=batch_axis
                )
            return out

        new_cache = jax.tree_util.tree_map_with_path(
            write, state["cache"], cache_r
        )
        out = dict(state)
        out["cache"] = new_cache
        row_buf = state["row"]
        tok_buf = state["img_tokens"]
        for r in range(prefill_batch):
            row_buf = jax.lax.dynamic_update_slice(
                row_buf, rows[r : r + 1].astype(row_buf.dtype), (slots[r], 0)
            )
            tok_buf = jax.lax.dynamic_update_slice(
                tok_buf, img_tokens[r : r + 1], (slots[r], 0)
            )
        out["row"] = row_buf
        out["img_tokens"] = tok_buf
        out["img_pos"] = state["img_pos"].at[slots].set(img_pos)
        out["active"] = state["active"].at[slots].set(True)
        out["seeds"] = state["seeds"].at[slots].set(seeds)
        out["temps"] = state["temps"].at[slots].set(temperatures)
        out["keep_k"] = state["keep_k"].at[slots].set(keep_ks)
        return out

    return fn


_resume_slots_paged_builder._donate_argnums = (1,)  # state


def slice_prefix_sidecar(model: DALLE, sidecar: dict, r: int):
    """Row `r` of a batched prefill sidecar (all leaves are row-major) —
    ONE compiled program per sidecar structure, so registering a prefix on
    a warm server never compiles."""
    return _jit_sample(
        _slice_sidecar_builder, model, (), sidecar, jnp.int32(r)
    )


def _slice_sidecar_builder(model, key):
    del model, key

    def fn(sidecar, r):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(a, r, axis=0, keepdims=False),
            sidecar,
        )

    return fn


def admit_cached_prefix(
    model: DALLE,
    state: dict,
    slot: int,
    sidecar: dict,
    seed,
    temperature,
    keep_k,
    partial_src,
    partial_dst,
    page_size: int,
):
    """Admit a FULL prefix-cache hit into `slot` with zero transformer
    dispatches: the prefix's K/V pages are already mapped into the row's
    page table by the host; this op restores the non-page-addressable
    remainder — pending logits + shift rings from the cached sidecar, the
    per-slot sampling params — and copy-on-writes the divergence block
    (`partial_src` = the cache's immutable snapshot page, `partial_dst` =
    the row's private copy the decode will mutate; configs whose text
    prefix ends exactly on a page boundary skip the copy statically).

    `state` is DONATED — replace your reference with the return value.
    """
    return _jit_sample(
        _admit_prefix_builder, model, (int(page_size),),
        state, jnp.int32(slot), sidecar,
        jnp.int32(seed), jnp.float32(temperature), jnp.int32(keep_k),
        jnp.int32(partial_src), jnp.int32(partial_dst),
    )


def _admit_prefix_builder(model, key):
    (page_size,) = key
    batch_axis = 1 if model.executor == "scan" else 0
    page_axis = batch_axis  # pages leaf: optional depth axis, then pages
    has_partial = (model.text_seq_len + 1) % page_size != 0

    def fn(state, slot, sidecar, seed, temperature, keep_k,
           partial_src, partial_dst):
        rings = sidecar["rings"]

        def upd(path, leaf):
            key_ = getattr(path[-1], "key", None)
            if key_ in ("k", "v", "k_scale", "v_scale"):
                if not has_partial:
                    return leaf
                blk = jax.lax.dynamic_slice_in_dim(
                    leaf, partial_src, 1, axis=page_axis
                )
                return jax.lax.dynamic_update_slice_in_dim(
                    leaf, blk, partial_dst, axis=page_axis
                )
            if key_ in ("shift_attn", "shift_ff"):
                node = rings
                for p in path:
                    node = node[p.key]
                return jax.lax.dynamic_update_slice_in_dim(
                    leaf,
                    jnp.expand_dims(node, batch_axis).astype(leaf.dtype),
                    slot,
                    axis=batch_axis,
                )
            return leaf  # index: stamped from img_pos every chunk

        out = dict(state)
        out["cache"] = jax.tree_util.tree_map_with_path(
            upd, state["cache"]
        )
        out["row"] = jax.lax.dynamic_update_slice(
            state["row"],
            sidecar["row"][None].astype(state["row"].dtype),
            (slot, 0),
        )
        out["img_tokens"] = jax.lax.dynamic_update_slice(
            state["img_tokens"],
            jnp.zeros((1, model.image_seq_len), jnp.int32),
            (slot, 0),
        )
        out["img_pos"] = state["img_pos"].at[slot].set(0)
        out["active"] = state["active"].at[slot].set(True)
        out["seeds"] = state["seeds"].at[slot].set(seed)
        out["temps"] = state["temps"].at[slot].set(temperature)
        out["keep_k"] = state["keep_k"].at[slot].set(keep_k)
        return out

    return fn


_admit_prefix_builder._donate_argnums = (0,)  # state


def decode_image_chunk_paged(
    model: DALLE, variables, state: dict, chunk: int, page_table,
    block_bitmap=None,
):
    """Paged-layout chunk step: identical decode semantics to
    `decode_image_chunk` (one shared program body — see `_make_chunk_fn`),
    with every row's K/V reads and writes indirected through `page_table`
    [max_batch, n_pages] (host numpy, traced data: ONE compiled program no
    matter which pages are mapped). `state` is DONATED; the page table is
    not (it is host-owned and tiny). `block_bitmap` arms policy sparsity
    exactly as in `decode_image_chunk` — on this layout the table-gated
    paged kernels skip dead PAGES through the same indirection."""
    if block_bitmap is None:
        return _jit_sample(
            _chunk_paged_builder, model, (int(chunk),),
            variables, state, jnp.asarray(page_table, jnp.int32),
        )
    return _jit_sample(
        _chunk_paged_builder, model, (int(chunk), "sparse"),
        variables, state, jnp.asarray(page_table, jnp.int32),
        jnp.asarray(block_bitmap, jnp.int32),
    )


def _chunk_paged_builder(model, key):
    chunk = key[0]
    return _make_chunk_fn(model, chunk, paged=True, sparse="sparse" in key)


_chunk_paged_builder._donate_argnums = (1,)  # state


def forward_with_cond_scale(
    model: DALLE, variables, text, image, cond_scale: float = 1.0, rngs=None
):
    """Two-forward classifier-free-guidance blend (`dalle_pytorch.py:575-585`)."""
    logits = model.apply(variables, text, image, rngs=rngs)
    if cond_scale == 1:
        return logits
    null_rngs = dict(rngs or {})
    null_rngs["null_cond"] = jax.random.PRNGKey(0)  # prob=1 -> rng irrelevant
    null_logits = model.apply(
        variables, text, image, null_cond_prob=1.0, rngs=null_rngs
    )
    return null_logits + (logits - null_logits) * cond_scale


def generate_images(
    model: DALLE,
    variables,
    rng: jax.Array,
    text: jnp.ndarray,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    cond_scale: float = 1.0,
    init_image_tokens: Optional[jnp.ndarray] = None,
    num_init_img_tokens: Optional[int] = None,
):
    """Jit-cached wrapper over the full-reforward sampling oracle."""
    static_key = (filter_thres, temperature, cond_scale, num_init_img_tokens)
    if init_image_tokens is None:
        return _jit_sample(
            _full_sampler_builder, model, static_key, variables, rng, text
        )
    return _jit_sample(
        _full_sampler_builder, model, static_key,
        variables, rng, text, init_image_tokens,
    )


def _full_sampler_builder(model, key):
    filter_thres, temperature, cond_scale, num_init = key

    def fn(variables, rng, text, init_image_tokens=None):
        return _generate_images_impl(
            model, variables, rng, text,
            filter_thres=filter_thres, temperature=temperature,
            cond_scale=cond_scale,
            init_image_tokens=init_image_tokens,
            num_init_img_tokens=num_init,
        )

    return fn


def _generate_images_impl(
    model: DALLE,
    variables,
    rng: jax.Array,
    text: jnp.ndarray,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
    cond_scale: float = 1.0,
    init_image_tokens: Optional[jnp.ndarray] = None,
    num_init_img_tokens: Optional[int] = None,
):
    """Autoregressively sample image codebook indices for `text`.

    Equivalent of `DALLE.generate_images` (`dalle_pytorch.py:517-567`) up to
    VAE decode, which the caller applies to the returned [B, image_seq_len]
    indices. Priming follows the reference's 43.75% default (`:542`).

    Implementation: `lax.scan` over image positions; each step runs a full
    forward over the fixed-shape token buffer (causality makes the suffix
    garbage irrelevant). This path is the correctness oracle for the
    KV-cached fast path, `generate_images_cached`, which is what production
    callers should use.
    """
    b = text.shape[0]
    image_seq_len = model.image_seq_len
    img_tokens, primed = _primed_image_tokens(
        model, b, init_image_tokens, num_init_img_tokens
    )

    def step(carry, i):
        img_tokens, rng = carry
        rng, sample_rng = jax.random.split(rng)
        logits = forward_with_cond_scale(
            model, variables, text, img_tokens, cond_scale=cond_scale
        )
        pos_logits = logits[:, model.text_seq_len + i]
        filtered = top_k_filter(pos_logits, thres=filter_thres)
        sample = gumbel_sample(sample_rng, filtered, temperature=temperature)
        sample = (sample - model.total_text_tokens).astype(jnp.int32)
        keep = i < primed
        prev = jax.lax.dynamic_index_in_dim(img_tokens, i, axis=1, keepdims=False)
        new = jnp.where(keep, prev, sample)
        img_tokens = jax.lax.dynamic_update_slice(img_tokens, new[:, None], (0, i))
        return (img_tokens, rng), None

    (img_tokens, _), _ = jax.lax.scan(
        step, (img_tokens, rng), jnp.arange(image_seq_len)
    )
    return img_tokens


def generate_texts(
    model: DALLE,
    variables,
    rng: jax.Array,
    text_prefix: jnp.ndarray,
    prefix_len: int,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
):
    """Jit-cached wrapper over autoregressive text completion.

    `prefix_len` is passed as a traced argument (it only feeds an `i <
    prefix_len` comparison), so varying prompt lengths reuse one compile.
    """
    static_key = (filter_thres, temperature)
    return _jit_sample(
        _text_sampler_builder, model, static_key,
        variables, rng, text_prefix, jnp.int32(prefix_len),
    )


def _text_sampler_builder(model, key):
    filter_thres, temperature = key

    def fn(variables, rng, text_prefix, prefix_len):
        return _generate_texts_impl(
            model, variables, rng, text_prefix, prefix_len,
            filter_thres=filter_thres, temperature=temperature,
        )

    return fn


def _generate_texts_impl(
    model: DALLE,
    variables,
    rng: jax.Array,
    text_prefix: jnp.ndarray,
    prefix_len: int,
    filter_thres: float = 0.5,
    temperature: float = 1.0,
):
    """Autoregressive text completion (`dalle_pytorch.py:470-515`).

    text_prefix: [B, text_seq_len] with ids after position `prefix_len`
    ignored/overwritten. Returns [B, text_seq_len] token ids.

    Note: a sampled id 0 is treated as padding on subsequent steps (the
    unique-pad remap applies to it, and decoding strips it) — consistent
    with the training distribution, where a raw 0 never appears
    mid-sequence; the model sampling 0 means "end of caption".
    """

    def step(carry, i):
        text, rng = carry
        rng, sample_rng = jax.random.split(rng)
        logits = model.apply(variables, text)  # image part absent
        pos_logits = logits[:, i]  # position i predicts text token i (bos shift)
        filtered = top_k_filter(pos_logits, thres=filter_thres)
        sample = gumbel_sample(sample_rng, filtered, temperature=temperature).astype(
            jnp.int32
        )
        keep = i < prefix_len
        prev = jax.lax.dynamic_index_in_dim(text, i, axis=1, keepdims=False)
        new = jnp.where(keep, prev, sample)
        text = jax.lax.dynamic_update_slice(text, new[:, None], (0, i))
        return (text, rng), None

    (text, _), _ = jax.lax.scan(
        step, (text_prefix.astype(jnp.int32), rng), jnp.arange(model.text_seq_len)
    )
    return text
