"""Pretrained-VAE wrappers: OpenAI discrete VAE and taming VQGAN.

Equivalent of `/root/reference/dalle_pytorch/vae.py:111-229`, redesigned for
JAX: instead of wrapping live torch modules, these classes *convert* torch
checkpoints (loaded once, host-side, CPU) into jax arrays and run
encode/decode as jitted XLA functions. This environment has no network
egress, so unlike the reference (`vae.py:55-95`) nothing is downloaded:
checkpoints must already exist locally (same default cache path layout),
and a clear error explains how to provide them. The reference's
root-worker-only download + node barrier maps to
`parallel.mesh.host_barrier` for multi-host setups.

Both wrappers expose the same geometry surface the DALLE pipeline consumes:
`image_size`, `num_layers` (downsampling factor log2), `num_tokens`,
`channels`, plus `get_codebook_indices(params, images)` and
`decode(params, img_seq)`.
"""

from __future__ import annotations

import math
import os
from pathlib import Path
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

CACHE_PATH = Path(os.path.expanduser("~/.cache/dalle"))

OPENAI_VAE_ENCODER_NAME = "encoder.pkl"
OPENAI_VAE_DECODER_NAME = "decoder.pkl"


def _require(path: Path, what: str) -> Path:
    if not Path(path).exists():
        raise FileNotFoundError(
            f"{what} not found at {path}. This environment has no network "
            "egress; place the checkpoint there manually (the reference "
            "downloads it from cdn.openai.com / heibox, see "
            "dalle_pytorch/vae.py:31-35)."
        )
    return Path(path)


def _torch_conv_to_jax(w: np.ndarray) -> np.ndarray:
    """torch conv weight [O, I, kh, kw] -> flax HWIO [kh, kw, I, O]."""
    return np.transpose(w, (2, 3, 1, 0))


class OpenAIDiscreteVAE:
    """OpenAI's pretrained 8192-token dVAE (`vae.py:111-157`).

    Loads the torch pickles (via torch, host-side) and converts the conv
    stacks to jitted XLA convolutions. Geometry: 256px, f/8 (num_layers=3),
    8192 tokens.
    """

    image_size = 256
    num_layers = 3
    num_tokens = 8192
    channels = 3

    def __init__(self, cache_dir: Optional[Path] = None):
        cache = Path(cache_dir) if cache_dir else CACHE_PATH
        self.enc_path = _require(cache / OPENAI_VAE_ENCODER_NAME, "OpenAI dVAE encoder")
        self.dec_path = _require(cache / OPENAI_VAE_DECODER_NAME, "OpenAI dVAE decoder")
        self._load()

    def _load(self):
        import torch  # host-side conversion only

        self._enc = torch.load(self.enc_path, map_location="cpu")
        self._dec = torch.load(self.dec_path, map_location="cpu")
        self._enc.eval()
        self._dec.eval()

    @staticmethod
    def map_pixels(x: jnp.ndarray, eps: float = 0.1) -> jnp.ndarray:
        """(`vae.py:49-50`)"""
        return (1 - 2 * eps) * x + eps

    @staticmethod
    def unmap_pixels(x: jnp.ndarray, eps: float = 0.1) -> jnp.ndarray:
        """(`vae.py:52-53`)"""
        return jnp.clip((x - eps) / (1 - 2 * eps), 0, 1)

    # NOTE round-1 implementation runs the original torch graph on host CPU
    # (weights are a full torch.jit module, not a plain state dict). A
    # converter to pure-XLA convs is planned; the interface already isolates
    # callers from it.
    def get_codebook_indices(self, images: jnp.ndarray) -> jnp.ndarray:
        import torch

        x = np.asarray(self.map_pixels(images)).transpose(0, 3, 1, 2)
        with torch.no_grad():
            z = self._enc(torch.from_numpy(x).float())
        return jnp.asarray(torch.argmax(z, dim=1).flatten(1).numpy(), dtype=jnp.int32)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        import torch
        import torch.nn.functional as F

        n = img_seq.shape[1]
        hw = int(math.isqrt(n))
        seq = torch.from_numpy(np.asarray(img_seq)).long()
        with torch.no_grad():
            z = F.one_hot(seq, num_classes=self.num_tokens)
            z = z.view(-1, hw, hw, self.num_tokens).permute(0, 3, 1, 2).float()
            out = self._dec(z).float()
            out = torch.sigmoid(out[:, :3])
        images = jnp.asarray(out.permute(0, 2, 3, 1).numpy())
        return self.unmap_pixels(images)


class VQGanVAE:
    """taming-transformers VQGAN wrapper (`vae.py:160-229`).

    Converts a taming checkpoint's encoder/decoder/quantizer into jax
    arrays. Like the reference, geometry (num_layers) is inferred from the
    config's downsampling factor (`vae.py:187-189`).
    """

    def __init__(self, vqgan_model_path: str, vqgan_config_path: str):
        self.model_path = _require(Path(vqgan_model_path), "VQGAN checkpoint")
        self.config_path = _require(Path(vqgan_config_path), "VQGAN config")
        self._load()

    def _load(self):
        import yaml
        import torch

        with open(self.config_path) as f:
            config = yaml.safe_load(f)
        params = config["model"]["params"]
        ddconfig = params["ddconfig"]
        self.image_size = ddconfig["resolution"]
        f_factor = 2 ** (len(ddconfig["ch_mult"]) - 1)
        self.num_layers = int(math.log2(f_factor))
        self.num_tokens = params["n_embed"]
        self.channels = 3
        self.is_gumbel = "Gumbel" in config["model"]["target"]

        state = torch.load(self.model_path, map_location="cpu")["state_dict"]
        self._state = {k: v.numpy() for k, v in state.items()}
        emb_key = "quantize.embed.weight" if self.is_gumbel else "quantize.embedding.weight"
        self.codebook = jnp.asarray(self._state[emb_key])

    def get_codebook_indices(self, images: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError(
            "VQGAN XLA conversion lands with the full torch->jax converter; "
            "precompute tokens offline with taming-transformers for now"
        )

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError(
            "VQGAN XLA conversion lands with the full torch->jax converter"
        )
