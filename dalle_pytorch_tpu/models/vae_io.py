"""Pretrained-VAE wrappers: OpenAI discrete VAE and taming VQGAN.

Equivalent of `/root/reference/dalle_pytorch/vae.py:111-229`, redesigned for
JAX: instead of wrapping live torch modules, these classes *convert* torch
checkpoints (loaded once, host-side, CPU) into jax arrays and run
encode/decode as jitted XLA functions. This environment has no network
egress, so unlike the reference (`vae.py:55-95`) nothing is downloaded:
checkpoints must already exist locally (same default cache path layout),
and a clear error explains how to provide them. The reference's
root-worker-only download + node barrier maps to
`parallel.mesh.host_barrier` for multi-host setups.

Both wrappers expose the same geometry surface the DALLE pipeline consumes:
`image_size`, `num_layers` (downsampling factor log2), `num_tokens`,
`channels`, plus `get_codebook_indices(params, images)` and
`decode(params, img_seq)`.
"""

from __future__ import annotations

import math
import os
from pathlib import Path
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

CACHE_PATH = Path(os.path.expanduser("~/.cache/dalle"))

OPENAI_VAE_ENCODER_NAME = "encoder.pkl"
OPENAI_VAE_DECODER_NAME = "decoder.pkl"


def _require(path: Path, what: str) -> Path:
    if not Path(path).exists():
        raise FileNotFoundError(
            f"{what} not found at {path}. This environment has no network "
            "egress; place the checkpoint there manually (the reference "
            "downloads it from cdn.openai.com / heibox, see "
            "dalle_pytorch/vae.py:31-35)."
        )
    return Path(path)


def _torch_conv_to_jax(w: np.ndarray) -> np.ndarray:
    """torch conv weight [O, I, kh, kw] -> flax HWIO [kh, kw, I, O]."""
    return np.transpose(w, (2, 3, 1, 0))


# --------------------------------------------------------------------------
# Pure-XLA VQGAN graph (taming-transformers architecture), evaluated
# directly against the converted torch state dict. Layout is NHWC
# throughout (TPU-native); torch OIHW conv kernels are transposed at load.
# Mirrors the modules the reference drives through taming
# (`/root/reference/dalle_pytorch/vae.py:160-229`): Encoder/Decoder stacks
# of GroupNorm+swish ResnetBlocks with optional spatial attention,
# stride-2 downsampling with (0,1,0,1) padding, nearest-neighbour 2x
# upsampling, and a nearest-codebook (or Gumbel argmax) quantizer.
# --------------------------------------------------------------------------


def _swish(x):
    return x * jax.nn.sigmoid(x)


class _VQGraph:
    """Functional VQGAN evaluator over a flat {torch_key: array} dict."""

    # prefixes the inference graph actually reads; taming checkpoints also
    # carry GAN-discriminator / LPIPS weights under `loss.*` that would
    # otherwise waste HBM
    _USED_PREFIXES = (
        "encoder.", "decoder.", "quantize.", "quant_conv.", "post_quant_conv.",
    )

    def __init__(self, state: dict, ddconfig: dict, num_tokens: int, is_gumbel: bool):
        self.ddconfig = ddconfig
        self.num_tokens = num_tokens
        self.is_gumbel = is_gumbel
        # convert once: conv kernels to HWIO jnp arrays, the rest as-is.
        # Params live in this dict and are passed to the graph methods
        # explicitly, so jit treats them as arguments (not baked constants).
        self.p = {}
        for k, v in state.items():
            if not k.startswith(self._USED_PREFIXES):
                continue
            v = np.asarray(v)
            if k.endswith("weight") and v.ndim == 4:
                v = _torch_conv_to_jax(v)
            self.p[k] = jnp.asarray(v)

    def _has(self, key):
        return f"{key}.weight" in self.p

    def _conv(self, p, key, x, stride=1, pad="SAME"):
        w = p[f"{key}.weight"]
        out = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype),
            window_strides=(stride, stride),
            padding=pad if isinstance(pad, str) else pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        b = p.get(f"{key}.bias")
        return out if b is None else out + b.astype(x.dtype)

    def _norm(self, p, key, x, groups=32, eps=1e-6):
        b, h, w, c = x.shape
        xg = x.reshape(b, h, w, groups, c // groups)
        mean = xg.mean(axis=(1, 2, 4), keepdims=True)
        var = ((xg - mean) ** 2).mean(axis=(1, 2, 4), keepdims=True)
        xg = (xg - mean) * jax.lax.rsqrt(var + eps)
        x = xg.reshape(b, h, w, c)
        return x * p[f"{key}.weight"] + p[f"{key}.bias"]

    def _resnet(self, p, key, x):
        h = self._conv(p, f"{key}.conv1", _swish(self._norm(p, f"{key}.norm1", x)))
        h = self._conv(p, f"{key}.conv2", _swish(self._norm(p, f"{key}.norm2", h)))
        if self._has(f"{key}.nin_shortcut"):
            x = self._conv(p, f"{key}.nin_shortcut", x)
        elif self._has(f"{key}.conv_shortcut"):
            x = self._conv(p, f"{key}.conv_shortcut", x)
        return x + h

    def _attn(self, p, key, x):
        b, hh, ww, c = x.shape
        h = self._norm(p, f"{key}.norm", x)
        q = self._conv(p, f"{key}.q", h).reshape(b, hh * ww, c)
        k = self._conv(p, f"{key}.k", h).reshape(b, hh * ww, c)
        v = self._conv(p, f"{key}.v", h).reshape(b, hh * ww, c)
        attn = jax.nn.softmax(
            jnp.einsum("bqc,bkc->bqk", q, k) * (c ** -0.5), axis=-1
        )
        out = jnp.einsum("bqk,bkc->bqc", attn, v).reshape(b, hh, ww, c)
        return x + self._conv(p, f"{key}.proj_out", out)

    # ------------------------------------------------------------ encoder

    def encode_z(self, p, x):
        """images NHWC in [-1, 1] -> latent grid [B, h, w, z]."""
        dd = self.ddconfig
        ch_mult = tuple(dd["ch_mult"])
        num_res = dd["num_res_blocks"]
        attn_res = set(dd.get("attn_resolutions", []))
        cur_res = dd["resolution"]

        h = self._conv(p, "encoder.conv_in", x)
        for i in range(len(ch_mult)):
            for j in range(num_res):
                h = self._resnet(p, f"encoder.down.{i}.block.{j}", h)
                if cur_res in attn_res:
                    h = self._attn(p, f"encoder.down.{i}.attn.{j}", h)
            if i != len(ch_mult) - 1:
                # taming Downsample: pad (left 0, right 1, top 0, bottom 1),
                # stride-2 valid conv
                h = self._conv(
                    p, f"encoder.down.{i}.downsample.conv",
                    h, stride=2, pad=[(0, 1), (0, 1)],
                )
                cur_res //= 2
        h = self._resnet(p, "encoder.mid.block_1", h)
        h = self._attn(p, "encoder.mid.attn_1", h)
        h = self._resnet(p, "encoder.mid.block_2", h)
        h = self._conv(p, "encoder.conv_out", _swish(self._norm(p, "encoder.norm_out", h)))
        if self._has("quant_conv"):
            h = self._conv(p, "quant_conv", h)
        return h

    def quantize_indices(self, p, z):
        """latent grid -> flat codebook indices [B, h*w]."""
        b, h, w, c = z.shape
        if self.is_gumbel:
            # GumbelQuantize hard path: argmax of the projection logits
            logits = self._conv(p, "quantize.proj", z)
            return jnp.argmax(logits, axis=-1).reshape(b, h * w).astype(jnp.int32)
        emb = p["quantize.embedding.weight"]  # [n, c]
        flat = z.reshape(-1, c)
        d = (
            (flat ** 2).sum(-1, keepdims=True)
            - 2 * flat @ emb.T
            + (emb ** 2).sum(-1)[None, :]
        )
        return jnp.argmin(d, axis=-1).reshape(b, h * w).astype(jnp.int32)

    # ------------------------------------------------------------ decoder

    def decode_indices(self, p, indices):
        """flat indices [B, n] -> images NHWC in [0, 1]."""
        dd = self.ddconfig
        emb_key = "quantize.embed.weight" if self.is_gumbel else "quantize.embedding.weight"
        emb = p[emb_key]
        b, n = indices.shape
        hw = int(math.isqrt(n))
        z = emb[indices].reshape(b, hw, hw, emb.shape[-1])

        ch_mult = tuple(dd["ch_mult"])
        num_res = dd["num_res_blocks"]
        attn_res = set(dd.get("attn_resolutions", []))
        cur_res = dd["resolution"] // 2 ** (len(ch_mult) - 1)

        if self._has("post_quant_conv"):
            z = self._conv(p, "post_quant_conv", z)
        h = self._conv(p, "decoder.conv_in", z)
        h = self._resnet(p, "decoder.mid.block_1", h)
        h = self._attn(p, "decoder.mid.attn_1", h)
        h = self._resnet(p, "decoder.mid.block_2", h)
        for i in reversed(range(len(ch_mult))):
            for j in range(num_res + 1):
                h = self._resnet(p, f"decoder.up.{i}.block.{j}", h)
                if cur_res in attn_res:
                    h = self._attn(p, f"decoder.up.{i}.attn.{j}", h)
            if i != 0:
                # taming Upsample: nearest 2x then 3x3 conv
                bb, hh, ww, cc = h.shape
                h = jnp.broadcast_to(
                    h[:, :, None, :, None, :], (bb, hh, 2, ww, 2, cc)
                ).reshape(bb, hh * 2, ww * 2, cc)
                h = self._conv(p, f"decoder.up.{i}.upsample.conv", h)
                cur_res *= 2
        h = self._conv(p, "decoder.conv_out", _swish(self._norm(p, "decoder.norm_out", h)))
        # reference clamps to [-1,1] then rescales to [0,1] (`vae.py:226-228`)
        return (jnp.clip(h, -1.0, 1.0) + 1.0) * 0.5


# --------------------------------------------------------------------------
# Pure-XLA OpenAI dVAE graph. The released encoder.pkl/decoder.pkl
# (`/root/reference/dalle_pytorch/vae.py:31-32,116-140`) carry the dall_e
# package's Encoder/Decoder: an `input` conv, `group_1..group_N` of
# residual blocks (res path = 4 relu+convs scaled by post_gain
# = 1/n_layers², id path = 1x1 conv on width change), maxpool (encoder) /
# nearest-2x upsample (decoder) between groups, and a relu+1x1 `output`
# head. Structure (group/block counts, kernel sizes, widths) is inferred
# from the state dict itself, so any geometry the pickles describe works.
# Layout is NHWC; torch OIHW kernels are transposed once at load.
# --------------------------------------------------------------------------


class _OpenAIGraph:
    """Functional dall_e dVAE evaluator over flat {torch_key: array} dicts."""

    def __init__(self, enc_state: dict, dec_state: dict):
        self.enc = self._convert(enc_state)
        self.dec = self._convert(dec_state)
        self.enc_groups, self.enc_blocks = self._structure(self.enc)
        self.dec_groups, self.dec_blocks = self._structure(self.dec)

    @staticmethod
    def _convert(state: dict) -> dict:
        """Normalize: numpy -> HWIO jnp; accept both dall_e's `.w`/`.b`
        conv param names and standard `.weight`/`.bias`."""
        out = {}
        for k, v in state.items():
            v = np.asarray(v, dtype=np.float32)
            if k.endswith(".weight"):
                k = k[: -len(".weight")] + ".w"
            elif k.endswith(".bias"):
                k = k[: -len(".bias")] + ".b"
            if k.endswith(".w") and v.ndim == 4:
                v = _torch_conv_to_jax(v)
            out[k] = jnp.asarray(v)
        return out

    @staticmethod
    def _structure(p: dict):
        import re

        groups, blocks = 0, 0
        for k in p:
            m = re.search(r"group_(\d+)\.block_(\d+)\.", k)
            if m:
                groups = max(groups, int(m.group(1)))
                blocks = max(blocks, int(m.group(2)))
        assert groups and blocks, "unrecognized dVAE state dict layout"
        return groups, blocks

    @staticmethod
    def _conv(p, key, x, stride=1):
        w = p[f"{key}.w"]
        kh, kw = w.shape[0], w.shape[1]
        out = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype),
            window_strides=(stride, stride),
            padding=[((kh - 1) // 2, kh // 2), ((kw - 1) // 2, kw // 2)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        b = p.get(f"{key}.b")
        return out if b is None else out + b.reshape(-1).astype(x.dtype)

    def _block(self, p, key, x, post_gain):
        """id_path(x) + post_gain * res_path(x) (dall_e Encoder/DecoderBlock)."""
        h = x
        for i in (1, 2, 3, 4):
            h = self._conv(p, f"{key}.res_path.conv_{i}", jax.nn.relu(h))
        if f"{key}.id_path.w" in p:
            x = self._conv(p, f"{key}.id_path", x)
        return x + post_gain * h

    def encode_logits(self, p, x):
        """pixel-mapped images NHWC -> token logits [B, h, w, vocab]."""
        post_gain = 1.0 / (self.enc_groups * self.enc_blocks) ** 2
        h = self._conv(p, "blocks.input", x)
        for g in range(1, self.enc_groups + 1):
            for blk in range(1, self.enc_blocks + 1):
                h = self._block(p, f"blocks.group_{g}.block_{blk}", h, post_gain)
            if g != self.enc_groups:  # MaxPool2d(2) between groups
                h = jax.lax.reduce_window(
                    h, -jnp.inf, jax.lax.max,
                    (1, 2, 2, 1), (1, 2, 2, 1), "VALID",
                )
        return self._conv(p, "blocks.output.conv", jax.nn.relu(h))

    def decode_pixels(self, p, indices):
        """flat indices [B, n] -> raw decoder output NHWC (pre-sigmoid)."""
        # input 1x1 conv on a one-hot == embedding gather of its kernel:
        # O(n·c) instead of an 8192-wide matmul per position
        w = p["blocks.input.w"]  # [1, 1, vocab, n_init]
        emb = w.reshape(w.shape[2], w.shape[3])
        b, n = indices.shape
        hw = int(math.isqrt(n))
        h = emb[indices].reshape(b, hw, hw, -1) + p["blocks.input.b"].reshape(-1)
        post_gain = 1.0 / (self.dec_groups * self.dec_blocks) ** 2
        for g in range(1, self.dec_groups + 1):
            for blk in range(1, self.dec_blocks + 1):
                h = self._block(p, f"blocks.group_{g}.block_{blk}", h, post_gain)
            if g != self.dec_groups:  # Upsample(scale 2, nearest)
                bb, hh, ww, cc = h.shape
                h = jnp.broadcast_to(
                    h[:, :, None, :, None, :], (bb, hh, 2, ww, 2, cc)
                ).reshape(bb, hh * 2, ww * 2, cc)
        return self._conv(p, "blocks.output.conv", jax.nn.relu(h))


class OpenAIDiscreteVAE:
    """OpenAI's pretrained 8192-token dVAE (`vae.py:111-157`).

    Loads the torch pickles ONCE (host-side) into plain arrays and runs
    encode/decode as jitted XLA graphs — no torch in the hot path, so the
    in-train-step frozen-VAE encode (`dalle_pytorch.py:619-627`) stays on
    chip. Geometry: 256px, f/8 (num_layers=3), 8192 tokens.
    """

    image_size = 256
    num_layers = 3
    num_tokens = 8192
    channels = 3

    def __init__(self, cache_dir: Optional[Path] = None):
        cache = Path(cache_dir) if cache_dir else CACHE_PATH
        self.enc_path = _require(cache / OPENAI_VAE_ENCODER_NAME, "OpenAI dVAE encoder")
        self.dec_path = _require(cache / OPENAI_VAE_DECODER_NAME, "OpenAI dVAE decoder")
        self._load()

    @staticmethod
    def _state_dict(obj) -> dict:
        """torch pickles may hold a module (dall_e classes / jit script) or
        a bare state dict; normalize to {key: numpy}."""
        if hasattr(obj, "state_dict"):
            obj = obj.state_dict()
        return {k: np.asarray(v.cpu() if hasattr(v, "cpu") else v)
                for k, v in obj.items()}

    def _load(self):
        import torch  # host-side, load-time only

        enc = torch.load(self.enc_path, map_location="cpu")
        dec = torch.load(self.dec_path, map_location="cpu")
        self._graph = _OpenAIGraph(self._state_dict(enc), self._state_dict(dec))
        del enc, dec
        g = self._graph
        # geometry from the pickles themselves (the class defaults describe
        # the released 256px/f8/8192 model; synthetic/test pickles differ)
        self.num_tokens = int(g.enc["blocks.output.conv.w"].shape[-1])
        self.num_layers = g.enc_groups - 1  # one maxpool between groups
        self._encode_jit = jax.jit(
            lambda p, x: jnp.argmax(g.encode_logits(p, x), axis=-1)
        )
        self._decode_jit = jax.jit(g.decode_pixels)

    @staticmethod
    def map_pixels(x: jnp.ndarray, eps: float = 0.1) -> jnp.ndarray:
        """(`vae.py:49-50`)"""
        return (1 - 2 * eps) * x + eps

    @staticmethod
    def unmap_pixels(x: jnp.ndarray, eps: float = 0.1) -> jnp.ndarray:
        """(`vae.py:52-53`)"""
        return jnp.clip((x - eps) / (1 - 2 * eps), 0, 1)

    def get_codebook_indices(self, images: jnp.ndarray) -> jnp.ndarray:
        """images NHWC [0,1] -> flat token indices (`vae.py:126-130`)."""
        idx = self._encode_jit(self._graph.enc, self.map_pixels(images))
        return idx.reshape(idx.shape[0], -1).astype(jnp.int32)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        """flat indices -> images NHWC [0,1] (`vae.py:132-140`): sigmoid of
        the first 3 output channels, then unmap_pixels."""
        out = self._decode_jit(self._graph.dec, jnp.asarray(img_seq))
        return self.unmap_pixels(jax.nn.sigmoid(out[..., :3]))


class VQGanVAE:
    """taming-transformers VQGAN wrapper (`vae.py:160-229`).

    Converts a taming checkpoint's encoder/decoder/quantizer into jax
    arrays. Like the reference, geometry (num_layers) is inferred from the
    config's downsampling factor (`vae.py:187-189`).
    """

    def __init__(self, vqgan_model_path: str, vqgan_config_path: str):
        self.model_path = _require(Path(vqgan_model_path), "VQGAN checkpoint")
        self.config_path = _require(Path(vqgan_config_path), "VQGAN config")
        self._load()

    def _load(self):
        import yaml
        import torch

        with open(self.config_path) as f:
            config = yaml.safe_load(f)
        params = config["model"]["params"]
        ddconfig = params["ddconfig"]
        self.ddconfig = ddconfig
        self.image_size = ddconfig["resolution"]
        f_factor = 2 ** (len(ddconfig["ch_mult"]) - 1)
        self.num_layers = int(math.log2(f_factor))
        self.num_tokens = params["n_embed"]
        self.channels = 3
        self.is_gumbel = "Gumbel" in config["model"]["target"]

        state = torch.load(self.model_path, map_location="cpu")["state_dict"]
        state = {k: v.numpy() for k, v in state.items()}
        emb_key = "quantize.embed.weight" if self.is_gumbel else "quantize.embedding.weight"
        self.codebook = jnp.asarray(state[emb_key])
        self._graph = _VQGraph(
            state, self.ddconfig, self.num_tokens, self.is_gumbel
        )
        del state  # drop host copies (incl. GAN/LPIPS `loss.*` weights)
        g = self._graph
        self._encode_jit = jax.jit(lambda p, x: g.quantize_indices(p, g.encode_z(p, x)))
        self._decode_jit = jax.jit(g.decode_indices)

    def get_codebook_indices(self, images: jnp.ndarray) -> jnp.ndarray:
        """images NHWC in [0, 1] -> flat codebook indices (`vae.py:210-217`)."""
        return self._encode_jit(self._graph.p, 2.0 * images - 1.0)

    def decode(self, img_seq: jnp.ndarray) -> jnp.ndarray:
        """flat indices -> images NHWC in [0, 1] (`vae.py:219-229`)."""
        return self._decode_jit(self._graph.p, jnp.asarray(img_seq))
