"""Attention module: one dense MXU-friendly kernel, many static mask patterns.

The reference has four attention classes
(`/root/reference/dalle_pytorch/attention.py:39,103,225,339`): full causal,
conv-like sparse (unfold), axial row/col sparse, and a DeepSpeed CUDA
block-sparse wrapper. On TPU, every one of these is expressed as *dense
attention with a static boolean mask* (see ops/masks.py) — a single fused
einsum chain that XLA tiles onto the MXU; masking is a free epilogue. This
is both simpler and faster than gather-based sparsity at DALL-E sequence
lengths (<= a few thousand tokens); the Pallas flash kernel
(ops/pallas_attention.py) takes over for long sequences — O(N) memory,
static-mask block skipping — selected via `attn_impl` ("auto" switches at
AUTO_FLASH_MIN_SEQ).

Semantics preserved from the reference:
  * rotary embeddings are applied to q, k AND v (`attention.py:67`);
  * optional stable softmax (`attention.py:27-30`);
  * key-padding mask [B, N] (True = valid key);
  * causal mask composed with the per-layer static pattern mask.

The decode-time KV cache is a fixed-shape pytree {k, v, index} with k/v of
shape [B, heads, max_len, dim_head]; causality during cached decode is
enforced by masking positions > index (the reference instead relies on only
having written the prefix, `attention.py:71-76,86`). The cached path has its
own kernel dispatch (`_use_flash_decode`): the Pallas flash-decode kernel
(ops/pallas_decode.py) reads only each row's live KV blocks — per-row
`index` included, the continuous-batching slot cache — with dense attention
over the whole cache as the fallback for pattern masks and small caches.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp
import jax.lax as lax
import flax.linen as nn

from dalle_pytorch_tpu.ops.attention_core import dense_attention
from dalle_pytorch_tpu.ops.pallas_attention import (
    flash_attention,
    lib_flash_attention,
)
from dalle_pytorch_tpu.ops.pallas_decode import (
    block_sparse_flash_decode_attention,
    flash_decode_attention,
    paged_decode_attention,
    paged_gather,
    sharded_flash_decode_attention,
    sharded_paged_decode_attention,
)
from dalle_pytorch_tpu.ops.rotary import apply_rotary

# Sequence length at or above which `attn_impl="auto"` switches from the
# dense einsum to the Pallas flash kernel (O(N) memory vs dense's O(N^2)
# score tensors). MEASURED default (scripts/flash_crossover.py, recorded in
# BASELINE.md §flash-crossover): on the v5e roofline over compiled-program
# cost analysis, dense attention is bandwidth-bound from seq 256 up (score
# chain 212 MB @256 → 4.5 GB @1280 vs flash's tiled 10→137 MB), but
# op-level counting can't resolve the sub-1k region (fusion may keep short
# score chains out of HBM), so the default is the largest bench-grid point
# that still auto-selects flash for the flagship 1280 — where the r3 HBM
# analysis, this measurement, and the r4 hardware run (flash wall == dense
# even under dispatch overhead) all agree. Overridable per model
# (attn_impl=) or by rebinding this constant; the on-chip wall-clock A/B
# (`scripts/pallas_onchip.py`) stays armed as the final decider.
AUTO_FLASH_MIN_SEQ = 1024

# Cache length at or above which `attn_impl="auto"` runs the CACHED decode
# path through the Pallas flash-decode kernel (ops/pallas_decode.py) instead
# of dense attention over the whole [B, H, max_len, D] cache. MEASURED
# (same script/table): one decode step's K/V reads cross at max_len 512 —
# below it the per-kernel overhead charge beats the saved reads at expected
# live length max_len/2; at the flagship cache (1281) flash-decode halves
# the average K/V reads and cuts them ~3x for a freshly-admitted
# continuous-batching slot still at its text prefix.
AUTO_FLASH_DECODE_MIN_LEN = 512

# KV tile width for POLICY-sparse flash decode (the per-row block bitmap in
# ops/pallas_decode.py:block_sparse_flash_decode_attention). MEASURED
# (scripts/flash_crossover.py --sparse sweep, BASELINE.md §block-sparse):
# the skip fraction a policy can express falls with tile width (an axial
# row policy at the flagship cache keeps 48% of 64-wide tiles live but 60%
# of 128-wide and 79% of 256-wide — every tile a single live position
# touches is read whole), while the per-tile grid charge grows as tiles
# shrink: on the v5e roofline a 32-wide sweep is SLOWER than plain
# length-skip flash at 128. 128 is the knee: near-minimal modeled step
# time (25.4 us vs 24.7 at 256) while capturing ~72% of the reachable
# byte savings, and it matches `flash_decode_attention`'s default block_k
# — so the all-ones bitmap keeps BIT-IDENTITY with the dense-causal flash
# path (same tile boundaries, same accumulation order), the serving
# stack's parity pin. Overridable per model (decode_sparse_block=); must
# divide into whole pages on the paged "kernel" impl (page_size | block).
DECODE_SPARSE_BLOCK = 128


def _cache_write(buf: jnp.ndarray, val: jnp.ndarray, index) -> jnp.ndarray:
    """Write val [B,H,n,D] into buf [B,H,S,D] at sequence position `index`
    (n = 1 for single-token decode, larger for prefill chunks).

    `index` is either a scalar (the whole batch sits at one position — the
    micro-batch decode scan) or a [B] vector (each row sits at its OWN
    position — the continuous-batching slot cache, where rows were admitted
    at different times)."""
    if jnp.ndim(index) == 0:
        return lax.dynamic_update_slice(
            buf, val.astype(buf.dtype), (0, 0, index, 0)
        )
    return jax.vmap(
        lambda b, v, i: lax.dynamic_update_slice(
            b, v.astype(b.dtype), (0, i, 0)
        )
    )(buf, val, index)


def _scale_write(buf: jnp.ndarray, val: jnp.ndarray, index) -> jnp.ndarray:
    """`_cache_write` for the per-(position, head) scale leaves: val
    [B,H,n] into buf [B,H,S] at sequence position `index`."""
    if jnp.ndim(index) == 0:
        return lax.dynamic_update_slice(
            buf, val.astype(buf.dtype), (0, 0, index)
        )
    return jax.vmap(
        lambda b, v, i: lax.dynamic_update_slice(b, v.astype(b.dtype), (0, i))
    )(buf, val, index)


def _kv_quantize(x: jnp.ndarray):
    """Symmetric int8 quantization over the head dim: x [B,H,n,D] ->
    (q int8 [B,H,n,D], scale fp32 [B,H,n]).

    fp32 math end to end (quantization error must not depend on the
    cache dtype), eps-clipped so an all-zero row round-trips to zeros
    instead of NaN. Dequant is `q.astype(f32) * scale[..., None]` —
    done INSIDE the decode kernels (ops/pallas_decode.py) so the HBM
    read stays 1 byte/element."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def _kv_dequantize(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale[..., None]


class Attention(nn.Module):
    """Multi-head (optionally causal) attention with a static pattern mask."""

    dim: int
    seq_len: int
    heads: int = 8
    dim_head: int = 64
    causal: bool = True
    dropout: float = 0.0
    stable: bool = False
    static_mask: Optional[np.ndarray] = None  # [S, S] bool, True = attend
    # "dense" | "flash" (in-repo Pallas) | "lib_flash" (jax library TPU
    # kernel; plain causal/full only) | "ring" | "auto"
    attn_impl: str = "auto"
    sp_mesh: Any = None  # Mesh with an "sp" axis, required for attn_impl="ring"
    # serving mesh for the SHARDED flash-decode dispatch: a Pallas call is
    # a single-device program GSPMD cannot partition, so when the sharded
    # continuous engine sets this the cached flash path runs
    # ops/pallas_decode.py:sharded_flash_decode_attention (shard_map over
    # `decode_heads_axis`, heads split — bit-identical to unsharded).
    # The axis must match the one the engine's KV-cache shardings use
    # (ShardedContinuousEngine clones the model with its model_axis).
    decode_mesh: Any = None
    decode_heads_axis: str = "tp"
    # KV tile width the decode-time block bitmap is expressed at (None =
    # DECODE_SPARSE_BLOCK). Static model config: the serving engine clones
    # the model with it when --decode_sparsity=policy, and the policy's
    # host-side bitmap derivation must use the SAME width (the bitmap
    # itself stays traced data — only this boundary is baked into the
    # compiled program).
    decode_sparse_block: Optional[int] = None
    dtype: Any = jnp.float32

    def _use_flash(self, n: int, key_mask) -> bool:
        """Flash path: static masks only (dynamic key-padding stays dense)."""
        if self.attn_impl == "lib_flash":
            if key_mask is not None or self.static_mask is not None:
                raise ValueError(
                    'attn_impl="lib_flash" supports plain causal/full '
                    "attention only (no key-padding or static masks); use "
                    '"flash" or "dense"'
                )
            return True
        if self.attn_impl == "flash":
            if key_mask is not None:
                raise ValueError(
                    'attn_impl="flash" does not support a dynamic key-padding '
                    "mask; encode padding statically or use attn_impl=\"dense\""
                )
            return True
        if self.attn_impl == "dense" or key_mask is not None:
            return False
        return n >= AUTO_FLASH_MIN_SEQ

    def _use_flash_decode(
        self, max_len: int, has_pattern: bool, sparse: bool = False
    ) -> bool:
        """Cached-path dispatch: flash-decode reads only each row's live KV
        blocks (ops/pallas_decode.py); dense reads the whole cache. Pattern
        masks (static or traced) fall back to dense — a per-step row-sliced
        mask cannot drive the kernel's block skip — UNLESS the cache
        carries a policy block bitmap (`sparse`): then the pattern's
        block-level shadow IS the skip structure, and masked rows route
        through the block-sparse flash kernel instead of reading the whole
        cache dense. `attn_impl="flash"` forces the kernel; "auto"
        switches on cache length; "dense"/"lib_flash"/"ring" stay dense
        (the library kernel has no decode analog, and ring is a
        training-time layout)."""
        if has_pattern and not sparse:
            return False
        if self.attn_impl == "flash":
            return True
        if self.attn_impl == "auto":
            return max_len >= AUTO_FLASH_DECODE_MIN_LEN
        return False

    def _full_mask(self, n_q: int, n_k: int) -> Optional[np.ndarray]:
        """Host-side composition of causal + static masks, cropped."""
        mask = None
        if self.causal:
            mask = np.tril(np.ones((n_k, n_k), dtype=bool))[n_k - n_q :, :]
        if self.static_mask is not None:
            sm = np.asarray(self.static_mask)[n_k - n_q : n_k, :n_k]
            mask = sm if mask is None else (mask & sm)
        return mask

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        key_mask: Optional[jnp.ndarray] = None,
        rotary: Optional[jnp.ndarray] = None,
        cache: Optional[dict] = None,
        deterministic: bool = True,
        mask_array: Optional[jnp.ndarray] = None,
    ):
        """`mask_array`: a TRACED [S, S] bool pattern mask (True = attend),
        the per-layer scanned-input analogue of the host-side `static_mask`
        attribute — used by the scan executor, where each layer's pattern
        arrives as data rather than a compile-time constant. Dense paths
        only (a traced mask cannot drive flash's host-side block-occupancy
        skipping); the cached path row-slices it at the decode position
        exactly like `static_mask`."""
        if mask_array is not None:
            assert self.static_mask is None, (
                "pass either the static_mask attribute or mask_array, not both"
            )
            assert self.attn_impl not in ("flash", "lib_flash", "ring"), (
                f'attn_impl="{self.attn_impl}" cannot apply a traced pattern '
                "mask; scan executor uses dense for masked layers"
            )
        b, n, _ = x.shape
        h, dh = self.heads, self.dim_head
        inner = h * dh

        qkv = nn.Dense(inner * 3, use_bias=False, dtype=self.dtype, name="to_qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(b, n, h, dh).transpose(0, 2, 1, 3) for t in (q, k, v))

        new_cache = None
        if cache is not None:
            # n-token chunk (prefill or single-token decode) written into a
            # fixed-shape cache at sequence position `index`. A scalar index
            # means the whole batch decodes in lockstep; a [B] index means
            # per-row positions (continuous-batching slots admitted at
            # different times) — every index-dependent op below (rotary row
            # slice, cache write, causal mask, pattern-mask row slice) then
            # runs per row via vmap, at identical per-row numerics.
            #
            # A cache carrying a "page_table" key is BLOCK-PAGED: k/v are a
            # physical page pool [P, H, page_size, D] shared by all rows
            # and the [B, n_pages] table maps each row's logical blocks to
            # pages (serving/paging.py allocates; released rows point at
            # the reserved garbage page 0, so a stale write can never
            # corrupt a reallocated page). Reads either gather the row's
            # logical view and run the IDENTICAL dense/flash path as the
            # slotted cache (bit-for-bit — the paging parity contract) or
            # stream pages directly through the paged Pallas kernel
            # (ops/pallas_decode.py PAGED_DECODE_IMPL).
            index = cache["index"]
            per_row = jnp.ndim(index) == 1
            paged = "page_table" in cache
            if rotary is not None:
                if per_row:
                    rot = jax.vmap(
                        lambda i: lax.dynamic_slice_in_dim(rotary, i, n, axis=0)
                    )(index)
                    rot = rot[:, None]  # [B,1,n,dr]
                else:
                    rot = lax.dynamic_slice_in_dim(rotary, index, n, axis=0)
                    rot = jnp.expand_dims(rot, (0, 1))  # [1,1,n,dr]
                q, k, v = (apply_rotary(rot, t) for t in (q, k, v))
            # int8 KV cache: quantize AFTER rotary (the cache stores what
            # attention reads), carry per-(position, head) fp32 scales in
            # sibling leaves; q stays full precision
            quant = "k_scale" in cache
            cks = cvs = None
            if quant:
                qk, k_sc = _kv_quantize(k)
                qv, v_sc = _kv_quantize(v)
            else:
                qk, qv = k, v
            if paged:
                assert per_row, "paged caches always carry per-row indices"
                pt = cache["page_table"]
                page_size = cache["k"].shape[2]
                # virtual contiguous length == the slotted cache's max_len
                # (total_seq_len + 1): gather crops to it so dense/flash see
                # byte-identical shapes on both layouts
                max_len = min(pt.shape[-1] * page_size, self.seq_len + 1)
                pos = jnp.minimum(
                    index[:, None] + jnp.arange(n), max_len - 1
                )  # [B, n]; finished rows clamp to the spare slot like the
                # slotted dynamic_update_slice does
                page = jnp.take_along_axis(pt, pos // page_size, axis=1)
                off = pos % page_size
                ck = cache["k"].at[page, :, off, :].set(
                    qk.transpose(0, 2, 1, 3).astype(cache["k"].dtype)
                )
                cv = cache["v"].at[page, :, off, :].set(
                    qv.transpose(0, 2, 1, 3).astype(cache["v"].dtype)
                )
                if quant:
                    cks = cache["k_scale"].at[page, :, off].set(
                        k_sc.transpose(0, 2, 1)
                    )
                    cvs = cache["v_scale"].at[page, :, off].set(
                        v_sc.transpose(0, 2, 1)
                    )
            else:
                ck = _cache_write(cache["k"], qk, index)
                cv = _cache_write(cache["v"], qv, index)
                if quant:
                    cks = _scale_write(cache["k_scale"], k_sc, index)
                    cvs = _scale_write(cache["v_scale"], v_sc, index)
                max_len = ck.shape[2]
            # policy block bitmap ([B, nb] int32, nb = ceil(max_len /
            # decode_sparse_block), nonzero = KV tile may be read): traced
            # DATA riding the cache pytree (models/dalle.py threads it from
            # the serving engine's host-side policy), so flipping or
            # re-deriving the policy NEVER recompiles the chunk program.
            # When present, it supersedes the pattern masks below — the
            # engine derived it FROM those patterns (conservative
            # block-level shadow, text prefix always live), and it unlocks
            # the flash path for pattern-masked rows.
            bitmap = cache.get("block_bitmap")
            sparse = bitmap is not None
            sparse_block = (
                DECODE_SPARSE_BLOCK
                if self.decode_sparse_block is None
                else self.decode_sparse_block
            )
            # mirror the kernel's block_k clamp so bitmap widths agree on
            # tiny caches (tests run seq_len << DECODE_SPARSE_BLOCK)
            sparse_block = max(min(sparse_block, max_len), 1)
            if self._use_flash_decode(
                max_len,
                has_pattern=(
                    self.static_mask is not None or mask_array is not None
                ),
                sparse=sparse,
            ):
                # per-row live length = cache index + this chunk; the kernel
                # applies the same causal-over-prefix mask the dense branch
                # builds below, but reads ONLY each row's live K/V blocks
                # (scalar index = lockstep decode: every row at one length)
                lengths = jnp.broadcast_to(index + n, (b,)).astype(jnp.int32)
                scales = {"k_scale": cks, "v_scale": cvs} if quant else {}
                sparse_kw = (
                    {"block_bitmap": bitmap, "sparse_block": sparse_block}
                    if sparse else {}
                )
                if paged:
                    if self.decode_mesh is not None:
                        out = sharded_paged_decode_attention(
                            self.decode_mesh, q, ck, cv, lengths, pt,
                            max_len, head_axis=self.decode_heads_axis,
                            **scales, **sparse_kw,
                        )
                    else:
                        out = paged_decode_attention(
                            q, ck, cv, lengths, pt, max_len,
                            **scales, **sparse_kw,
                        )
                elif self.decode_mesh is not None:
                    out = sharded_flash_decode_attention(
                        self.decode_mesh, q, ck, cv, lengths,
                        head_axis=self.decode_heads_axis,
                        **scales, **sparse_kw,
                    )
                elif sparse:
                    out = block_sparse_flash_decode_attention(
                        q, ck, cv, lengths, bitmap,
                        block_k=sparse_block, **scales,
                    )
                else:
                    out = flash_decode_attention(q, ck, cv, lengths, **scales)
            else:
                if paged:
                    # one gathered view per dispatch; dead positions hold
                    # garbage-page bytes but the causal mask below replaces
                    # their scores with the same NEG constant the slotted
                    # path uses, so outputs stay bit-identical
                    gk = paged_gather(ck, pt, max_len)
                    gv = paged_gather(cv, pt, max_len)
                    if quant:
                        gk = _kv_dequantize(
                            gk,
                            paged_gather(cks[..., None], pt, max_len)[..., 0],
                        )
                        gv = _kv_dequantize(
                            gv,
                            paged_gather(cvs[..., None], pt, max_len)[..., 0],
                        )
                else:
                    gk, gv = ck, cv
                    if quant:
                        gk = _kv_dequantize(gk, cks)
                        gv = _kv_dequantize(gv, cvs)
                # query row i sits at global position index + i: causal over
                # the written prefix (the reference instead relies on only
                # having written the prefix, `attention.py:71-76,86`)
                if per_row:
                    valid = (
                        jnp.arange(max_len)[None, None, :]
                        <= index[:, None, None] + jnp.arange(n)[None, :, None]
                    )
                    mask = valid[:, None]  # [B,1,n,max_len]
                else:
                    valid = (
                        jnp.arange(max_len)[None, :]
                        <= index + jnp.arange(n)[:, None]
                    )
                    mask = valid[None, None]

                def mask_rows_at(pm):
                    # pad to max_len with True (decode caches may be 1
                    # longer than the mask), then row-slice at the decode
                    # position — shared by the host-side static_mask and
                    # the scan executor's traced mask_array so the two
                    # paths cannot drift
                    if pm.shape[0] < max_len:
                        pad = max_len - pm.shape[0]
                        pm = jnp.pad(
                            pm, ((0, pad), (0, pad)), constant_values=True
                        )
                    pm = pm[:, :max_len]
                    if per_row:
                        return jax.vmap(
                            lambda i: lax.dynamic_slice_in_dim(pm, i, n, axis=0)
                        )(index)[:, None]  # [B,1,n,max_len]
                    return lax.dynamic_slice_in_dim(pm, index, n, axis=0)[
                        None, None
                    ]

                if sparse:
                    # the bitmap supersedes the pattern masks on the dense
                    # fallback too (small caches / attn_impl="dense"), so
                    # BOTH decode paths compute the identical block-level
                    # policy — the sparse-vs-dense oracle the tests pin
                    kv_live = jnp.repeat(bitmap != 0, sparse_block, axis=1)
                    mask = mask & kv_live[:, :max_len][:, None, None, :]
                else:
                    if self.static_mask is not None:
                        mask = mask & mask_rows_at(
                            jnp.asarray(np.asarray(self.static_mask))
                        )
                    if mask_array is not None:
                        mask = mask & mask_rows_at(mask_array)
                out = dense_attention(q, gk, gv, mask=mask, stable=self.stable)
            new_cache = {"k": ck, "v": cv, "index": index + n}
            if quant:
                new_cache["k_scale"] = cks
                new_cache["v_scale"] = cvs
            if paged:
                new_cache["page_table"] = pt
            if sparse:
                # structural round-trip: nn.scan requires carry-in/carry-out
                # pytrees to match, so the bitmap leaf rides back out
                new_cache["block_bitmap"] = bitmap
        else:
            if rotary is not None:
                rot = jnp.expand_dims(rotary[:n], (0, 1))
                q, k, v = (apply_rotary(rot, t) for t in (q, k, v))
            if self.attn_impl == "ring":
                # sequence-parallel exact attention: tokens sharded over the
                # mesh "sp" axis, KV blocks rotate via ppermute (parallel/
                # ring.py). Long-context path beyond the reference's
                # sparsity-based scaling (SURVEY.md §5.7).
                from dalle_pytorch_tpu.parallel.ring import ring_attention_sharded

                assert self.sp_mesh is not None, 'attn_impl="ring" needs sp_mesh'
                assert self.static_mask is None and key_mask is None, (
                    "ring attention supports plain causal/full attention only"
                )
                # the streaming LSE accumulator is inherently max-subtracted;
                # reject the stable flag rather than silently diverge from
                # the dense stable-softmax numerics
                assert not self.stable, 'attn_impl="ring" does not take stable='
                sp = self.sp_mesh.shape["sp"]
                assert n % sp == 0, (
                    f"sequence length {n} must be divisible by the sp axis ({sp}); note "
                    "the uncached generate_images() re-forwards growing "
                    "prefixes — use the KV-cached decode path with ring models"
                )
                out = ring_attention_sharded(
                    self.sp_mesh, q, k, v, causal=self.causal
                )
            elif mask_array is None and self._use_flash(n, key_mask):
                if self.attn_impl == "lib_flash":
                    out = lib_flash_attention(q, k, v, causal=self.causal)
                else:
                    out = flash_attention(
                        q, k, v,
                        mask=self._full_mask(n, n) if self.static_mask is not None else None,
                        causal=self.causal,
                    )
            else:
                mask = self._full_mask(n, n)
                mask = None if mask is None else jnp.asarray(mask)[None, None]
                if mask_array is not None:
                    tm = mask_array[:n, :n][None, None]
                    mask = tm if mask is None else (mask & tm)
                if key_mask is not None:
                    km = key_mask[:, None, None, :]
                    mask = km if mask is None else (mask & km)
                out = dense_attention(q, k, v, mask=mask, stable=self.stable)

        out = out.transpose(0, 2, 1, 3).reshape(b, n, inner)
        out = nn.Dense(self.dim, dtype=self.dtype, name="to_out")(out)
        out = nn.Dropout(self.dropout)(out, deterministic=deterministic)
        return out, new_cache
