from dalle_pytorch_tpu.models.dvae import DiscreteVAE, ResBlock
from dalle_pytorch_tpu.models.clip import CLIP
from dalle_pytorch_tpu.models.dalle import DALLE
from dalle_pytorch_tpu.models.vae_io import OpenAIDiscreteVAE, VQGanVAE
