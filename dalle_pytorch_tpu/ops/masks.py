"""Static attention-mask builders.

The reference implements its sparse attention variants as separate kernels
(`/root/reference/dalle_pytorch/attention.py:103-398`) plus a static-mask
simulation for cache-friendly inference
(`/root/reference/dalle_pytorch/transformer.py:336-353`). On TPU the
mask-based formulation *is* the fast path for moderate sequence lengths:
one big MXU matmul with a fused mask beats gather-heavy sparse layouts.
These builders produce boolean masks with the convention **True = may
attend** (the reference mixes conventions; we standardize).

All masks are built host-side with numpy (static data closed over by jit).
Masks are sized [padded_seq, padded_seq] where padded_seq = text_len +
image_fmap_size**2 and text_len counts <bos> (reference: seq_len -
img_seq_len + 1); slice to [:n, :n] for the actual sequence length.
"""

from __future__ import annotations

import numpy as np


def causal_mask(n: int) -> np.ndarray:
    """Lower-triangular allowed mask."""
    return np.tril(np.ones((n, n), dtype=bool))


def axial_static_mask(seq_len: int, image_fmap_size: int, axis: int) -> np.ndarray:
    """Axial row (axis=0) / column (axis=1) attention as a static mask.

    Semantics of the reference's `Transformer._get_attention_mask`
    (`transformer.py:336-353`): every position may attend to all text;
    image positions may additionally attend within their own row (axis=0)
    or column (axis=1) of the feature map. Combine with `causal_mask` at
    use-site.
    """
    img_seq_len = image_fmap_size**2
    text_len = seq_len + 1 - img_seq_len
    total = text_len + img_seq_len

    mask = np.zeros((total, total), dtype=bool)
    mask[:, :text_len] = True
    img = np.arange(img_seq_len)
    rows, cols = img // image_fmap_size, img % image_fmap_size
    same = (rows[:, None] == rows[None, :]) if axis == 0 else (cols[:, None] == cols[None, :])
    mask[text_len:, text_len:] = same
    return mask


def conv_like_mask(
    seq_len: int,
    image_fmap_size: int,
    kernel_size: int = 5,
    dilation: int = 1,
) -> np.ndarray:
    """Convolutional sparse attention pattern as a static mask.

    Mirrors `SparseConvCausalAttention` (`attention.py:103-221`): text
    attends causally to text only; an image query at grid position (r, c)
    attends to all text plus the causally-padded k x k neighborhood
    {(r - 2*sp + i*dil, c - 2*sp + j*dil) : 0 <= i, j < k} where
    sp = ((kernel_size - 1) * dilation + 1) // 2 — i.e. rows/cols at or
    before its own, within the dilated window.
    """
    assert kernel_size % 2 == 1, "kernel size must be odd"
    img_seq_len = image_fmap_size**2
    text_len = seq_len + 1 - img_seq_len
    total = text_len + img_seq_len
    eff = (kernel_size - 1) * dilation + 1
    sp = eff // 2

    mask = np.zeros((total, total), dtype=bool)
    mask[:text_len, :text_len] = causal_mask(text_len)
    mask[text_len:, :text_len] = True

    img_block = np.zeros((img_seq_len, img_seq_len), dtype=bool)
    for r in range(image_fmap_size):
        for c in range(image_fmap_size):
            q = r * image_fmap_size + c
            for i in range(kernel_size):
                for j in range(kernel_size):
                    kr, kc = r - 2 * sp + i * dilation, c - 2 * sp + j * dilation
                    if 0 <= kr < image_fmap_size and 0 <= kc < image_fmap_size:
                        img_block[q, kr * image_fmap_size + kc] = True
    mask[text_len:, text_len:] = img_block
    return mask


def block_sparse_layout(
    seq_len: int,
    block: int = 16,
    num_local_blocks: int = 4,
    num_random_blocks: int | None = None,
    global_block_indices: tuple[int, ...] | list[int] = (),
    causal: bool = True,
    seed: int = 0,
) -> np.ndarray:
    """Block-level sparsity layout, VariableSparsityConfig-compatible.

    Re-implements the *configuration semantics* the reference requests from
    DeepSpeed's sparse attention (`attention.py:339-365`): a sliding window
    of `num_local_blocks` preceding blocks, `num_random_blocks` random
    earlier blocks per block-row (default seq_len//block//4), and global
    attention to the text blocks (`global_block_indices`). Deterministic
    given `seed`. Returns a [nb, nb] bool block layout (True = block pair
    computed).
    """
    assert seq_len % block == 0, "seq_len must be divisible by block size"
    nb = seq_len // block
    if num_random_blocks is None:
        num_random_blocks = max(nb // 4, 1)
    rng = np.random.RandomState(seed)

    layout = np.zeros((nb, nb), dtype=bool)
    for i in range(nb):
        lo = max(0, i - num_local_blocks + 1)
        layout[i, lo : i + 1] = True
        hi = i + 1 if causal else nb
        if num_random_blocks > 0 and hi > 0:
            layout[i, rng.randint(0, hi, size=num_random_blocks)] = True
    for g in global_block_indices:
        layout[:, g] = True          # everyone attends to global (text) blocks
        layout[g, : g + 1 if causal else nb] = True  # global rows attend widely
    if causal:
        layout &= np.tril(np.ones((nb, nb), dtype=bool))
    return layout


def block_layout_to_token_mask(layout: np.ndarray, block: int, causal: bool = True) -> np.ndarray:
    """Expand a block layout to a token-level allowed mask."""
    mask = np.kron(layout, np.ones((block, block), dtype=bool))
    if causal:
        mask &= causal_mask(mask.shape[0])
    return mask


def mask_to_block_bitmap(
    mask: np.ndarray,
    block: int,
    n_blocks: int | None = None,
    always_live: int = 0,
) -> np.ndarray:
    """Reduce a token-level allowed mask to per-query-row KV-tile liveness.

    The decode-time contract of the block-sparse flash kernel
    (`ops/pallas_decode.py`): bitmap[i, j] says whether query row i may
    read ANY position in KV tile j (tile j covers key positions
    [j*block, (j+1)*block)). The reduction is conservative by
    construction — a tile with a single allowed key is read whole, and
    the kernel's in-tile causal/length mask trims the rest — so sparse
    decode can only ever read a superset of the mask's positions, never
    miss one.

    `n_blocks` widens (False-pads) or crops the tile axis to the serving
    cache's ceil(max_len/block); `always_live` forces the first tiles
    covering that many key positions live (the text prefix + <bos>, which
    every decode policy keeps resident). Host-side numpy, like every
    builder here: the result rides into the chunk program as TRACED data.
    """
    t_q, t_k = mask.shape
    if n_blocks is None:
        n_blocks = -(-t_k // block)
    out = np.zeros((t_q, n_blocks), dtype=bool)
    for j in range(n_blocks):
        lo = j * block
        if lo >= t_k:
            break
        out[:, j] = mask[:, lo : min(lo + block, t_k)].any(axis=1)
    if always_live > 0:
        out[:, : -(-min(always_live, n_blocks * block) // block)] = True
    return out
