"""Dense attention core: one fused MXU-friendly path for every mask pattern.

The reference ships four attention kernels (full / conv-like / axial /
DeepSpeed block-sparse, `/root/reference/dalle_pytorch/attention.py`). On
TPU the idiomatic design is a *single* dense attention einsum with a static
boolean mask (XLA fuses mask + softmax into the matmul epilogue), with a
Pallas flash kernel as the long-sequence fast path. Scores accumulate in
fp32 regardless of the bf16 compute dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = float(jnp.finfo(jnp.float32).max) * -1.0


def stable_softmax(t: jnp.ndarray, axis: int = -1, alpha: float = 32.0**2) -> jnp.ndarray:
    """fp16/bf16-stable softmax: pre-divide by alpha before the max-subtract.

    Matches `stable_softmax` (`attention.py:27-30`).
    """
    t = t / alpha
    t = t - lax.stop_gradient(jnp.max(t, axis=axis, keepdims=True))
    return jax.nn.softmax(t * alpha, axis=axis)


def dense_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray | None = None,
    stable: bool = False,
) -> jnp.ndarray:
    """Scaled dot-product attention. q,k,v: [..., n, d]; mask True=attend."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "...id,...jd->...ij", q * scale, k, preferred_element_type=jnp.float32
    )
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    if stable:
        attn = stable_softmax(scores, axis=-1)
    else:
        attn = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("...ij,...jd->...id", attn.astype(v.dtype), v)
    return out
