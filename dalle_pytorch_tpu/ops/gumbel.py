"""Gumbel-softmax relaxation with straight-through and ReinMax estimators.

Functional equivalents of the sampling used by the reference DiscreteVAE
(`/root/reference/dalle_pytorch/dalle_pytorch.py:236-246`): soft/hard
gumbel-softmax over the codebook axis, plus the ReinMax second-order
straight-through correction (https://arxiv.org/abs/2304.08612, alg. 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _log(t: jnp.ndarray, eps: float = 1e-20) -> jnp.ndarray:
    return jnp.log(jnp.maximum(t, eps))


def gumbel_softmax(
    rng: jax.Array,
    logits: jnp.ndarray,
    tau: float = 1.0,
    hard: bool = False,
    reinmax: bool = False,
    axis: int = -1,
) -> jnp.ndarray:
    """Sample from the Gumbel-softmax distribution over `axis`.

    hard=False  -> soft relaxed one-hot.
    hard=True   -> exact one-hot forward, straight-through gradient.
    reinmax=True (with hard) -> ReinMax second-order gradient correction.
    """
    g = jax.random.gumbel(rng, logits.shape, dtype=logits.dtype)
    y_soft = jax.nn.softmax((logits + g) / tau, axis=axis)

    if not hard:
        return y_soft

    index = jnp.argmax(y_soft, axis=axis)
    one_hot = jax.nn.one_hot(index, logits.shape[axis], dtype=logits.dtype, axis=axis)

    if not reinmax:
        # classic straight-through
        return one_hot + y_soft - lax.stop_gradient(y_soft)

    # ReinMax algorithm 2
    pi0 = jax.nn.softmax(logits, axis=axis)
    pi1 = (one_hot + jax.nn.softmax(logits / tau, axis=axis)) / 2.0
    pi1 = jax.nn.softmax(lax.stop_gradient(_log(pi1) - logits) + logits, axis=axis)
    pi2 = 2.0 * pi1 - 0.5 * pi0
    return pi2 - lax.stop_gradient(pi2) + one_hot
