"""Token-shift for the joint text+image sequence.

Functional re-derivation of the reference's `PreShiftToken`
(`/root/reference/dalle_pytorch/transformer.py:128-202`): before attention
and feed-forward, part of each token's channels are replaced by channels of
a *previous* token — a cheap locality prior.

  * text positions: the first half of the channels is shifted one position
    to the right (channel content comes from the token to the left);
  * image positions (viewed as an H x W grid): the first quarter comes from
    the token one row up, the second quarter from the token one column left,
    and the remaining half passes through.

Pure function of a fixed-shape [B, N, D] array — jit/scan friendly; the
reference's deque-based streaming variant is replaced by a ring-buffer cache
in the decode loop (see models/transformer.py cached path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def shift_tokens_dalle(x: jnp.ndarray, text_len: int, image_fmap_size: int) -> jnp.ndarray:
    """Apply DALL-E token-shift. x: [B, N, D]; text_len counts <bos>."""
    b, n, d = x.shape
    assert d % 4 == 0, "model dim must be divisible by 4 for token shift"
    img_seq_len = image_fmap_size * image_fmap_size

    if n < text_len:  # static shape check: no image tokens present
        half = d // 2
        x_shift = jnp.pad(x[:, :-1, :half], ((0, 0), (1, 0), (0, 0)))
        return jnp.concatenate([x_shift, x[..., half:]], axis=-1)

    x_text, x_img = x[:, :text_len], x[:, text_len:]

    half = d // 2
    t_shift = jnp.pad(x_text[:, :-1, :half], ((0, 0), (1, 0), (0, 0)))
    x_text = jnp.concatenate([t_shift, x_text[..., half:]], axis=-1)

    img_len = x_img.shape[1]
    pad_rows = img_seq_len - img_len
    x_img = jnp.pad(x_img, ((0, 0), (0, pad_rows), (0, 0)))
    x_img = x_img.reshape(b, image_fmap_size, image_fmap_size, d)

    q = d // 4
    top = jnp.pad(x_img[:, :-1, :, :q], ((0, 0), (1, 0), (0, 0), (0, 0)))
    left = jnp.pad(x_img[:, :, :-1, q : 2 * q], ((0, 0), (0, 0), (1, 0), (0, 0)))
    x_img = jnp.concatenate([top, left, x_img[..., 2 * q :]], axis=-1)

    x_img = x_img.reshape(b, img_seq_len, d)[:, :img_len]
    return jnp.concatenate([x_text, x_img], axis=1)


# ------------------------------------------------- streaming (cached decode)
#
# The reference streams token-shift during cached inference with a python
# deque of recent tokens (`transformer.py:140-155`). The jit/scan-friendly
# equivalent is a ring buffer of the last `image_fmap_size` pre-shift token
# vectors, indexed by global position mod fmap: the slot about to be
# overwritten at position p holds exactly h[p - fmap] (the token one grid
# row up), and slot (p-1) mod fmap holds h[p-1] (one position left).


def shift_ring_from_prefill(h: jnp.ndarray, fmap: int) -> jnp.ndarray:
    """Ring buffer after prefilling positions 0..n-1 with pre-shift values h."""
    import numpy as np

    b, n, d = h.shape
    ring = jnp.zeros((b, fmap, d), h.dtype)
    start = max(0, n - fmap)
    slots = np.arange(start, n) % fmap  # static, distinct -> one scatter
    return ring.at[:, slots].set(h[:, start:])


def shift_ring_from_prefill_at(
    h: jnp.ndarray, fmap: int, end: jnp.ndarray
) -> jnp.ndarray:
    """Ring buffer as if only positions 0..end[b]-1 had been prefilled.

    The decode-resume path (models/dalle.py `decode_resume`) runs ONE
    teacher-forced forward over the whole prompt + generated-image
    prefix, but each row resumes at its OWN position `end[b]` — the ring
    must hold the pre-shift values of the last `fmap` positions BELOW
    that end, exactly as the incremental decode would have left it, not
    the trailing window of the full padded sequence. For every slot j,
    that is h at the largest position p < end with p ≡ j (mod fmap);
    positions below 0 (end < fmap) stay zero, matching
    `shift_ring_from_prefill`'s unwritten-slot semantics — and with
    end == n this IS `shift_ring_from_prefill`, value for value.
    """
    b, n, d = h.shape
    end = jnp.asarray(end, jnp.int32)  # [B] global resume positions
    slots = jnp.arange(fmap, dtype=jnp.int32)[None, :]  # [1, fmap]
    last = end[:, None] - 1  # [B, 1] last prefilled position per row
    p = last - jnp.mod(last - slots, fmap)  # [B, fmap], p ≡ slot (mod fmap)
    vals = jax.vmap(
        lambda row, idx: row[jnp.clip(idx, 0, n - 1)]
    )(h, p)  # [B, fmap, d]
    return jnp.where((p >= 0)[..., None], vals, jnp.zeros_like(vals))


def shift_token_step(
    h: jnp.ndarray, ring: jnp.ndarray, pos: jnp.ndarray, text_len: int, fmap: int
):
    """One-token token-shift against the ring buffer.

    h: [B, 1, D] pre-shift value of the token at global position `pos` — a
    traced scalar (all rows at one position, the micro-batch decode scan)
    or a traced [B] vector (per-row positions, the continuous-batching slot
    cache). Returns (shifted [B, 1, D], updated ring).
    """
    b, _, d = h.shape
    half, q = d // 2, d // 4
    cur = h[:, 0]

    if jnp.ndim(pos) == 1:
        # per-row positions: each row reads/writes its OWN ring slots
        prev = jax.vmap(
            lambda r, p: lax.dynamic_slice_in_dim(
                r, jnp.mod(p - 1, fmap), 1, axis=0
            )
        )(ring, pos)[:, 0]
        up = jax.vmap(
            lambda r, p: lax.dynamic_slice_in_dim(r, jnp.mod(p, fmap), 1, axis=0)
        )(ring, pos)[:, 0]
        posb = pos[:, None]  # [B,1] broadcasting against [B, channels]
    else:
        prev = lax.dynamic_slice_in_dim(
            ring, jnp.mod(pos - 1, fmap), 1, axis=1
        )[:, 0]
        up = lax.dynamic_slice_in_dim(ring, jnp.mod(pos, fmap), 1, axis=1)[:, 0]
        posb = pos

    # text position: first half of channels from the previous token
    t_first = jnp.where(posb > 0, prev[:, :half], jnp.zeros_like(prev[:, :half]))
    text_shift = jnp.concatenate([t_first, cur[:, half:]], axis=-1)

    # image position i (row r, col c): first quarter from one row up
    # (i - fmap, valid when r > 0), second quarter from one col left
    # (i - 1, valid when c > 0); both sources are image positions whenever
    # valid, so text never leaks into the grid.
    i = posb - text_len
    top = jnp.where(i >= fmap, up[:, :q], jnp.zeros_like(up[:, :q]))
    left = jnp.where(
        jnp.mod(i, fmap) != 0, prev[:, q : 2 * q], jnp.zeros_like(prev[:, q : 2 * q])
    )
    img_shift = jnp.concatenate([top, left, cur[:, 2 * q :]], axis=-1)

    out = jnp.where(posb < text_len, text_shift, img_shift)
    if jnp.ndim(pos) == 1:
        ring = jax.vmap(
            lambda r, c, p: lax.dynamic_update_slice(
                r, c[None], (jnp.mod(p, fmap), 0)
            )
        )(ring, cur, pos)
    else:
        ring = lax.dynamic_update_slice(
            ring, cur[:, None], (0, jnp.mod(pos, fmap), 0)
        )
    return out[:, None], ring
