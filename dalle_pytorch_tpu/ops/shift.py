"""Token-shift for the joint text+image sequence.

Functional re-derivation of the reference's `PreShiftToken`
(`/root/reference/dalle_pytorch/transformer.py:128-202`): before attention
and feed-forward, part of each token's channels are replaced by channels of
a *previous* token — a cheap locality prior.

  * text positions: the first half of the channels is shifted one position
    to the right (channel content comes from the token to the left);
  * image positions (viewed as an H x W grid): the first quarter comes from
    the token one row up, the second quarter from the token one column left,
    and the remaining half passes through.

Pure function of a fixed-shape [B, N, D] array — jit/scan friendly; the
reference's deque-based streaming variant is replaced by a ring-buffer cache
in the decode loop (see models/transformer.py cached path).
"""

from __future__ import annotations

import jax.numpy as jnp


def shift_tokens_dalle(x: jnp.ndarray, text_len: int, image_fmap_size: int) -> jnp.ndarray:
    """Apply DALL-E token-shift. x: [B, N, D]; text_len counts <bos>."""
    b, n, d = x.shape
    assert d % 4 == 0, "model dim must be divisible by 4 for token shift"
    img_seq_len = image_fmap_size * image_fmap_size

    if n < text_len:  # static shape check: no image tokens present
        half = d // 2
        x_shift = jnp.pad(x[:, :-1, :half], ((0, 0), (1, 0), (0, 0)))
        return jnp.concatenate([x_shift, x[..., half:]], axis=-1)

    x_text, x_img = x[:, :text_len], x[:, text_len:]

    half = d // 2
    t_shift = jnp.pad(x_text[:, :-1, :half], ((0, 0), (1, 0), (0, 0)))
    x_text = jnp.concatenate([t_shift, x_text[..., half:]], axis=-1)

    img_len = x_img.shape[1]
    pad_rows = img_seq_len - img_len
    x_img = jnp.pad(x_img, ((0, 0), (0, pad_rows), (0, 0)))
    x_img = x_img.reshape(b, image_fmap_size, image_fmap_size, d)

    q = d // 4
    top = jnp.pad(x_img[:, :-1, :, :q], ((0, 0), (1, 0), (0, 0), (0, 0)))
    left = jnp.pad(x_img[:, :, :-1, q : 2 * q], ((0, 0), (0, 0), (1, 0), (0, 0)))
    x_img = jnp.concatenate([top, left, x_img[..., 2 * q :]], axis=-1)

    x_img = x_img.reshape(b, img_seq_len, d)[:, :img_len]
    return jnp.concatenate([x_text, x_img], axis=1)
