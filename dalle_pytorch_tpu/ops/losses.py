"""Memory-efficient (vocab-chunked) cross-entropy for the DALLE head.

The straightforward loss path materializes `[B, N, V]` fp32 logits twice
(forward + softmax-minus-onehot backward); at the flagship geometry
(B16 x N1280 x V18448) that is ~1.5 GB per materialization and ~24 GB of
HBM traffic per step (BASELINE.md round-3 decomposition). This module
computes the same split cross-entropy by scanning the vocabulary in
chunks: each chunk's logits live only in registers/VMEM-sized transients,
and `jax.checkpoint` on the scan body makes the backward recompute chunk
logits instead of saving them.

Semantics match `DALLE.__call__`'s loss exactly (reference
`dalle_pytorch.py:450-464,694-706`): per-position vocab blocking (text
rows emit text vocab only, image rows image vocab only; the NEG-masked
entries contribute nothing to the logsumexp) and per-position text/image
loss weighting.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG = np.float32(-1e30)  # np, NOT jnp: a module-level jax Array would be
# hoisted into every fused-CE executable as a runtime constant argument,
# and the jit C++ fastpath drops hoisted const args after 2 calls on
# jax 0.9 ("Execution supplied N buffers but compiled program expected M")


def chunked_masked_ce(
    h: jnp.ndarray,
    kernel: jnp.ndarray,
    bias: Optional[jnp.ndarray],
    labels: jnp.ndarray,
    *,
    row_is_text: jnp.ndarray,
    num_text_vocab: int,
    chunk: int = 2048,
) -> jnp.ndarray:
    """Per-position CE of `softmax(h @ kernel + bias)` vs `labels`.

    h: [B, N, D] (any float dtype; matmul accumulates fp32)
    kernel: [D, V]; bias: [V] or None
    labels: [B, N] int ids into V
    row_is_text: [N] bool — True rows may only emit ids < num_text_vocab,
        False rows only ids >= num_text_vocab (the reference's logits
        mask, applied on the fly per chunk instead of via a [N, V] where).
    Returns per-position loss [B, N] (caller applies weights/averaging).
    """
    B, N, D = h.shape
    V = kernel.shape[1]
    # don't pad tiny vocabularies up to a full `chunk` (a 90-entry test
    # vocab would otherwise compute 2048 logit columns); lane-align to 128
    chunk = min(chunk, max(128, -(-V // 128) * 128))
    n_chunks = -(-V // chunk)
    pad = n_chunks * chunk - V
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        if bias is not None:
            bias = jnp.pad(bias, (0, pad), constant_values=0.0)

    # scan carry: streaming logsumexp (m, s) + gathered gold logit
    m0 = jnp.full((B, N), NEG, jnp.float32)
    s0 = jnp.zeros((B, N), jnp.float32)
    g0 = jnp.zeros((B, N), jnp.float32)

    kernel_chunks = kernel.reshape(D, n_chunks, chunk).transpose(1, 0, 2)
    bias_chunks = (
        bias.reshape(n_chunks, chunk)
        if bias is not None
        else jnp.zeros((n_chunks, chunk), jnp.float32)
    )

    text_rows = row_is_text[None, :]  # [1, N]

    @jax.checkpoint
    def body(carry, inp):
        m, s, g = carry
        ci, kc, bc = inp
        base = ci * chunk
        # [B, N, chunk] fp32 — the only logits transient that ever exists
        logits = jnp.einsum(
            "bnd,dc->bnc", h, kc.astype(h.dtype),
            preferred_element_type=jnp.float32,
        ) + bc.astype(jnp.float32)
        ids = base + jnp.arange(chunk)
        id_is_text = (ids < num_text_vocab)[None, None, :]
        id_is_real = (ids < V)[None, None, :]
        allowed = (text_rows[..., None] == id_is_text) & id_is_real
        logits = jnp.where(allowed, logits, NEG)

        cmax = logits.max(axis=-1)
        m_new = jnp.maximum(m, cmax)
        # guard exp(NEG - NEG): scale both by finite m_new
        s = s * jnp.exp(m - m_new) + jnp.exp(logits - m_new[..., None]).sum(-1)
        in_chunk = (labels >= base) & (labels < base + chunk)
        local = jnp.clip(labels - base, 0, chunk - 1)
        gold_c = jnp.take_along_axis(logits, local[..., None], axis=-1)[..., 0]
        g = jnp.where(in_chunk, gold_c, g)
        return (m_new, s, g), None

    if n_chunks == 1:
        # single chunk: call the body directly. A length-1 lax.scan here
        # miscompiles under grad on jax 0.9 ("Execution supplied N buffers
        # but compiled program expected M" after a few cached-executable
        # calls); the scan is pointless at length 1 anyway.
        (m, s, g), _ = body(
            (m0, s0, g0),
            (jnp.zeros((), jnp.int32), kernel_chunks[0], bias_chunks[0]),
        )
    else:
        (m, s, g), _ = lax.scan(
            body,
            (m0, s0, g0),
            (jnp.arange(n_chunks), kernel_chunks, bias_chunks),
        )
    logz = m + jnp.log(s)
    return logz - g


def split_weighted_mean(
    per_pos: jnp.ndarray,
    split: int,
    first_weight: float,
    second_weight: float,
    drop_last_of_first: bool = False,
):
    """((w1 * mean(first part) + w2 * mean(second part)) / (w1 + w2)).

    `drop_last_of_first` reproduces the inverse-mapping quirk where the
    image segment excludes its final position (reference `:686-687`).
    """
    first = per_pos[:, : split - 1] if drop_last_of_first else per_pos[:, :split]
    second = per_pos[:, split:]
    return (first_weight * first.mean() + second_weight * second.mean()) / (
        first_weight + second_weight
    )
