"""Rotary position embeddings for the joint text+image sequence.

Re-derivation of the reference's dual rotary scheme
(`/root/reference/dalle_pytorch/transformer.py:306-330`): each head gets
three rotary blocks —

  1. a 1-D "language" rotary over text positions, with every image position
     pinned to the far-away sentinel position 8192;
  2. a 2-D axial "pixel" rotary over the image feature-map grid (row and
     column coordinates in linspace(-1, 1)), with every text position pinned
     to the off-grid sentinel coordinate -10 on both axes.

rot_dim = dim_head // 3 per block; pairs are interleaved (adjacent even/odd
channels form a rotation pair), matching the rotary-embedding-torch
convention used by the reference (`attention.py:32-35`).

Everything here is precomputed host-side once and closed over by the jitted
step functions — it is static data, not traced computation.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def rotary_freqs_lang(rot_dim: int, theta: float = 10000.0) -> np.ndarray:
    """Inverse-frequency vector for ordinary (language) rotary embeddings."""
    return 1.0 / (theta ** (np.arange(0, rot_dim, 2)[: rot_dim // 2] / rot_dim))


def rotary_freqs_pixel(rot_dim: int, max_freq: float = 10.0) -> np.ndarray:
    """Frequency vector for 'pixel' rotary embeddings (coords in [-1, 1])."""
    return np.linspace(1.0, max_freq / 2.0, rot_dim // 2) * np.pi


def _angles(positions: np.ndarray, freqs: np.ndarray) -> np.ndarray:
    """Outer product position x freq, duplicated per rotation pair.

    Returns [..., 2 * len(freqs)] with layout [f0, f0, f1, f1, ...] so that
    adjacent channels share a rotation angle (interleaved-pair convention).
    """
    ang = np.einsum("...,f->...f", positions.astype(np.float64), freqs)
    return np.repeat(ang, 2, axis=-1)


def build_dalle_rotary(
    text_len: int,
    image_fmap_size: int,
    dim_head: int,
    theta: float = 10000.0,
    max_freq: float = 10.0,
    text_sentinel: float = 8192.0,
    pixel_sentinel: float = -10.0,
) -> jnp.ndarray:
    """Build the combined [seq_len + 1, 3 * 2*(rot_dim//2)] rotary angle table.

    `text_len` counts the <bos> token (reference: seq_len - img_seq_len + 1).
    Row layout: text rows first, then image rows in raster order.
    Channel layout: [text-1D block | image-row block | image-col block].
    """
    rot_dim = dim_head // 3
    img_seq_len = image_fmap_size * image_fmap_size

    lang = rotary_freqs_lang(rot_dim, theta)
    pixel = rotary_freqs_pixel(rot_dim, max_freq)

    # block 1: 1-D language rotary (text positions; images at far sentinel)
    text_block = np.concatenate(
        [
            _angles(np.arange(text_len), lang),
            _angles(np.full((img_seq_len,), text_sentinel), lang),
        ],
        axis=0,
    )

    # blocks 2+3: 2-D axial pixel rotary (texts at off-grid sentinel)
    coords = np.linspace(-1.0, 1.0, image_fmap_size)
    ax = _angles(coords, pixel)  # [fmap, d]
    row = np.broadcast_to(ax[:, None, :], (image_fmap_size, image_fmap_size, ax.shape[-1]))
    col = np.broadcast_to(ax[None, :, :], (image_fmap_size, image_fmap_size, ax.shape[-1]))
    img_axial = np.concatenate([row, col], axis=-1).reshape(img_seq_len, -1)

    text_sent = _angles(np.full((text_len,), pixel_sentinel), pixel)
    text_axial = np.concatenate([text_sent, text_sent], axis=-1)
    axial_block = np.concatenate([text_axial, img_axial], axis=0)

    table = np.concatenate([text_block, axial_block], axis=-1)
    return jnp.asarray(table, dtype=jnp.float32)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    """(x0, x1, x2, x3, ...) -> (-x1, x0, -x3, x2, ...) on the last axis."""
    x = x.reshape(*x.shape[:-1], -1, 2)
    x1, x2 = x[..., 0], x[..., 1]
    return jnp.stack([-x2, x1], axis=-1).reshape(*x.shape[:-2], -1)


def apply_rotary(angles: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
    """Rotate the first `angles.shape[-1]` channels of t; pass the rest through.

    angles: [..., n, d_rot] broadcastable against t[..., n, :d_rot].
    """
    d_rot = angles.shape[-1]
    angles = angles.astype(t.dtype)
    t_rot, t_pass = t[..., :d_rot], t[..., d_rot:]
    t_rot = t_rot * jnp.cos(angles) + _rotate_half(t_rot) * jnp.sin(angles)
    return jnp.concatenate([t_rot, t_pass], axis=-1)
