from dalle_pytorch_tpu.ops.rotary import (
    build_dalle_rotary,
    apply_rotary,
    rotary_freqs_lang,
    rotary_freqs_pixel,
)
from dalle_pytorch_tpu.ops.gumbel import gumbel_softmax
from dalle_pytorch_tpu.ops.sampling import top_k_filter, gumbel_sample
from dalle_pytorch_tpu.ops.masks import (
    causal_mask,
    axial_static_mask,
    conv_like_mask,
    block_sparse_layout,
    block_layout_to_token_mask,
)
from dalle_pytorch_tpu.ops.shift import shift_tokens_dalle
from dalle_pytorch_tpu.ops.attention_core import (
    stable_softmax,
    dense_attention,
)
from dalle_pytorch_tpu.ops.pallas_attention import flash_attention, mask_block_layout
