"""Token sampling helpers for autoregressive decoding.

Functional equivalents of the reference's sampling utilities
(`/root/reference/dalle_pytorch/dalle_pytorch.py:55-71`): top-k filtering
keyed by a *fraction* threshold and gumbel-max sampling. Implemented with
`lax.top_k` + threshold comparison so shapes stay static under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def top_k_filter(logits: jnp.ndarray, thres: float = 0.5) -> jnp.ndarray:
    """Keep the top max(int((1-thres)*V), 1) logits; set the rest to -inf.

    Matches the reference's `top_k(logits, thres)` semantics where `thres`
    is the fraction of the vocabulary to drop (default 0.5; generation CLI
    uses 0.9).
    """
    num_logits = logits.shape[-1]
    k = max(int((1.0 - thres) * num_logits), 1)
    kth = lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def gumbel_sample(
    rng: jax.Array, logits: jnp.ndarray, temperature: float = 1.0
) -> jnp.ndarray:
    """Sample token ids via the gumbel-max trick: argmax(logits/T + G)."""
    g = jax.random.gumbel(rng, logits.shape, dtype=jnp.float32)
    return jnp.argmax(logits.astype(jnp.float32) / temperature + g, axis=-1)


def top_k_filter_per_row(logits: jnp.ndarray, keep_k: jnp.ndarray) -> jnp.ndarray:
    """Per-row top-k: row i keeps its keep_k[i] largest logits, -inf elsewhere.

    `keep_k` is a traced [B] int array, so heterogeneous requests batch into
    one compiled program (the serving micro-batcher's requirement). Costs a
    full per-row sort instead of `lax.top_k`'s partial selection — fine at
    decode-vocab widths, and the batch is the point.
    """
    sorted_desc = -jnp.sort(-logits.astype(jnp.float32), axis=-1)
    idx = jnp.clip(keep_k - 1, 0, logits.shape[-1] - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    return jnp.where(logits < kth, -jnp.inf, logits)


def per_row_step_keys(seeds: jnp.ndarray, positions: jnp.ndarray) -> jax.Array:
    """Per-row sampling keys for decode step(s): fold (seed, position).

    Row i's stream is a pure function of (seeds[i], positions[i]) — its own
    request seed and its own IMAGE position — never of batch composition,
    slot index, or wall-clock step. This is the single derivation shared by
    the micro-batch sampler (`models/dalle.py:
    _generate_images_cached_batched_impl`, where every row sits at the same
    position) and the continuous-batching chunk decode (where rows sit at
    DIFFERENT positions), so a request's tokens are bit-identical whichever
    engine — and whichever mid-flight admission point — serves it.
    """
    base = jax.vmap(lambda s: jax.random.fold_in(jax.random.PRNGKey(0), s))(
        seeds
    )
    return jax.vmap(jax.random.fold_in)(base, positions)


def gumbel_sample_per_row(
    keys: jax.Array, logits: jnp.ndarray, temperature: jnp.ndarray
) -> jnp.ndarray:
    """Gumbel-max with a per-row PRNG key [B, ...] and temperature [B].

    Temperatures are clamped away from zero; callers wanting greedy decode
    pass a tiny temperature (the argmax then dominates the gumbel noise).
    """
    g = jax.vmap(
        lambda k, row: jax.random.gumbel(k, row.shape, dtype=jnp.float32)
    )(keys, logits)
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-4)[:, None]
    return jnp.argmax(logits.astype(jnp.float32) / t + g, axis=-1)
