"""Pallas TPU flash-decode: cached attention over a slot KV cache with
per-row live lengths.

The decode analog of `ops/pallas_attention.py`. During KV-cached decode the
dense path attends every query chunk against the ENTIRE fixed-shape cache
[B, H, max_len, D] — dead positions included, masked out in the softmax
epilogue — so every decode step pays max_len worth of K/V reads no matter
how short the live prefix is. Under continuous batching the waste compounds:
each slot row sits at its OWN position, and a freshly-admitted row drags the
full cache through the MXU for a prefix of a few hundred tokens.

Design (split-K over the key axis, cf. flash-decoding / "SparkAttention",
PAPERS.md):

  * grid (b, h, ki): the small query chunk (1..K tokens per slot row) stays
    resident in VMEM while [block_k, d] K/V tiles stream through; the
    online-softmax state (m, l, acc) carries across ki in fp32 VMEM scratch
    and the normalized output flushes on the last step — O(max_len) memory
    never materializes a [*, max_len] score row in HBM;
  * per-row liveness: `lengths[b]` (the row's cache index + the chunk size)
    arrives via scalar prefetch (SMEM), so K/V tiles fully above a row's
    live prefix are skipped ENTIRELY — the kernel predicates compute with
    `@pl.when`, and the DMA index map clamps dead steps to the row's last
    live tile (Pallas elides the copy when the block index repeats, the
    same trick as the causal skip in `ops/pallas_attention.py`), so a row
    at position p costs ceil(p/block_k) tiles of K/V traffic, not
    max_len/block_k;
  * within the live region, causality over the written prefix matches the
    dense cached path exactly: query row i (global position
    lengths[b] - n + i) attends to cache positions <= lengths[b] - n + i;
  * fp32 accumulation regardless of input dtype; no VJP (decode is
    inference-only — the training path keeps `flash_attention`'s
    recompute-based backward).

Dispatch lives in `models/attention.py` (`Attention._use_flash_decode`):
the dense cached path remains the fallback for pattern masks (static or
traced — a per-step row-sliced mask cannot drive the block skip) and for
small caches below `AUTO_FLASH_DECODE_MIN_LEN`. Interpret mode is selected
automatically off-TPU (same `_use_interpret` probe as the training kernel)
so CPU tests exercise the real kernel logic.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dalle_pytorch_tpu.ops.pallas_attention import (
    NEG_INF,
    CompilerParams,
    _pad_to,
    _use_interpret,
)

#: minimum q-axis tile (fp32 sublane count) — single-token decode pads its
#: one query row up to this and slices the garbage rows back off
_MIN_BLOCK_Q = 8


def _last_live_block(length, block_k):
    """Index of the last K/V block holding a live position for a row of
    `length` live cache entries. Single source of truth for the kernel's
    liveness predicate AND the DMA-skip index map — they must stay in
    lockstep (a skipped copy for a step the kernel treats as live would
    compute on stale data silently)."""
    return jnp.maximum(length - 1, 0) // block_k


def _decode_kernel(
    lengths_ref, q_ref, *refs,
    sm_scale, block_k, n_real_q, nk_blocks, quantized=False, live_ref=None,
):
    """Grid (b, h, ki): the q chunk stays put over the inner ki steps while
    [block_k, d] K/V tiles stream through (auto double-buffered). Tiles
    fully above the row's live length never run — and never DMA (their
    index-map steps repeat the last live tile, so the copy is elided).

    `quantized=True` interleaves per-(position, head) fp32 scale refs
    ([block_k] tiles) after each int8 K/V ref and dequantizes IN KERNEL —
    the HBM read stays 1 byte/element; compute is fp32 as always.

    `live_ref` ([B, nk_blocks] int32 in SMEM, block-sparse mode) replaces
    the length-derived liveness predicate with a per-(row, tile) bitmap:
    a 0 entry skips the tile's compute here AND its DMA (the block-map
    scalar operand the sparse index maps read re-indexes the previous
    live tile, so Pallas elides the copy — the exact length-skip trick,
    generalized to holes). The bitmap arrives pre-ANDed with the length
    bound, so in-live-range causality still comes from `lengths_ref`."""
    if quantized:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    ki = pl.program_id(2)
    length = lengths_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if live_ref is None:
        live = ki <= _last_live_block(length, block_k)
    else:
        live = live_ref[b, ki] == 1

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [bq, d]
        kb = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            kb = kb * ks_ref[0, 0][:, None]
            vb = vb * vs_ref[0, 0][:, None]
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        bq = q.shape[0]
        col = ki * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        row = lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
        # query row i sits at global position length - n + i; causal over
        # the written prefix (same mask the dense cached path builds in
        # models/attention.py) — this also masks the key padding, since
        # length <= n_real_k <= padded length
        s = jnp.where(col <= length - n_real_q + row, s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk_blocks - 1)
    def _flush():
        # padded q rows (bq > n_real_q) DO accumulate — their causal bound
        # is wider than any real row's — but the caller slices them off;
        # the guard only protects the lengths == 0 corner (no live key at
        # all), which real callers never produce (lengths >= n >= 1)
        safe_l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def flash_decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    sm_scale: Optional[float] = None,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Cached-decode attention with per-row live lengths and KV block skip.

    q: [B, H, n, D] — the current chunk's queries (n = 1 for single-token
       decode, larger for prefill chunks), already written into the cache;
    k, v: [B, H, S, D] — the fixed-shape slot cache AFTER the chunk write;
    lengths: [B] int — per-row live cache entries INCLUDING the chunk, i.e.
       the row's pre-chunk cache index + n. Query row i of batch row b
       attends to cache positions <= lengths[b] - n + i, exactly the mask
       the dense cached path applies.

    `k_scale`/`v_scale` ([B, H, S] fp32, both or neither) mark an int8
    cache: K/V tiles are dequantized inside the kernel (tile element *
    its position's scale) before the fp32 flash math — so the per-token
    HBM read is 1 byte/element and no fp copy of the cache ever
    materializes. (TPU note: the scale tiles are (1, 1, block_k) —
    fine for the Mosaic layouts this repo's geometries use; the CPU
    interpret path the tests pin is layout-agnostic.)

    Matches `dense_attention(q, k, v, mask)` over that mask to fp32
    tolerance (pinned in tests/test_pallas_decode.py). Not differentiable
    by design — decode only.
    """
    b, h, n, d = q.shape
    s_len = k.shape[2]
    assert k.shape == v.shape == (b, h, s_len, d), (q.shape, k.shape, v.shape)
    assert lengths.shape == (b,), f"lengths {lengths.shape} != ({b},)"
    quantized = k_scale is not None
    assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
    if quantized:
        assert k_scale.shape == v_scale.shape == (b, h, s_len), (
            k_scale.shape, (b, h, s_len),
        )
    scale = d**-0.5 if sm_scale is None else sm_scale
    interp = _use_interpret() if interpret is None else interpret

    block_k = max(min(block_k, s_len), 1)
    qp = _pad_to(q, 2, _MIN_BLOCK_Q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    bq = qp.shape[2]
    nk_blocks = kp.shape[2] // block_k
    lengths = jnp.clip(lengths.astype(jnp.int32), 0, s_len)

    kernel = functools.partial(
        _decode_kernel,
        sm_scale=scale,
        block_k=block_k,
        n_real_q=n,
        nk_blocks=nk_blocks,
        quantized=quantized,
    )
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, lens: (b_, h_, 0, 0))

    def k_idx(b_, h_, j, lens):
        # DMA skip: steps above the row's last live tile re-index that tile,
        # so Pallas elides their copies (repeat block index = no new DMA)
        return (b_, h_, jnp.minimum(j, _last_live_block(lens[b_], block_k)), 0)

    kspec = pl.BlockSpec((1, 1, block_k, d), k_idx)
    in_specs = [qspec, kspec, kspec]
    operands = [qp, kp, vp]
    if quantized:
        sspec = pl.BlockSpec(
            (1, 1, block_k),
            lambda b_, h_, j, lens: (
                b_, h_, jnp.minimum(j, _last_live_block(lens[b_], block_k)),
            ),
        )
        ksp = _pad_to(k_scale.astype(jnp.float32), 2, block_k)
        vsp = _pad_to(v_scale.astype(jnp.float32), 2, block_k)
        in_specs = [qspec, kspec, sspec, kspec, sspec]
        operands = [qp, kp, ksp, vp, vsp]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h, nk_blocks),
            in_specs=in_specs,
            out_specs=qspec,
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interp,
    )(lengths, *operands)
    return out[:, :, :n, :]


# ------------------------------------------------- block-sparse tile skip
#
# Policy sparsity (axial / block-sparse attention layouts) generalizes the
# length skip: a row's dead KV tiles are not just the suffix above its live
# length but arbitrary HOLES the attention pattern never reads (an axial-row
# image query only attends its own feature-map row + the text prefix). The
# bitmap is per (batch row, KV tile), rides scalar prefetch next to the
# lengths, and drives BOTH the compute predicate and the DMA index map —
# so a skipped tile costs zero FLOPs and zero HBM traffic, and (since the
# int8 scale sidecars share the same index maps) zero scale reads too.
# An all-ones bitmap reduces the predicate and the index map to EXACTLY
# the length-skip forms above, which is the bit-identity pin the tests
# hold the sparse kernel to.


def _sparse_maps(lengths, block_bitmap, block_k, nk_blocks):
    """Per-(row, tile) liveness + DMA re-index maps for the sparse kernels.

    live[b, j]  = bitmap says read it AND tile j intersects the live prefix;
    bmap[b, j]  = j for live tiles, else the nearest live tile index <= j
                  (0 before the first live tile — that one copy is real but
                  its compute is predicated off). Consecutive dead steps
                  repeat an index, so Pallas elides their DMAs.

    Both are traced int32 — policy flips never recompile (the bitmap is
    DATA, not structure)."""
    j = lax.broadcasted_iota(jnp.int32, (lengths.shape[0], nk_blocks), 1)
    llb = _last_live_block(lengths, block_k)[:, None]
    live = (block_bitmap != 0) & (j <= llb)
    bmap = jnp.maximum(lax.cummax(jnp.where(live, j, -1), axis=1), 0)
    return live.astype(jnp.int32), bmap.astype(jnp.int32)


def _sparse_decode_kernel(lengths_ref, live_ref, bmap_ref, q_ref, *refs, **kw):
    """Online-softmax body with the bitmap predicate; the block-map ref is
    consumed by the K/V BlockSpec index maps, not the body."""
    del bmap_ref
    _decode_kernel(lengths_ref, q_ref, *refs, live_ref=live_ref, **kw)


def block_sparse_flash_decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    lengths: jnp.ndarray,
    block_bitmap: jnp.ndarray,
    *,
    sm_scale: Optional[float] = None,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """`flash_decode_attention` with per-row per-KV-tile policy skipping.

    block_bitmap: [B, ceil(S/block_k)] int (nonzero = tile may be read) —
    tile j of row b covers cache positions [j*block_k, (j+1)*block_k).
    Within live tiles the causal-over-prefix mask still applies, so an
    all-ones bitmap is bit-identical to `flash_decode_attention` (same
    tile order, same predicates, same accumulation — pinned in tests).
    The bitmap is traced data: policy changes never trigger a compile.

    int8 caches pass `k_scale`/`v_scale` as usual; the scale sidecars ride
    the same block-map index maps, so a skipped tile skips its scale read.
    """
    b, h, n, d = q.shape
    s_len = k.shape[2]
    assert k.shape == v.shape == (b, h, s_len, d), (q.shape, k.shape, v.shape)
    assert lengths.shape == (b,), f"lengths {lengths.shape} != ({b},)"
    quantized = k_scale is not None
    assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
    if quantized:
        assert k_scale.shape == v_scale.shape == (b, h, s_len), (
            k_scale.shape, (b, h, s_len),
        )
    scale = d**-0.5 if sm_scale is None else sm_scale
    interp = _use_interpret() if interpret is None else interpret

    block_k = max(min(block_k, s_len), 1)
    qp = _pad_to(q, 2, _MIN_BLOCK_Q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    bq = qp.shape[2]
    nk_blocks = kp.shape[2] // block_k
    assert block_bitmap.shape == (b, nk_blocks), (
        f"block_bitmap {block_bitmap.shape} != ({b}, {nk_blocks}) "
        f"for S={s_len}, block_k={block_k}"
    )
    lengths = jnp.clip(lengths.astype(jnp.int32), 0, s_len)
    live_map, block_map = _sparse_maps(
        lengths, block_bitmap, block_k, nk_blocks
    )

    kernel = functools.partial(
        _sparse_decode_kernel,
        sm_scale=scale,
        block_k=block_k,
        n_real_q=n,
        nk_blocks=nk_blocks,
        quantized=quantized,
    )
    qspec = pl.BlockSpec(
        (1, 1, bq, d), lambda b_, h_, j, lens, live, bmap: (b_, h_, 0, 0)
    )

    def k_idx(b_, h_, j, lens, live, bmap):
        # dead steps re-index the nearest preceding live tile -> copy elided
        return (b_, h_, bmap[b_, j], 0)

    kspec = pl.BlockSpec((1, 1, block_k, d), k_idx)
    in_specs = [qspec, kspec, kspec]
    operands = [qp, kp, vp]
    if quantized:
        sspec = pl.BlockSpec(
            (1, 1, block_k),
            lambda b_, h_, j, lens, live, bmap: (b_, h_, bmap[b_, j]),
        )
        ksp = _pad_to(k_scale.astype(jnp.float32), 2, block_k)
        vsp = _pad_to(v_scale.astype(jnp.float32), 2, block_k)
        in_specs = [qspec, kspec, sspec, kspec, sspec]
        operands = [qp, kp, ksp, vp, vsp]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, h, nk_blocks),
            in_specs=in_specs,
            out_specs=qspec,
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interp,
    )(lengths, live_map, block_map, *operands)
    return out[:, :, :n, :]


# ------------------------------------------------------------ paged KV cache
#
# The continuous engine's paged layout stores K/V as a pool of fixed-size
# pages [P, H, page_size, D] plus a per-row page table [B, n_pages] mapping
# logical block j of row b to a physical page (serving/paging.py owns the
# allocation; models/dalle.py the scatter/gather ops). Two decode-attention
# implementations sit behind `paged_decode_attention`:
#
#   "gather"  materialize each row's logical view with one gather and run
#             the EXACT `flash_decode_attention` kernel above. Same tile
#             boundaries, same online-softmax accumulation order — so the
#             paged engine is bit-for-bit identical to the slotted one
#             (the parity contract tests/test_paging.py pins). Costs one
#             transient contiguous copy of the virtual cache per dispatch.
#   "kernel"  the true paged kernel: the page table rides scalar prefetch
#             and the K/V index maps dereference it per grid step, so a row
#             at position p streams only its ceil(p/page_size) live pages
#             out of HBM — no contiguous copy ever materializes. Tile size
#             equals the page size, so its accumulation ORDER differs from
#             the slotted kernel's; it matches the gather oracle to fp32
#             tolerance (pinned), not bit-for-bit.
#
# Default is "gather" (bit-exactness is the serving stack's contract and
# CPU-hosted tests exercise it end to end); flip `PAGED_DECODE_IMPL` or set
# DALLE_PAGED_DECODE_IMPL=kernel to arm the bandwidth-optimal path on TPU.

PAGED_DECODE_IMPL = os.environ.get("DALLE_PAGED_DECODE_IMPL", "gather")


def paged_gather(pages: jnp.ndarray, page_table: jnp.ndarray, vlen: int):
    """Contiguous per-row view of a paged K/V pool.

    pages: [P, H, page_size, D]; page_table: [B, n_pages] int32 physical
    page per logical block. Returns [B, H, vlen, D] — the first `vlen`
    positions of each row's logical sequence (positions no table entry was
    ever written for come from the garbage page; callers mask them).
    """
    b, n_pages = page_table.shape
    _, h, bs, d = pages.shape
    g = pages[page_table]  # [B, n_pages, H, bs, D]
    g = g.transpose(0, 2, 1, 3, 4).reshape(b, h, n_pages * bs, d)
    return g[:, :, :vlen, :]


def _paged_decode_kernel(lengths_ref, pt_ref, *refs, **kw):
    """Same online-softmax body as `_decode_kernel`; the page table ref is
    consumed by the K/V BlockSpec index maps, not the body."""
    del pt_ref
    _decode_kernel(lengths_ref, *refs, **kw)


def paged_flash_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,
    page_table: jnp.ndarray,
    *,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Flash decode directly over the paged pool: grid step (b, h, j) DMAs
    physical page `page_table[b, j]`, and steps past the row's last live
    block re-index that block so Pallas elides the copy (the same dead-tile
    trick as the contiguous kernel). The causal-over-prefix mask is
    identical to `flash_decode_attention`'s.

    q: [B, H, n, D]; k_pages/v_pages: [P, H, page_size, D]; lengths: [B]
    live positions including the current chunk; page_table: [B, n_pages].
    Tile size == page_size (TPU wants page_size a multiple of 8 and D of
    128 off interpret mode). `k_scale`/`v_scale` ([P, H, page_size] fp32)
    mark an int8 pool — scale pages ride the SAME page-table indirection
    and dequant happens in kernel. fp32 accumulation; decode-only, no VJP.
    """
    b, h, n, d = q.shape
    p_total, hk, page_size, dk = k_pages.shape
    assert k_pages.shape == v_pages.shape and (hk, dk) == (h, d), (
        q.shape, k_pages.shape, v_pages.shape,
    )
    n_pages = page_table.shape[1]
    assert page_table.shape == (b, n_pages), (page_table.shape, b)
    quantized = k_scale is not None
    assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
    if quantized:
        assert k_scale.shape == v_scale.shape == (p_total, h, page_size), (
            k_scale.shape, (p_total, h, page_size),
        )
    scale = d**-0.5 if sm_scale is None else sm_scale
    interp = _use_interpret() if interpret is None else interpret

    qp = _pad_to(q, 2, _MIN_BLOCK_Q)
    bq = qp.shape[2]
    lengths = jnp.clip(lengths.astype(jnp.int32), 0, n_pages * page_size)
    page_table = page_table.astype(jnp.int32)

    kernel = functools.partial(
        _paged_decode_kernel,
        sm_scale=scale,
        block_k=page_size,
        n_real_q=n,
        nk_blocks=n_pages,
        quantized=quantized,
    )
    qspec = pl.BlockSpec(
        (1, 1, bq, d), lambda b_, h_, j, lens, pt: (b_, h_, 0, 0)
    )

    def kv_idx(b_, h_, j, lens, pt):
        # dead steps re-index the row's last live page -> copy elided
        jc = jnp.minimum(j, _last_live_block(lens[b_], page_size))
        return (pt[b_, jc], h_, 0, 0)

    kvspec = pl.BlockSpec((1, 1, page_size, d), kv_idx)
    in_specs = [qspec, kvspec, kvspec]
    operands = [qp, k_pages, v_pages]
    if quantized:
        def sv_idx(b_, h_, j, lens, pt):
            jc = jnp.minimum(j, _last_live_block(lens[b_], page_size))
            return (pt[b_, jc], h_, 0)

        svspec = pl.BlockSpec((1, 1, page_size), sv_idx)
        in_specs = [qspec, kvspec, svspec, kvspec, svspec]
        operands = [
            qp, k_pages, k_scale.astype(jnp.float32),
            v_pages, v_scale.astype(jnp.float32),
        ]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, n_pages),
            in_specs=in_specs,
            out_specs=qspec,
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interp,
    )(lengths, page_table, *operands)
    return out[:, :, :n, :]


def _sparse_paged_decode_kernel(
    lengths_ref, pt_ref, live_ref, bmap_ref, q_ref, *refs, **kw
):
    """Paged sparse body: page table + block map feed the index maps."""
    del pt_ref, bmap_ref
    _decode_kernel(lengths_ref, q_ref, *refs, live_ref=live_ref, **kw)


def block_sparse_paged_flash_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,
    page_table: jnp.ndarray,
    block_bitmap: jnp.ndarray,
    *,
    sm_scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """`paged_flash_decode_attention` with policy tile skipping at PAGE
    granularity: block_bitmap is [B, n_pages] (one bit per page-table
    entry), and a dead page is never dereferenced — its grid step
    re-indexes the nearest preceding live page through the block map, so
    the physical-page DMA is elided along with the compute. An all-ones
    bitmap is bit-identical to `paged_flash_decode_attention`. int8 scale
    pages ride the same indirection and skip with their page."""
    b, h, n, d = q.shape
    p_total, hk, page_size, dk = k_pages.shape
    assert k_pages.shape == v_pages.shape and (hk, dk) == (h, d), (
        q.shape, k_pages.shape, v_pages.shape,
    )
    n_pages = page_table.shape[1]
    assert page_table.shape == (b, n_pages), (page_table.shape, b)
    assert block_bitmap.shape == (b, n_pages), (
        f"block_bitmap {block_bitmap.shape} != ({b}, {n_pages})"
    )
    quantized = k_scale is not None
    assert (k_scale is None) == (v_scale is None), "pass both scales or neither"
    if quantized:
        assert k_scale.shape == v_scale.shape == (p_total, h, page_size), (
            k_scale.shape, (p_total, h, page_size),
        )
    scale = d**-0.5 if sm_scale is None else sm_scale
    interp = _use_interpret() if interpret is None else interpret

    qp = _pad_to(q, 2, _MIN_BLOCK_Q)
    bq = qp.shape[2]
    lengths = jnp.clip(lengths.astype(jnp.int32), 0, n_pages * page_size)
    page_table = page_table.astype(jnp.int32)
    live_map, block_map = _sparse_maps(
        lengths, block_bitmap, page_size, n_pages
    )

    kernel = functools.partial(
        _sparse_paged_decode_kernel,
        sm_scale=scale,
        block_k=page_size,
        n_real_q=n,
        nk_blocks=n_pages,
        quantized=quantized,
    )
    qspec = pl.BlockSpec(
        (1, 1, bq, d),
        lambda b_, h_, j, lens, pt, live, bmap: (b_, h_, 0, 0),
    )

    def kv_idx(b_, h_, j, lens, pt, live, bmap):
        # dead steps re-index the nearest preceding live PAGE -> copy elided
        return (pt[b_, bmap[b_, j]], h_, 0, 0)

    kvspec = pl.BlockSpec((1, 1, page_size, d), kv_idx)
    in_specs = [qspec, kvspec, kvspec]
    operands = [qp, k_pages, v_pages]
    if quantized:
        def sv_idx(b_, h_, j, lens, pt, live, bmap):
            return (pt[b_, bmap[b_, j]], h_, 0)

        svspec = pl.BlockSpec((1, 1, page_size), sv_idx)
        in_specs = [qspec, kvspec, svspec, kvspec, svspec]
        operands = [
            qp, k_pages, k_scale.astype(jnp.float32),
            v_pages, v_scale.astype(jnp.float32),
        ]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(b, h, n_pages),
            in_specs=in_specs,
            out_specs=qspec,
            scratch_shapes=[
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interp,
    )(lengths, page_table, live_map, block_map, *operands)
    return out[:, :, :n, :]


def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,
    page_table: jnp.ndarray,
    vlen: int,
    *,
    impl: Optional[str] = None,
    sm_scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    block_bitmap: Optional[jnp.ndarray] = None,
    sparse_block: Optional[int] = None,
) -> jnp.ndarray:
    """Flash-path dispatch for the paged cache — see the section comment
    above for the "gather" (bit-exact) vs "kernel" (bandwidth-optimal)
    trade. `vlen` is the virtual contiguous length the gather path crops
    to (the slotted cache's max_len, so tile boundaries match exactly).
    int8 pools pass their [P, H, page_size] scale pools: the gather path
    gathers int8 pages + scales and hands BOTH to the contiguous kernel
    (in-kernel dequant, identical math to the slotted quantized path),
    keeping the slotted-vs-paged parity contract on the quantized cache.

    `block_bitmap` ([B, ceil(vlen/sparse_block)], with `sparse_block` the
    policy's tile width) arms policy skipping: the gather path hands it to
    the contiguous sparse kernel at `sparse_block` granularity (same tile
    boundaries as the slotted engine, so paged-vs-slotted parity holds
    under sparsity too); the "kernel" path re-expands it to PAGE
    granularity (sparse_block must be a page_size multiple) so dead pages
    are never dereferenced through the table."""
    impl = PAGED_DECODE_IMPL if impl is None else impl
    if impl == "gather":
        k = paged_gather(k_pages, page_table, vlen)
        v = paged_gather(v_pages, page_table, vlen)
        kw = {}
        if k_scale is not None:
            kw = {
                "k_scale": paged_gather(
                    k_scale[..., None], page_table, vlen
                )[..., 0],
                "v_scale": paged_gather(
                    v_scale[..., None], page_table, vlen
                )[..., 0],
            }
        if block_bitmap is not None:
            assert sparse_block is not None, "sparse_block rides block_bitmap"
            return block_sparse_flash_decode_attention(
                q, k, v, lengths, block_bitmap,
                sm_scale=sm_scale, block_k=sparse_block, **kw,
            )
        return flash_decode_attention(q, k, v, lengths, sm_scale=sm_scale, **kw)
    assert impl == "kernel", f"unknown paged decode impl {impl!r}"
    if block_bitmap is not None:
        assert sparse_block is not None, "sparse_block rides block_bitmap"
        page_size = k_pages.shape[2]
        n_pages = page_table.shape[1]
        assert sparse_block % page_size == 0, (
            f"sparse_block {sparse_block} must be a multiple of "
            f"page_size {page_size} for the paged kernel"
        )
        bm = jnp.repeat(block_bitmap, sparse_block // page_size, axis=1)
        if bm.shape[1] < n_pages:
            # trailing pages beyond the policy's bitmap window: dead (the
            # live-length AND inside the kernel keeps this conservative)
            bm = jnp.pad(bm, ((0, 0), (0, n_pages - bm.shape[1])))
        else:
            bm = bm[:, :n_pages]
        return block_sparse_paged_flash_decode_attention(
            q, k_pages, v_pages, lengths, page_table, bm,
            sm_scale=sm_scale, k_scale=k_scale, v_scale=v_scale,
        )
    return paged_flash_decode_attention(
        q, k_pages, v_pages, lengths, page_table, sm_scale=sm_scale,
        k_scale=k_scale, v_scale=v_scale,
    )


# ------------------------------------------------- mesh-sharded dispatch
#
# A Pallas call is a single-device program: GSPMD cannot partition it, so
# a mesh-sharded serving engine must split the kernel EXPLICITLY. Heads
# are the natural cut (SNIPPETS.md [1]: shard_map-wrapped flash/paged
# attention with P(data, model, ...) specs): decode attention is
# head-independent, so each device runs the unmodified kernel over its
# own head shard and the concatenation over heads is exact — the sharded
# kernel is bit-for-bit the unsharded one, preserving the serving
# stack's decode-composition-invariance contract.


def sharded_flash_decode_attention(
    mesh,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    head_axis: str = "tp",
    sm_scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    block_bitmap: Optional[jnp.ndarray] = None,
    sparse_block: Optional[int] = None,
):
    """`flash_decode_attention` split over `head_axis` of `mesh` via
    shard_map (`parallel/mesh.py`'s compat wrapper keeps it running on
    jax 0.4.37). Heads that don't divide the axis fall back to the
    unsharded kernel — same drop-to-replicated posture as
    `serving_partition`'s divisibility rule. int8 caches hand their
    [B, H, S] scale leaves along — per-head scales split with the heads
    (reduction-free), so the sharded quantized kernel stays bit-identical
    to the unsharded quantized one. `block_bitmap`/`sparse_block` arm
    policy tile skipping: the bitmap is head-independent so it REPLICATES
    (P()) like the lengths and every head shard skips the same tiles."""
    from dalle_pytorch_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    def dispatch(q_, k_, v_, lens_, bm_=None, ks_=None, vs_=None):
        kw = {"sm_scale": sm_scale, "k_scale": ks_, "v_scale": vs_}
        if bm_ is not None:
            return block_sparse_flash_decode_attention(
                q_, k_, v_, lens_, bm_,
                block_k=128 if sparse_block is None else sparse_block, **kw,
            )
        return flash_decode_attention(q_, k_, v_, lens_, **kw)

    h = q.shape[1]
    # a mesh without the axis (custom caller-built meshes) falls back
    # unsharded rather than raising at trace time inside the chunk program
    axis_n = dict(mesh.shape).get(head_axis, 1)
    if axis_n == 1 or h % axis_n != 0:
        return dispatch(q, k, v, lengths, block_bitmap, k_scale, v_scale)
    spec = P(None, head_axis, None, None)
    args = [q, k, v, lengths]
    in_specs = [spec, spec, spec, P()]
    if block_bitmap is not None:
        args.append(block_bitmap)
        in_specs.append(P())
    if k_scale is not None:
        sspec = P(None, head_axis, None)
        args += [k_scale, v_scale]
        in_specs += [sspec, sspec]

    def call(q_, k_, v_, lens_, *rest):
        rest = list(rest)
        bm_ = rest.pop(0) if block_bitmap is not None else None
        ks_, vs_ = rest if rest else (None, None)
        return dispatch(q_, k_, v_, lens_, bm_, ks_, vs_)

    fn = shard_map(
        call,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        check_vma=False,
    )
    return fn(*args)


def sharded_paged_decode_attention(
    mesh,
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    lengths: jnp.ndarray,
    page_table: jnp.ndarray,
    vlen: int,
    *,
    head_axis: str = "tp",
    impl: Optional[str] = None,
    sm_scale: Optional[float] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    block_bitmap: Optional[jnp.ndarray] = None,
    sparse_block: Optional[int] = None,
):
    """`paged_decode_attention` split over `head_axis` of `mesh`: the page
    pool shards at its HEAD axis (axis 1 of [P, H, page_size, D]) — pages
    stay whole per device because the host page table addresses physical
    pages globally — and the table + lengths replicate, so every device
    dereferences the same logical->physical mapping over its own head
    shard. Both impls ("gather" and the per-page-DMA "kernel") run the
    unmodified single-device code per shard; the head concat is exact, so
    sharded paged decode is bit-identical to single-device paged decode.
    Never split the PAGE axis: a page-split pool silently reads other
    rows' pages through the global table (tracelint TL008 flags it).
    `block_bitmap`/`sparse_block` replicate (P()) like the page table —
    policy skipping is head-independent."""
    from dalle_pytorch_tpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    def dispatch(q_, kp_, vp_, lens_, pt_, bm_=None, ks_=None, vs_=None):
        return paged_decode_attention(
            q_, kp_, vp_, lens_, pt_, vlen, impl=impl, sm_scale=sm_scale,
            k_scale=ks_, v_scale=vs_,
            block_bitmap=bm_, sparse_block=sparse_block,
        )

    h = q.shape[1]
    axis_n = dict(mesh.shape).get(head_axis, 1)
    if axis_n == 1 or h % axis_n != 0:
        return dispatch(
            q, k_pages, v_pages, lengths, page_table,
            block_bitmap, k_scale, v_scale,
        )
    spec = P(None, head_axis, None, None)
    args = [q, k_pages, v_pages, lengths, page_table]
    in_specs = [spec, spec, spec, P(), P()]
    if block_bitmap is not None:
        args.append(block_bitmap)
        in_specs.append(P())
    if k_scale is not None:
        sspec = P(None, head_axis, None)
        args += [k_scale, v_scale]
        in_specs += [sspec, sspec]

    def call(q_, kp_, vp_, lens_, pt_, *rest):
        rest = list(rest)
        bm_ = rest.pop(0) if block_bitmap is not None else None
        ks_, vs_ = rest if rest else (None, None)
        return dispatch(q_, kp_, vp_, lens_, pt_, bm_, ks_, vs_)

    fn = shard_map(
        call,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=spec,
        check_vma=False,
    )
    return fn(*args)
