"""Pallas TPU flash attention with static-mask block sparsity.

This is the TPU-native replacement for the reference's DeepSpeed CUDA/Triton
block-sparse kernel (`/root/reference/dalle_pytorch/attention.py:339-398`,
built via `DS_BUILD_SPARSE_ATTN=1`, `install_deepspeed.sh`) and the
long-sequence fast path for every other attention pattern (full causal,
axial row/col, conv-like — `attention.py:39,103,225`), all of which are
static token masks in this framework (ops/masks.py).

Design:
  * classic flash attention: q blocks stay resident, k/v blocks stream
    through VMEM while an online-softmax accumulator (m, l, acc) builds the
    exact result — O(N) memory instead of O(N^2);
  * the static mask is analyzed host-side into a per-block occupancy layout;
    fully-empty (q-block, k-block) tiles are skipped entirely (`lax.cond`),
    so axial/conv/block-sparse patterns get real compute savings, and
    partially-occupied tiles apply the token-level mask streamed from the
    mask operand;
  * with no mask and `causal=True`, the k-loop bound is the block-triangle
    cut — no mask tensor ever materializes;
  * full custom-VJP: backward recomputes attention blockwise from the saved
    log-sum-exp (two kernels: dq over q blocks, dk/dv over k blocks), the
    same recompute-instead-of-store trade the reference's reversible layers
    make (`reversible.py:57-127`);
  * fp32 accumulation regardless of input dtype (bf16 inputs stay bf16 on
    the MXU operands).

Interpret mode (CPU) is selected automatically off-TPU so the full test
suite exercises these kernels without hardware.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; support both
#: so the kernels run on whichever jax the image bakes in
CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

#: whether this jax can force the LIBRARY TPU kernel through the
#: interpreter on CPU (`pltpu.force_tpu_interpret_mode`). The in-repo
#: kernels pass `interpret=` per pallas_call and don't need it; the
#: library kernel's internal pallas_calls (and its custom-VJP backward's)
#: can only be interpreted via this context manager, so without it
#: `lib_flash` is TPU-hardware-only (tests skip accordingly).
HAS_FORCE_TPU_INTERPRET = hasattr(pltpu, "force_tpu_interpret_mode")


def _use_interpret() -> bool:
    """Compile the kernel on real TPU hardware, interpret elsewhere.

    Checks the device kind, not just the backend name: tunneled/plugin
    backends (e.g. "axon") expose a real TPU under a different platform
    string, and interpret mode there would silently bench the emulator.
    """
    if jax.default_backend() == "tpu":
        return False
    try:
        return "tpu" not in jax.devices()[0].device_kind.lower()
    except Exception:
        return True


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _causal_last_live_k(qi, block_q, block_k):
    """Last k-block index a causal q block `qi` attends to. Single source
    of truth for BOTH the kernels' liveness predicates and the DMA-skip
    index maps — they must stay in lockstep (a skip for a step the kernel
    treats as live would load stale data silently)."""
    return ((qi + 1) * block_q - 1) // block_k


def _causal_first_live_q(ki, block_k, block_q):
    """First q-block index that attends to causal k block `ki` (transposed
    twin of `_causal_last_live_k`)."""
    return (ki * block_k) // block_q


def mask_block_layout(mask: np.ndarray, block_q: int, block_k: int):
    """(padded token mask, [nq, nk] int32 occupancy layout) for a static mask.

    Every real query row must attend to at least one key: with a finite
    NEG_INF sentinel an all-masked row would softmax to a uniform average of
    its tile's values instead of the dense oracle's uniform-over-all-keys
    garbage — neither is meaningful, so we reject the mask outright.
    """
    mask = np.asarray(mask, dtype=bool)
    empty = ~mask.any(axis=1)
    if empty.any():
        raise ValueError(
            f"static attention mask has {int(empty.sum())} fully-masked query "
            f"row(s) (first: {int(np.argmax(empty))}); every query must be "
            "allowed to attend to at least one key"
        )
    nq = math.ceil(mask.shape[0] / block_q)
    nk = math.ceil(mask.shape[1] / block_k)
    padded = np.zeros((nq * block_q, nk * block_k), dtype=bool)
    padded[: mask.shape[0], : mask.shape[1]] = mask
    blocks = padded.reshape(nq, block_q, nk, block_k)
    layout = blocks.any(axis=(1, 3)).astype(np.int32)
    return padded, layout


# ------------------------------------------------------------------ forward


def _fwd_kernel(
    *refs,
    sm_scale: float,
    block_k: int,
    causal: bool,
    has_mask: bool,
    n_real_k: int,
    nk_blocks: int,
):
    """Grid (b, h, qi, ki): the q block stays put over the inner ki steps
    while [block_k, d] k/v tiles stream through (auto double-buffered), so
    VMEM holds one tile of each operand regardless of sequence length. The
    online-softmax state (m, l, acc) carries across ki in fp32 VMEM
    scratch and the normalized output flushes on the last step."""
    if has_mask:
        (layout_ref, q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
         m_ref, l_ref, acc_ref) = refs
    else:
        layout_ref = mask_ref = None
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref = refs

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    bq = q_ref.shape[2]

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if causal and not has_mask:
        # block-triangle cut: k blocks strictly above the diagonal never run
        live = ki <= _causal_last_live_k(qi, bq, block_k)
    elif has_mask:
        live = layout_ref[qi, ki] != 0
    else:
        live = True

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [bq, d]
        kb = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        col = ki * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        if causal and not has_mask:
            row = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            s = jnp.where(row >= col, s, NEG_INF)
        if has_mask:
            s = jnp.where(mask_ref[...], s, NEG_INF)
        if n_real_k % block_k != 0:  # mask key padding
            s = jnp.where(col < n_real_k, s, NEG_INF)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jnp.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk_blocks - 1)
    def _flush():
        safe_l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[...] + jnp.log(safe_l)  # [bq, 1]


def _flash_forward(
    q, k, v, mask_pad, layout, *,
    sm_scale, block_q, block_k, causal, n_real_q, n_real_k, interpret,
):
    b, h, n_q, d = q.shape
    n_k = k.shape[2]
    nq_blocks = n_q // block_q
    nk_blocks = n_k // block_k
    has_mask = mask_pad is not None

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        block_k=block_k,
        causal=causal,
        has_mask=has_mask,
        n_real_k=n_real_k,
        nk_blocks=nk_blocks,
    )
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    if causal and not has_mask:
        # Causal DMA skip: k tiles strictly above the block diagonal are
        # dead (the kernel predicates compute with `live`), but a naive
        # j-index map still streams them in — ~2x K/V tile traffic at the
        # diagonal-heavy DALL-E lengths. Remapping every dead step to the
        # LAST live tile makes consecutive dead steps index the same
        # block, and Pallas elides the copy when the block index repeats,
        # so the dead region costs zero DMA. (min(j, ...) also keeps the
        # index in range: the clamp target never exceeds j itself.)
        k_idx = lambda b_, h_, i, j: (
            b_, h_, jnp.minimum(j, _causal_last_live_k(i, block_q, block_k)), 0
        )
    else:
        k_idx = lambda b_, h_, i, j: (b_, h_, j, 0)
    kspec = pl.BlockSpec((1, 1, block_k, d), k_idx)
    in_specs = [qspec, kspec, kspec]
    operands = [q, k, v]
    if has_mask:
        in_specs = [
            pl.BlockSpec(memory_space=pltpu.SMEM),  # layout, whole array
            *in_specs,
            pl.BlockSpec((block_q, block_k), lambda b_, h_, i, j: (i, j)),
        ]
        operands = [layout, q, k, v, mask_pad]

    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq_blocks, nk_blocks),
        in_specs=in_specs,
        out_specs=[
            qspec,
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_q, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, n_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "parallel", "arbitrary",
            ),
        ),
        interpret=interpret,
    )(*operands)
    return o, lse


# ----------------------------------------------------------------- backward


def _dq_kernel(
    *refs, sm_scale, block_k, causal, has_mask, n_real_k, nk_blocks,
):
    """Grid (b, h, qi, ki): the q block stays put over the inner ki steps
    while [block_k, d] k/v tiles stream through — VMEM holds one tile of
    each operand regardless of sequence length (the previous revision gave
    every program instance the ENTIRE K/V, which scales VMEM with n_k).
    dq accumulates in an fp32 VMEM scratch across ki and flushes on the
    last step."""
    if has_mask:
        (layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         mask_ref, dq_ref, acc_ref) = refs
    else:
        layout_ref = mask_ref = None
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref = refs

    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    bq = q_ref.shape[2]
    if causal and not has_mask:
        # k blocks strictly above the block triangle contribute nothing
        live = ki <= _causal_last_live_k(qi, bq, block_k)
    elif has_mask:
        live = layout_ref[qi, ki] != 0
    else:
        live = True

    @pl.when(live)
    def _attend():
        q = q_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        kb = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * sm_scale
        col = ki * block_k + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        if causal and not has_mask:
            row = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            s = jnp.where(row >= col, s, NEG_INF)
        if has_mask:
            s = jnp.where(mask_ref[...], s, NEG_INF)
        if n_real_k % block_k != 0:
            s = jnp.where(col < n_real_k, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        acc_ref[...] += jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    @pl.when(ki == nk_blocks - 1)
    def _flush():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _dkv_kernel(
    *refs, sm_scale, block_q, causal, has_mask, n_real_q, n_real_k,
    block_k, nq_blocks,
):
    """Grid (b, h, ki, qi): the k/v blocks stay put over the inner qi steps
    while [block_q, d] q/do tiles stream through (bounded VMEM — see
    `_dq_kernel`). dk/dv accumulate in fp32 VMEM scratch across qi and
    flush on the last step."""
    if has_mask:
        (layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         mask_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        layout_ref = mask_ref = None
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
         dk_acc, dv_acc) = refs

    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    bk = k_ref.shape[2]
    if causal and not has_mask:
        # q blocks strictly below the k-block diagonal start never attend
        live = qi >= _causal_first_live_q(ki, bk, block_q)
    elif has_mask:
        live = layout_ref[qi, ki] != 0
    else:
        live = True

    @pl.when(live)
    def _attend():
        kb = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        vb = v_ref[0, 0].astype(jnp.float32)
        qb = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        dob = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # [bq, 1]
        delta = delta_ref[0, 0]
        col = ki * bk + lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * sm_scale
        if causal and not has_mask:
            row = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0
            )
            s = jnp.where(row >= col, s, NEG_INF)
        if has_mask:
            s = jnp.where(mask_ref[...], s, NEG_INF)
        if n_real_k % bk != 0:
            s = jnp.where(col < n_real_k, s, NEG_INF)
        if n_real_q % block_q != 0:  # padded q rows have garbage lse: drop them
            row = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0
            )
            s = jnp.where(row < n_real_q, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_acc[...] += jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_acc[...] += jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)

    @pl.when(qi == nq_blocks - 1)
    def _flush():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(
    res, g, *, sm_scale, block_q, block_k, causal, n_real_q, n_real_k, interpret,
):
    q, k, v, o, lse, mask_pad, layout = res
    do = g
    b, h, n_q, d = q.shape
    n_k = k.shape[2]
    has_mask = mask_pad is not None

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )

    nq_blocks = n_q // block_q
    nk_blocks = n_k // block_k

    # Both passes run a 4D grid with the reduction as the INNER dimension
    # and fp32 VMEM scratch carrying the accumulator across its steps; every
    # operand arrives as one [block, d] tile per step (auto double-buffered
    # by Pallas), so VMEM use is flat in sequence length — the previous
    # revision's whole-K/V ("kfull") BlockSpecs scaled VMEM with n_k and
    # became hostile at exactly the long sequences flash exists for.

    # dq: grid (b, h, qi, ki) — q-indexed tiles ignore ki, k-indexed use ki
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    if causal and not has_mask:
        # causal DMA skip (see _flash_forward): dead above-diagonal steps
        # re-index the last live k tile so Pallas elides their copies
        k_idx = lambda b_, h_, i, j: (
            b_, h_, jnp.minimum(j, _causal_last_live_k(i, block_q, block_k)), 0
        )
    else:
        k_idx = lambda b_, h_, i, j: (b_, h_, j, 0)
    kspec = pl.BlockSpec((1, 1, block_k, d), k_idx)
    rowspec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    dq_in = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    dq_ops = [q, k, v, do, lse, delta]
    if has_mask:
        dq_in = [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            *dq_in,
            pl.BlockSpec((block_q, block_k), lambda b_, h_, i, j: (i, j)),
        ]
        dq_ops = [layout, *dq_ops, mask_pad]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, block_k=block_k, causal=causal,
            has_mask=has_mask, n_real_k=n_real_k, nk_blocks=nk_blocks,
        ),
        grid=(b, h, nq_blocks, nk_blocks),
        in_specs=dq_in,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "parallel", "arbitrary",
            ),
        ),
        interpret=interpret,
    )(*dq_ops)

    # dk/dv: grid (b, h, ki, qi) — k-indexed tiles ignore qi
    kspec2 = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    if causal and not has_mask:
        # causal DMA skip, transposed: for k block i the dead q tiles are
        # the PREFIX qi < first_live; clamp re-indexes them to the first
        # live tile so their copies are elided. The outer min keeps the
        # index in range when n_k > n_q (a fully-dead k row's first_live
        # would point past the last q block — the whole row is dead, so
        # any in-range tile serves; without the min the DMA reads out of
        # bounds)
        q_idx = lambda b_, h_, i, j: (
            b_, h_,
            jnp.minimum(
                jnp.maximum(j, _causal_first_live_q(i, block_k, block_q)),
                nq_blocks - 1,
            ),
            0,
        )
    else:
        q_idx = lambda b_, h_, i, j: (b_, h_, j, 0)
    qspec2 = pl.BlockSpec((1, 1, block_q, d), q_idx)
    rowspec2 = pl.BlockSpec((1, 1, block_q, 1), q_idx)
    dkv_in = [qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2]
    dkv_ops = [q, k, v, do, lse, delta]
    if has_mask:
        dkv_in = [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            *dkv_in,
            pl.BlockSpec((block_q, block_k), lambda b_, h_, i, j: (j, i)),
        ]
        dkv_ops = [layout, *dkv_ops, mask_pad]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, block_q=block_q, causal=causal,
            has_mask=has_mask, n_real_q=n_real_q, n_real_k=n_real_k,
            block_k=block_k, nq_blocks=nq_blocks,
        ),
        grid=(b, h, nk_blocks, nq_blocks),
        in_specs=dkv_in,
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "parallel", "arbitrary",
            ),
        ),
        interpret=interpret,
    )(*dkv_ops)
    return dq, dk, dv


# -------------------------------------------------------------- public API


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[np.ndarray] = None,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention over [B, H, N, D] with an optional STATIC token mask.

    `mask` must be a host-side numpy bool array [Nq, Nk] (True = attend); it
    is analyzed into a block-occupancy layout so empty tiles are skipped.
    Every query row must have at least one attendable key (enforced —
    see `mask_block_layout`). When `mask` is None and `causal=True`,
    causality is enforced in-kernel with a block-triangle loop bound and no
    materialized mask. Differentiable (custom VJP, recompute-based backward).
    """
    assert q.ndim == 4, f"expected [B,H,N,D], got {q.shape}"
    n_q, n_k = q.shape[2], k.shape[2]
    d = q.shape[3]
    block_q = min(block_q, max(n_q, 1))
    block_k = min(block_k, max(n_k, 1))
    scale = d**-0.5 if sm_scale is None else sm_scale
    interp = _use_interpret() if interpret is None else interpret

    if mask is not None:
        assert mask.shape == (n_q, n_k), f"mask {mask.shape} != {(n_q, n_k)}"
        mask_pad_np, layout_np = mask_block_layout(mask, block_q, block_k)
        mask_pad = jnp.asarray(mask_pad_np)
        layout = jnp.asarray(layout_np)
    else:
        mask_pad = layout = None

    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)

    static = dict(
        sm_scale=scale, block_q=block_q, block_k=block_k,
        causal=causal and mask is None, n_real_q=n_q, n_real_k=n_k,
        interpret=interp,
    )

    @jax.custom_vjp
    def _attn(q_, k_, v_):
        o, _ = _flash_forward(q_, k_, v_, mask_pad, layout, **static)
        return o

    def _attn_fwd(q_, k_, v_):
        o, lse = _flash_forward(q_, k_, v_, mask_pad, layout, **static)
        return o, (q_, k_, v_, o, lse, mask_pad, layout)

    def _attn_bwd(res, g):
        return _flash_backward(res, g, **static)

    _attn.defvjp(_attn_fwd, _attn_bwd)
    out = _attn(qp, kp, vp)
    return out[:, :, :n_q, :]


def lib_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """jax's library TPU flash kernel (pallas.ops.tpu.flash_attention)
    behind the in-repo calling convention ([B, H, N, D], scale d^-0.5).

    Alternative backend to the in-repo `flash_attention` for plain
    causal/full attention (no static-mask block skipping — the library
    kernel has no occupancy layout). Exists so the on-chip A/B
    (`scripts/pallas_onchip.py`) can pick whichever is faster on real
    hardware; differentiable (the library defines its own custom VJP).

    CPU caveat: the interpret guard below covers only the forward trace;
    the library's custom-VJP backward traces its own pallas_calls at grad
    time, so CPU *training* with lib_flash must run the whole grad inside
    `pltpu.force_tpu_interpret_mode()` (tests do). On TPU none of this
    applies. This is a TPU-hardware option; `flash` is the portable one.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _lib,
    )

    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if _use_interpret():
        if not HAS_FORCE_TPU_INTERPRET:
            raise NotImplementedError(
                'attn_impl="lib_flash" off-TPU needs '
                "pltpu.force_tpu_interpret_mode, which this jax does not "
                'provide — use attn_impl="flash" (the in-repo kernel '
                "interprets per-call) or run on TPU hardware"
            )
        with pltpu.force_tpu_interpret_mode():
            return _lib(q, k, v, causal=causal, sm_scale=scale)
    return _lib(q, k, v, causal=causal, sm_scale=scale)
