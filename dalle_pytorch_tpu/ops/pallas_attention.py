"""Pallas TPU flash attention with static-mask block sparsity.

This is the TPU-native replacement for the reference's DeepSpeed CUDA/Triton
block-sparse kernel (`/root/reference/dalle_pytorch/attention.py:339-398`,
built via `DS_BUILD_SPARSE_ATTN=1`, `install_deepspeed.sh`) and the
long-sequence fast path for every other attention pattern (full causal,
axial row/col, conv-like — `attention.py:39,103,225`), all of which are
static token masks in this framework (ops/masks.py).

Design:
  * classic flash attention: q blocks stay resident, k/v blocks stream
    through VMEM while an online-softmax accumulator (m, l, acc) builds the
    exact result — O(N) memory instead of O(N^2);
  * the static mask is analyzed host-side into a per-block occupancy layout;
    fully-empty (q-block, k-block) tiles are skipped entirely (`lax.cond`),
    so axial/conv/block-sparse patterns get real compute savings, and
    partially-occupied tiles apply the token-level mask streamed from the
    mask operand;
  * with no mask and `causal=True`, the k-loop bound is the block-triangle
    cut — no mask tensor ever materializes;
  * full custom-VJP: backward recomputes attention blockwise from the saved
    log-sum-exp (two kernels: dq over q blocks, dk/dv over k blocks), the
    same recompute-instead-of-store trade the reference's reversible layers
    make (`reversible.py:57-127`);
  * fp32 accumulation regardless of input dtype (bf16 inputs stay bf16 on
    the MXU operands).

Interpret mode (CPU) is selected automatically off-TPU so the full test
suite exercises these kernels without hardware.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    """Compile the kernel on real TPU hardware, interpret elsewhere.

    Checks the device kind, not just the backend name: tunneled/plugin
    backends (e.g. "axon") expose a real TPU under a different platform
    string, and interpret mode there would silently bench the emulator.
    """
    if jax.default_backend() == "tpu":
        return False
    try:
        return "tpu" not in jax.devices()[0].device_kind.lower()
    except Exception:
        return True


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def mask_block_layout(mask: np.ndarray, block_q: int, block_k: int):
    """(padded token mask, [nq, nk] int32 occupancy layout) for a static mask.

    Every real query row must attend to at least one key: with a finite
    NEG_INF sentinel an all-masked row would softmax to a uniform average of
    its tile's values instead of the dense oracle's uniform-over-all-keys
    garbage — neither is meaningful, so we reject the mask outright.
    """
    mask = np.asarray(mask, dtype=bool)
    empty = ~mask.any(axis=1)
    if empty.any():
        raise ValueError(
            f"static attention mask has {int(empty.sum())} fully-masked query "
            f"row(s) (first: {int(np.argmax(empty))}); every query must be "
            "allowed to attend to at least one key"
        )
    nq = math.ceil(mask.shape[0] / block_q)
    nk = math.ceil(mask.shape[1] / block_k)
    padded = np.zeros((nq * block_q, nk * block_k), dtype=bool)
    padded[: mask.shape[0], : mask.shape[1]] = mask
    blocks = padded.reshape(nq, block_q, nk, block_k)
    layout = blocks.any(axis=(1, 3)).astype(np.int32)
    return padded, layout


# ------------------------------------------------------------------ forward


def _fwd_kernel(
    *refs,
    sm_scale: float,
    block_k: int,
    causal: bool,
    has_mask: bool,
    n_real_k: int,
):
    if has_mask:
        layout_ref, q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref = refs
    else:
        layout_ref = mask_ref = None
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs

    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [bq, d]
    bq, d = q.shape
    n_k_pad = k_ref.shape[2]
    nk_blocks = n_k_pad // block_k

    def attend(ki, m, l, acc):
        start = ki * block_k
        kb = k_ref[0, 0, pl.ds(start, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(start, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        col = start + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        if causal and not has_mask:
            row = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            s = jnp.where(row >= col, s, NEG_INF)
        if has_mask:
            mb = mask_ref[:, pl.ds(start, block_k)]
            s = jnp.where(mb, s, NEG_INF)
        if n_real_k % block_k != 0:  # mask key padding
            s = jnp.where(col < n_real_k, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(
            p.astype(vb.dtype), vb, preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    def body(ki, carry):
        m, l, acc = carry
        if has_mask:
            return lax.cond(
                layout_ref[qi, ki] != 0,
                lambda c: attend(ki, *c),
                lambda c: c,
                (m, l, acc),
            )
        return attend(ki, m, l, acc)

    if causal and not has_mask:
        # block-triangle cut: k blocks strictly above the diagonal never run
        hi = lax.min(((qi + 1) * bq + block_k - 1) // block_k, nk_blocks)
    else:
        hi = nk_blocks

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, hi, body, (m0, l0, acc0))

    safe_l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / safe_l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(safe_l)  # [bq, 1]


def _flash_forward(
    q, k, v, mask_pad, layout, *,
    sm_scale, block_q, block_k, causal, n_real_q, n_real_k, interpret,
):
    b, h, n_q, d = q.shape
    n_k = k.shape[2]
    nq_blocks = n_q // block_q
    has_mask = mask_pad is not None

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        block_k=block_k,
        causal=causal,
        has_mask=has_mask,
        n_real_k=n_real_k,
    )
    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, n_k, d), lambda b_, h_, i: (b_, h_, 0, 0))
    in_specs = [qspec, kspec, kspec]
    operands = [q, k, v]
    if has_mask:
        in_specs = [
            pl.BlockSpec(memory_space=pltpu.SMEM),  # layout, whole array
            *in_specs,
            pl.BlockSpec((block_q, n_k), lambda b_, h_, i: (i, 0)),
        ]
        operands = [layout, q, k, v, mask_pad]

    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq_blocks),
        in_specs=in_specs,
        out_specs=[
            qspec,
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, n_q, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, n_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return o, lse


# ----------------------------------------------------------------- backward


def _dq_kernel(
    *refs, sm_scale, block_k, causal, has_mask, n_real_k,
):
    if has_mask:
        layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, dq_ref = refs
    else:
        layout_ref = mask_ref = None
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs

    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]  # [bq, 1]
    delta = delta_ref[0, 0]
    bq, d = q.shape
    nk_blocks = k_ref.shape[2] // block_k

    def attend(ki, dq):
        start = ki * block_k
        kb = k_ref[0, 0, pl.ds(start, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(start, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32) * sm_scale
        col = start + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        if causal and not has_mask:
            row = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            s = jnp.where(row >= col, s, NEG_INF)
        if has_mask:
            mb = mask_ref[:, pl.ds(start, block_k)]
            s = jnp.where(mb, s, NEG_INF)
        if n_real_k % block_k != 0:
            s = jnp.where(col < n_real_k, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return dq + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    def body(ki, dq):
        if has_mask:
            return lax.cond(
                layout_ref[qi, ki] != 0, lambda a: attend(ki, a), lambda a: a, dq
            )
        return attend(ki, dq)

    if causal and not has_mask:
        hi = lax.min(((qi + 1) * bq + block_k - 1) // block_k, nk_blocks)
    else:
        hi = nk_blocks

    dq = lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(
    *refs, sm_scale, block_q, causal, has_mask, n_real_q, n_real_k, block_k,
):
    if has_mask:
        layout_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, dk_ref, dv_ref = refs
    else:
        layout_ref = mask_ref = None
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref = refs

    ki = pl.program_id(2)
    kb = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
    vb = v_ref[0, 0].astype(jnp.float32)
    bk, d = kb.shape
    nq_blocks = q_ref.shape[2] // block_q
    col = ki * bk + lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)

    def attend(qi, dk, dv):
        start = qi * block_q
        qb = q_ref[0, 0, pl.ds(start, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(start, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(start, block_q), :]  # [bq, 1]
        delta = delta_ref[0, 0, pl.ds(start, block_q), :]
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * sm_scale
        if causal and not has_mask:
            row = start + lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            s = jnp.where(row >= col, s, NEG_INF)
        if has_mask:
            mb = mask_ref[pl.ds(start, block_q), :]
            s = jnp.where(mb, s, NEG_INF)
        if n_real_k % bk != 0:
            s = jnp.where(col < n_real_k, s, NEG_INF)
        if n_real_q % block_q != 0:  # padded q rows have garbage lse: drop them
            row = start + lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
            s = jnp.where(row < n_real_q, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jnp.dot(p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)
        return dk, dv

    def body(qi, carry):
        dk, dv = carry
        if has_mask:
            return lax.cond(
                layout_ref[qi, ki] != 0,
                lambda c: attend(qi, *c),
                lambda c: c,
                (dk, dv),
            )
        return attend(qi, dk, dv)

    if causal and not has_mask:
        # q blocks strictly below the k-block diagonal start never attend here
        lo = (ki * bk) // block_q
    else:
        lo = 0

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = lax.fori_loop(lo, nq_blocks, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_backward(
    res, g, *, sm_scale, block_q, block_k, causal, n_real_q, n_real_k, interpret,
):
    q, k, v, o, lse, mask_pad, layout = res
    do = g
    b, h, n_q, d = q.shape
    n_k = k.shape[2]
    has_mask = mask_pad is not None

    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )

    qspec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    qfull = pl.BlockSpec((1, 1, n_q, d), lambda b_, h_, i: (b_, h_, 0, 0))
    kspec = pl.BlockSpec((1, 1, block_k, d), lambda b_, h_, i: (b_, h_, i, 0))
    kfull = pl.BlockSpec((1, 1, n_k, d), lambda b_, h_, i: (b_, h_, 0, 0))
    rowspec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i: (b_, h_, i, 0))
    rowfull = pl.BlockSpec((1, 1, n_q, 1), lambda b_, h_, i: (b_, h_, 0, 0))

    # dq: grid over q blocks
    dq_in = [qspec, kfull, kfull, qspec, rowspec, rowspec]
    dq_ops = [q, k, v, do, lse, delta]
    if has_mask:
        dq_in = [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            *dq_in,
            pl.BlockSpec((block_q, n_k), lambda b_, h_, i: (i, 0)),
        ]
        dq_ops = [layout, *dq_ops, mask_pad]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, sm_scale=sm_scale, block_k=block_k, causal=causal,
            has_mask=has_mask, n_real_k=n_real_k,
        ),
        grid=(b, h, n_q // block_q),
        in_specs=dq_in,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(*dq_ops)

    # dk/dv: grid over k blocks
    dkv_in = [qfull, kspec, kspec, qfull, rowfull, rowfull]
    dkv_ops = [q, k, v, do, lse, delta]
    if has_mask:
        dkv_in = [
            pl.BlockSpec(memory_space=pltpu.SMEM),
            *dkv_in,
            pl.BlockSpec((n_q, block_k), lambda b_, h_, i: (0, i)),
        ]
        dkv_ops = [layout, *dkv_ops, mask_pad]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, sm_scale=sm_scale, block_q=block_q, causal=causal,
            has_mask=has_mask, n_real_q=n_real_q, n_real_k=n_real_k,
            block_k=block_k,
        ),
        grid=(b, h, n_k // block_k),
        in_specs=dkv_in,
        out_specs=[kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=interpret,
    )(*dkv_ops)
    return dq, dk, dv


# -------------------------------------------------------------- public API


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[np.ndarray] = None,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention over [B, H, N, D] with an optional STATIC token mask.

    `mask` must be a host-side numpy bool array [Nq, Nk] (True = attend); it
    is analyzed into a block-occupancy layout so empty tiles are skipped.
    Every query row must have at least one attendable key (enforced —
    see `mask_block_layout`). When `mask` is None and `causal=True`,
    causality is enforced in-kernel with a block-triangle loop bound and no
    materialized mask. Differentiable (custom VJP, recompute-based backward).
    """
    assert q.ndim == 4, f"expected [B,H,N,D], got {q.shape}"
    n_q, n_k = q.shape[2], k.shape[2]
    d = q.shape[3]
    block_q = min(block_q, max(n_q, 1))
    block_k = min(block_k, max(n_k, 1))
    scale = d**-0.5 if sm_scale is None else sm_scale
    interp = _use_interpret() if interpret is None else interpret

    if mask is not None:
        assert mask.shape == (n_q, n_k), f"mask {mask.shape} != {(n_q, n_k)}"
        mask_pad_np, layout_np = mask_block_layout(mask, block_q, block_k)
        mask_pad = jnp.asarray(mask_pad_np)
        layout = jnp.asarray(layout_np)
    else:
        mask_pad = layout = None

    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)

    static = dict(
        sm_scale=scale, block_q=block_q, block_k=block_k,
        causal=causal and mask is None, n_real_q=n_q, n_real_k=n_k,
        interpret=interp,
    )

    @jax.custom_vjp
    def _attn(q_, k_, v_):
        o, _ = _flash_forward(q_, k_, v_, mask_pad, layout, **static)
        return o

    def _attn_fwd(q_, k_, v_):
        o, lse = _flash_forward(q_, k_, v_, mask_pad, layout, **static)
        return o, (q_, k_, v_, o, lse, mask_pad, layout)

    def _attn_bwd(res, g):
        return _flash_backward(res, g, **static)

    _attn.defvjp(_attn_fwd, _attn_bwd)
    out = _attn(qp, kp, vp)
    return out[:, :, :n_q, :]


def lib_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jnp.ndarray:
    """jax's library TPU flash kernel (pallas.ops.tpu.flash_attention)
    behind the in-repo calling convention ([B, H, N, D], scale d^-0.5).

    Alternative backend to the in-repo `flash_attention` for plain
    causal/full attention (no static-mask block skipping — the library
    kernel has no occupancy layout). Exists so the on-chip A/B
    (`scripts/pallas_onchip.py`) can pick whichever is faster on real
    hardware; differentiable (the library defines its own custom VJP).

    CPU caveat: the interpret guard below covers only the forward trace;
    the library's custom-VJP backward traces its own pallas_calls at grad
    time, so CPU *training* with lib_flash must run the whole grad inside
    `pltpu.force_tpu_interpret_mode()` (tests do). On TPU none of this
    applies. This is a TPU-hardware option; `flash` is the portable one.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _lib,
    )

    scale = q.shape[-1] ** -0.5 if sm_scale is None else sm_scale
    if _use_interpret():
        import jax.experimental.pallas.tpu as pltpu

        with pltpu.force_tpu_interpret_mode():
            return _lib(q, k, v, causal=causal, sm_scale=scale)
    return _lib(q, k, v, causal=causal, sm_scale=scale)
