from dalle_pytorch_tpu.utils.images import save_image_grid, to_uint8
from dalle_pytorch_tpu.utils.trees import param_count, tree_bytes
