from dalle_pytorch_tpu.utils.compile_guard import (
    RecompileError,
    assert_no_recompiles,
    cache_hit_count,
    compile_count,
    track_compiles,
)
from dalle_pytorch_tpu.utils.compile_cache import (
    CompileCache,
    boot_fingerprint,
)
from dalle_pytorch_tpu.utils.images import save_image_grid, to_uint8
from dalle_pytorch_tpu.utils.trees import param_count, tree_bytes
