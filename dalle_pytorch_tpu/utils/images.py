"""Image output helpers (PNG grids replace the reference's wandb.Image /
torchvision.utils.save_image usage, `train_vae.py:252-271`,
`generate.py:138-141`)."""

from __future__ import annotations

from pathlib import Path

import numpy as np


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[H,W,C] float (any range ~[0,1]) -> uint8, clipped."""
    img = np.asarray(img, dtype=np.float32)
    return (np.clip(img, 0.0, 1.0) * 255).astype(np.uint8)


def save_image_grid(images: np.ndarray, path, nrow: int = 8) -> None:
    """[N,H,W,C] -> single PNG grid at `path`."""
    from PIL import Image

    images = np.asarray(images)
    n, h, w, c = images.shape
    nrow = min(nrow, n)
    ncol = (n + nrow - 1) // nrow
    grid = np.zeros((ncol * h, nrow * w, c), dtype=np.uint8)
    for i in range(n):
        r, col = divmod(i, nrow)
        grid[r * h : (r + 1) * h, col * w : (col + 1) * w] = to_uint8(images[i])
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(grid.squeeze()).save(path)
