"""Analytic FLOPs model for throughput/MFU accounting.

Counts matmul FLOPs only (the MXU-relevant work) for the DALLE
transformer; elementwise/softmax/embedding work is excluded by
convention, matching how MFU is normally quoted. Used by `bench.py` and
the trainer's live MFU log (the reference logs only `sample_per_sec`,
`/root/reference/train_dalle.py:578-581`).
"""

from __future__ import annotations

# published bf16 peak FLOP/s per chip, keyed by substrings of
# jax.Device.device_kind (lowercased)
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5": 459e12,  # v5p
    "v6": 918e12,
    "cpu": 5e11,  # nominal, so CPU smoke runs still report something
}


def peak_flops_per_chip(device_kind: str) -> float:
    kind = device_kind.lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return 197e12


def transformer_train_flops(
    dim: int, depth: int, heads: int, dim_head: int, seq: int, ff_mult: int = 4,
    vocab: int = 0,
) -> float:
    """Matmul FLOPs per sample for one fwd+bwd training step.

    `vocab` adds the logits-head projection (standard MFU accounting
    includes the LM head; ~6% of the flagship's matmul FLOPs). Remat
    recompute is deliberately NOT counted — MFU quotes useful FLOPs.
    """
    inner = heads * dim_head
    per_layer = (
        2 * seq * dim * 3 * inner            # qkv proj
        + 2 * seq * seq * inner * 2          # qk^T and attn@v
        + 2 * seq * inner * dim              # out proj
        + 2 * seq * dim * dim * ff_mult * 2  # ff up (GEGLU: 2x width)
        + 2 * seq * dim * ff_mult * dim      # ff down
    )
    fwd = depth * per_layer + 2 * seq * dim * vocab
    return 3 * fwd  # fwd + 2x bwd


# objective mode (training/steps.py MODES) -> number of full fwd+bwd
# transformer passes per sample. forward_forward / forward_reverse_partial
# run the model twice (forward objective + inverse objective, steps.py
# `loss_fn`), so their useful work is 2x a single-objective step.
OBJECTIVE_PASSES = {
    "forward_only": 1,
    "reverse_only": 1,
    "forward_forward": 2,
    "forward_reverse_partial": 2,
}


def dalle_train_flops_per_sample(model, mode: str = "forward_only") -> float:
    """FLOPs/sample for a DALLE model instance under an objective mode.

    Counts `OBJECTIVE_PASSES[mode]` full fwd+bwd passes; in-step dVAE
    encoding (when images rather than tokens are fed) is excluded — it is
    frozen forward-only conv work, small next to the transformer.
    Gradient accumulation does not change FLOPs/sample: `_accumulate`
    scan-splits the same global batch into microbatches, so per-sample
    work is identical and `sample_per_sec * flops_per_sample` stays the
    correct MFU numerator.
    """
    passes = OBJECTIVE_PASSES[mode]
    return passes * transformer_train_flops(
        model.dim, model.depth, model.heads, model.dim_head,
        model.total_seq_len, vocab=model.total_tokens,
    )


def mfu(samples_per_sec: float, flops_per_sample: float, device_kind: str,
        n_chips: int = 1) -> float:
    return samples_per_sec * flops_per_sample / (
        peak_flops_per_chip(device_kind) * n_chips
    )
