"""Persistent compile cache + AOT executable export for seconds-scale boots.

Every replica boot re-traces and re-compiles the whole serving program
ladder — for a fleet, mean time to full capacity after a crash is
dominated by XLA compilation, not failure detection (the pjit/TPUv4
systems literature treats compile amortization as a first-class
operational constraint, PAPERS.md). This module makes a restart cheap:

  * **XLA executable store** (`DIR/xla/`): handed to jax's persistent
    compilation cache (`jax_compilation_cache_dir`), so every jit/pjit
    compile — warmup ladder, AOT cost capture, lazy pixel decode — is
    content-addressed by HLO hash and the second boot LOADS executables
    instead of compiling them. `utils/compile_guard.py` counts the
    cache-hit events, so the warm-boot contract is pinnable:
    `tally.uncached == 0` across a full warmup + serve cycle.
  * **AOT artifact export** (`DIR/aot/`): each warmed program's
    `jit(...).lower().compile()` executable is serialized
    (`jax.experimental.serialize_executable`) to a versioned on-disk
    artifact keyed by a BOOT FINGERPRINT (jax version, backend, mesh
    shape, model config, program ladder). Boot validates the artifacts
    against the fingerprint and a per-file checksum and reports a
    warm/cold plan — a mismatch, missing file, or corrupt/truncated
    entry degrades to a full recompile (counted), NEVER to a failed
    boot. The artifacts are the ship-a-warm-cache unit for fleet
    rollouts: rsync `DIR` to a new host and its first boot is warm.

Accounting: `dalle_boot_cache_{hits,misses,rejects}_total` counters and
a `dalle_boot_seconds{phase=}` gauge family (checkpoint / plan / warmup /
export) so dashboards can separate "slow because cold" from "slow
because sick".

Backend caveat: XLA:CPU (jax 0.4.37) serializes executables but cannot
DESERIALIZE them into a callable ("Symbols not found") — `deserialize`
degrades to None there; the warm boot still works because the dispatch
path loads through the XLA store above. On TPU both paths are live.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import time
from pathlib import Path
from typing import Dict, Iterable, Optional, Sequence

#: artifact container format — bump on any layout change so an old
#: artifact is a clean miss, not a parse error
FORMAT_VERSION = 1
MAGIC = b"DALLEAOT\n"

#: manifest filename inside DIR/aot/
MANIFEST = "MANIFEST.json"


def _canonical(obj) -> str:
    """Deterministic JSON for fingerprint hashing (sorted keys, default
    repr for exotic leaves — a config object that can't serialize still
    fingerprints stably as long as its repr is stable)."""
    return json.dumps(obj, sort_keys=True, default=repr, separators=(",", ":"))


def config_payload(cfg) -> object:
    """Best-effort stable serialization of a model/train config for the
    fingerprint: dicts pass through, config objects use their dict
    conversion where available, anything else falls back to repr."""
    if cfg is None or isinstance(cfg, (dict, list, str, int, float, bool)):
        return cfg
    for attr in ("to_dict", "as_dict"):
        fn = getattr(cfg, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                pass
    try:
        from dalle_pytorch_tpu.training.config import config_to_dict

        return config_to_dict(cfg)
    except Exception:
        return repr(cfg)


def boot_fingerprint(
    backend: Optional[str] = None,
    mesh_shape=None,
    model_config=None,
    programs: Sequence[str] = (),
    jax_version: Optional[str] = None,
    extra=None,
) -> str:
    """Stable identity of one compiled-ladder universe. Any input drift —
    a jax upgrade, a different backend, a resharded mesh, a new model
    config, a program added to the ladder — changes the fingerprint, and
    stale artifacts become misses instead of wrong executables."""
    if jax_version is None:
        import jax

        jax_version = jax.__version__
    payload = {
        "format": FORMAT_VERSION,
        "jax": jax_version,
        "backend": backend,
        "mesh": mesh_shape,
        "model": config_payload(model_config),
        "programs": sorted(str(p) for p in programs),
        "extra": extra,
    }
    return hashlib.sha256(_canonical(payload).encode()).hexdigest()[:32]


def _atomic_write(path: Path, data: bytes) -> None:
    tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
    tmp.write_bytes(data)
    os.replace(tmp, path)


# --------------------------------------------------- artifact container
#
# The self-validating container format — MAGIC + canonical-JSON header
# (format version, fingerprint, payload length, payload sha256) + one
# newline + payload — is shared by the AOT compile-cache artifacts and
# the decode-state checkpoints (serving/migrate.py). ONE pack/unpack
# pair keeps the integrity-critical validation in lockstep: a fix to a
# torn-read edge case reaches both consumers.


def pack_artifact(magic: bytes, fingerprint: str, payload: bytes,
                  format_version: int = FORMAT_VERSION,
                  extra: Optional[Dict] = None) -> bytes:
    """Payload -> self-validating blob (the caller picks MAGIC and
    format version; `extra` adds caller-specific header fields)."""
    header = {
        "format": int(format_version),
        "fingerprint": str(fingerprint),
        "payload_bytes": len(payload),
        "payload_sha256": hashlib.sha256(payload).hexdigest(),
        **(extra or {}),
    }
    return bytes(magic) + _canonical(header).encode() + b"\n" + bytes(payload)


def unpack_artifact(raw: bytes, magic: bytes, fingerprint: str,
                    format_version: int = FORMAT_VERSION):
    """Blob -> (status, reason, payload): "hit" (valid, payload usable),
    "miss" (a DIFFERENT build's artifact — format or fingerprint drift;
    expected after any upgrade), or "reject" (integrity failure: bad
    magic, corrupt header, truncated payload, checksum mismatch —
    investigate the volume/transport). Never raises."""
    if not raw.startswith(magic):
        return "reject", "bad magic", None
    rest = raw[len(magic):]
    try:
        nl = rest.index(b"\n")
        header = json.loads(rest[:nl])
    except Exception as exc:
        return "reject", f"corrupt header: {exc!r}", None
    payload = rest[nl + 1:]
    try:
        if int(header.get("format", -1)) != int(format_version):
            return (
                "miss",
                f"format {header.get('format')} != {format_version}",
                None,
            )
        if header.get("fingerprint") != str(fingerprint):
            return (
                "miss",
                "fingerprint mismatch "
                f"({header.get('fingerprint')!r} != {str(fingerprint)!r})",
                None,
            )
        if len(payload) != int(header.get("payload_bytes", -1)):
            return "reject", "truncated payload", None
        if hashlib.sha256(payload).hexdigest() != header.get(
            "payload_sha256"
        ):
            return "reject", "checksum mismatch", None
    except Exception as exc:
        return "reject", f"corrupt header: {exc!r}", None
    return "hit", None, payload


class CompileCache:
    """One directory holding both compile-persistence layers plus the
    boot accounting. Lifecycle:

        cache = CompileCache(dir, registry=reg, log=log)
        cache.install()                      # jax persistent cache on
        ... build engine ...
        cache.bind(fingerprint, programs)    # identity of this ladder
        plan = cache.plan_boot()             # warm/cold verdict, counted
        engine.compile_cache = cache         # warmup exports artifacts
        with cache.boot_phase("warmup"):
            engine.warmup()

    Every load-side failure is absorbed: a bad cache degrades to a cold
    boot with the reject counted, never to a crashed replica.
    """

    def __init__(self, directory, registry=None, log=None):
        self.dir = Path(directory)
        self.xla_dir = self.dir / "xla"
        self.aot_dir = self.dir / "aot"
        self.xla_dir.mkdir(parents=True, exist_ok=True)
        self.aot_dir.mkdir(parents=True, exist_ok=True)
        self.log = log
        self.fingerprint: Optional[str] = None
        self.programs: tuple = ()
        self.plan: Optional[Dict] = None
        #: fault-injection seam (serving/faults.py `corrupt_cache` rules):
        #: called with (program, path) before every artifact read
        self.faults = None
        self._exported: set = set()
        self._errors: Dict[str, str] = {}
        self.boot_seconds: Dict[str, float] = {}
        self._m_hits = self._m_misses = self._m_rejects = None
        self._m_phase = None
        if registry is not None:
            self._m_hits = registry.counter(
                "dalle_boot_cache_hits_total",
                "AOT cache artifacts that validated against the boot "
                "fingerprint (warm-boot evidence)",
            )
            self._m_misses = registry.counter(
                "dalle_boot_cache_misses_total",
                "AOT cache artifacts missing or keyed to a different "
                "fingerprint (cold recompile, expected after any "
                "config/jax/mesh change)",
            )
            self._m_rejects = registry.counter(
                "dalle_boot_cache_rejects_total",
                "AOT cache artifacts rejected as corrupt/truncated "
                "(cold recompile; investigate the cache volume)",
            )
            self._m_phase = registry.gauge_family(
                "dalle_boot_seconds",
                "wall seconds of the most recent boot, by phase",
                label_name="phase",
            )

    # ------------------------------------------------------------ wiring

    @staticmethod
    def _reset_jax_cache_state() -> None:
        """jax latches its compilation-cache state (`_cache_checked` /
        `_cache_initialized`) on the FIRST compile of the process — a
        compile that ran before the dir was configured permanently
        disables the cache unless the state is reset. Best-effort
        private-API touch; a jax without it just means install() must
        precede the first compile (which serve.py guarantees anyway)."""
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            pass

    def install(self) -> "CompileCache":
        """Point jax's persistent compilation cache at `DIR/xla` —
        process-wide, ideally before the first compile (a pre-existing
        latch is reset). Thresholds are zeroed so toy/CPU programs cache
        too (the default min-compile-time guard would skip exactly the
        programs tests exercise)."""
        import jax

        jax.config.update("jax_compilation_cache_dir", str(self.xla_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        self._reset_jax_cache_state()
        return self

    @staticmethod
    def uninstall() -> None:
        """Detach the process from the persistent cache (tests restore
        global state; serving processes never call this)."""
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
        CompileCache._reset_jax_cache_state()

    def bind(self, fingerprint: str, programs: Iterable[str]) -> "CompileCache":
        self.fingerprint = str(fingerprint)
        self.programs = tuple(str(p) for p in programs)
        return self

    # ------------------------------------------------------------- layout

    def artifact_path(self, program: str) -> Path:
        safe = "".join(
            c if c.isalnum() or c in "._-" else "_" for c in str(program)
        )
        return self.aot_dir / f"{safe}.aotx"

    @property
    def manifest_path(self) -> Path:
        return self.aot_dir / MANIFEST

    def _read_manifest(self) -> Optional[Dict]:
        try:
            return json.loads(self.manifest_path.read_text())
        except FileNotFoundError:
            return None
        except Exception:
            return {"corrupt": True}

    def _write_manifest(self, entries: Dict[str, Dict]) -> None:
        _atomic_write(
            self.manifest_path,
            json.dumps(
                {
                    "format": FORMAT_VERSION,
                    "fingerprint": self.fingerprint,
                    "programs": entries,
                    "written_at": time.time(),
                },
                indent=1,
                sort_keys=True,
            ).encode(),
        )

    # -------------------------------------------------------------- plan

    def _count(self, metric, n: int = 1) -> None:
        if metric is not None:
            metric.inc(n)

    def _validate(self, program: str) -> Dict:
        """One artifact's verdict: {"status": "hit"|"miss"|"reject",
        "reason": ...}. Never raises — a bad artifact is a counted
        verdict, not a boot failure."""
        path = self.artifact_path(program)
        if self.faults is not None:
            # corrupt_cache fault seam: the injector may truncate/garble
            # the file on disk before this read, exercising the exact
            # torn-write/bad-volume path the reject branch guards
            self.faults.on_artifact_load(program, path)
        try:
            raw = path.read_bytes()
        except FileNotFoundError:
            return {"status": "miss", "reason": "missing artifact"}
        except Exception as exc:
            return {"status": "reject", "reason": f"unreadable: {exc!r}"}
        status, reason, payload = unpack_artifact(
            raw, MAGIC, self.fingerprint
        )
        if status != "hit":
            return {"status": status, "reason": reason}
        return {"status": "hit", "reason": None, "bytes": len(payload)}

    def plan_boot(self) -> Dict:
        """Validate every ladder artifact against the bound fingerprint
        and return the boot plan: `mode` is "warm" only when EVERY
        program's artifact is a hit (the dispatch path will then load
        from the XLA store without compiling); anything else is "cold"
        with per-program reasons. Hits/misses/rejects are counted into
        the registry here, once per boot."""
        assert self.fingerprint is not None, "bind() before plan_boot()"
        t0 = time.perf_counter()
        verdicts: Dict[str, Dict] = {}
        manifest = self._read_manifest()
        for program in self.programs:
            v = self._validate(program)
            verdicts[program] = v
            self._count(
                {
                    "hit": self._m_hits,
                    "miss": self._m_misses,
                    "reject": self._m_rejects,
                }[v["status"]]
            )
        statuses = {v["status"] for v in verdicts.values()}
        mode = "warm" if statuses == {"hit"} and verdicts else "cold"
        reason = None
        if mode == "cold":
            if manifest is None:
                reason = "no manifest (first boot against this directory)"
            elif manifest.get("corrupt"):
                reason = "corrupt manifest"
            elif manifest.get("fingerprint") != self.fingerprint:
                reason = "fingerprint mismatch (config/jax/mesh/ladder drift)"
            else:
                bad = {
                    p: v["reason"] for p, v in verdicts.items()
                    if v["status"] != "hit"
                }
                reason = f"invalid artifacts: {bad}"
        self.plan = {
            "mode": mode,
            "reason": reason,
            "fingerprint": self.fingerprint,
            "programs": verdicts,
            "plan_s": round(time.perf_counter() - t0, 4),
        }
        if self.log is not None:
            self.log.event(
                "boot_cache_plan", mode=mode, reason=reason,
                fingerprint=self.fingerprint,
                programs={p: v["status"] for p, v in verdicts.items()},
            )
        return self.plan

    # ------------------------------------------------------------- export

    def wants(self, program: str) -> bool:
        """Should warmup export this program? Only when bound, in the
        ladder, not already exported this boot, and not already valid on
        disk (a warm boot re-exports nothing)."""
        if self.fingerprint is None or program in self._exported:
            return False
        if self.programs and program not in self.programs:
            return False
        if self.plan is not None:
            v = self.plan["programs"].get(program)
            if v is not None and v["status"] == "hit":
                return False
        return True

    def _serialize(self, compiled) -> bytes:
        """Executable -> portable bytes. Overridable seam (tests force
        failures/fakes without a real backend): the default pickles the
        `serialize_executable` triple (payload, in_tree, out_tree)."""
        import pickle

        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        return pickle.dumps(
            {"exe": payload, "trees": (in_tree, out_tree)}, protocol=4
        )

    def export(self, program: str, compiled) -> bool:
        """Serialize one compiled executable into a fingerprint-stamped
        artifact (atomic tmp+rename; the manifest is rewritten after
        every export so an interrupted boot self-heals into partial
        misses next time). Failures are recorded, never raised — a
        backend that can't serialize must not break warmup."""
        try:
            payload = self._serialize(compiled)
            _atomic_write(
                self.artifact_path(program),
                pack_artifact(
                    MAGIC, self.fingerprint, payload,
                    extra={
                        "program": str(program),
                        "written_at": time.time(),
                    },
                ),
            )
        except Exception as exc:
            self._errors[str(program)] = repr(exc)
            if self.log is not None:
                self.log.event(
                    "boot_cache_export_failed", program=str(program),
                    error=repr(exc),
                )
            return False
        self._exported.add(str(program))
        # one validation sweep over ladder ∪ exported (normally equal):
        # still-valid artifacts from earlier boots carry forward so one
        # incremental export can't orphan the rest of the ladder
        entries = {}
        for p in dict.fromkeys(list(self.programs) + sorted(self._exported)):
            v = self._validate(p)
            if v["status"] == "hit":
                entries[p] = {"bytes": v.get("bytes", 0)}
        self._write_manifest(entries)
        return True

    # --------------------------------------------------------------- load

    def _deserialize(self, blob: bytes):
        """Artifact bytes -> loaded executable, or None where the backend
        cannot deserialize (XLA:CPU). Overridable seam for tests."""
        import pickle

        from jax.experimental import serialize_executable

        record = pickle.loads(blob)
        in_tree, out_tree = record["trees"]
        return serialize_executable.deserialize_and_load(
            record["exe"], in_tree, out_tree
        )

    def deserialize(self, program: str):
        """Best-effort load of one validated artifact into a callable
        executable. None on any failure (invalid artifact, backend that
        can't deserialize) — callers fall back to the jit dispatch path,
        which the XLA store keeps warm anyway."""
        v = self._validate(program)
        if v["status"] != "hit":
            return None
        try:
            raw = self.artifact_path(program).read_bytes()
            payload = raw[raw.index(b"\n", len(MAGIC)) + 1:]
            return self._deserialize(payload)
        except Exception as exc:
            self._errors[str(program)] = repr(exc)
            return None

    # --------------------------------------------------------- accounting

    def record_error(self, program: str, exc: BaseException) -> None:
        self._errors[str(program)] = repr(exc)

    @contextlib.contextmanager
    def boot_phase(self, phase: str):
        """Time one boot phase into `dalle_boot_seconds{phase=}` (and the
        `boot_seconds` dict the boot_cache log event carries)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            s = time.perf_counter() - t0
            self.boot_seconds[phase] = round(s, 4)
            if self._m_phase is not None:
                self._m_phase.labels(phase).set(s)

    def detail(self) -> Dict:
        return {
            "dir": str(self.dir),
            "fingerprint": self.fingerprint,
            "programs": list(self.programs),
            "plan": self.plan,
            "exported": sorted(self._exported),
            "errors": dict(self._errors),
            "boot_seconds": dict(self.boot_seconds),
        }
