"""Pytree utilities."""

from __future__ import annotations

import jax


def param_count(tree) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))
