"""Compile-count guard: pin that a code region compiles NOTHING new.

The serving stack's latency story rests on a fixed ladder of compiled
shapes (engine warmup compiles every program the steady state will ever
dispatch). A stray recompile — a drifting shape, a new dtype, an eager op
with a data-dependent shape — silently turns a ~ms dispatch into a
~seconds compile, exactly the hazard class tracelint's TL001 hunts
statically. `assert_no_recompiles` is the RUNTIME end of that contract:

    engine.warmup()
    with assert_no_recompiles():
        ...steady-state serve cycle...   # raises if anything compiles

Counting is based on `jax.monitoring`'s backend-compile duration events
(one per XLA compilation, cache hits emit nothing), which covers jit,
pjit, AND first-execution compiles of eager ops. The listener is installed
once per process and counts into a module global; the context manager
snapshots the counter around the block, so guards nest safely.

CAVEAT — attribution is process-wide, not per-thread: jax.monitoring
events carry no thread identity, so a compilation triggered on ANY thread
during the block (another engine warming up in a parallel fixture, a lazy
jit on a server thread) counts against the guard and fails it. Guard
regions while no other thread is dispatching to JAX; the failure message
lists the observed events so a cross-thread culprit is identifiable.
"""

from __future__ import annotations

import contextlib
import threading
from collections import deque
from dataclasses import dataclass
from typing import Deque, Iterator, List

#: the event jax.monitoring emits once per backend (XLA) compilation
_COMPILE_EVENT_SUFFIX = "backend_compile"
#: the event the persistent compilation cache emits once per CACHE HIT —
#: measured on jax 0.4.37: a hit still fires the backend_compile event
#: (around the executable load), so `compiles - cache_hits` is the count
#: of compilations that actually ran XLA. The warm-boot contract
#: (`utils/compile_cache.py`) pins `uncached == 0` on a second boot.
_CACHE_HIT_EVENT_SUFFIX = "cache_retrieval_time_sec"

_lock = threading.Lock()
_installed = False
_compile_count = 0
_cache_hit_count = 0
#: recent event names only (error-message context) — a bare counter plus a
#: bounded deque keeps a long-lived process from accumulating one string
#: per compilation forever
_recent_events: Deque[str] = deque(maxlen=32)


def _install_listener() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax

        def _on_event(name: str, duration: float, **kwargs) -> None:
            # '/jax/core/compile/backend_compile_duration' et al.
            if _COMPILE_EVENT_SUFFIX in name:
                global _compile_count
                # the deque append is guarded so `recent_events()` can
                # snapshot from other threads (the vitals state dump);
                # compiles are rare, the lock is noise
                with _lock:
                    _compile_count += 1
                    _recent_events.append(name)
            elif _CACHE_HIT_EVENT_SUFFIX in name:
                global _cache_hit_count
                with _lock:
                    _cache_hit_count += 1

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _installed = True


def install_listener() -> None:
    """Public hook for consumers that read `compile_count()` outside a
    guard block — the span tracer (`obs/tracing.py`) installs it so spans
    can tally the compilations that happened while they were open.
    Idempotent; imports jax on first call."""
    _install_listener()


def compile_count() -> int:
    """Backend compilations observed so far this process (after the first
    guard/`track_compiles`/`install_listener` use installed the
    listener; 0 forever before that — readers treat it as a delta
    source, not an absolute truth)."""
    return _compile_count


def cache_hit_count() -> int:
    """Backend compilations that were served from the persistent
    compilation cache (`jax_compilation_cache_dir`) rather than run
    through XLA. Each hit ALSO fires the backend-compile event, so
    `compile_count() - cache_hit_count()` is the number of compilations
    that actually paid XLA time. 0 forever when no cache dir is
    configured."""
    return _cache_hit_count


def recent_events() -> List[str]:
    """The most recent compile event names (bounded window) — engine-state
    dumps (`/debug/state`) and stall reports include them so an unexpected
    mid-serve compile is identifiable without a guard block in place.
    Snapshot under the lock: the listener appends from whichever thread
    compiles."""
    with _lock:
        return list(_recent_events)


class RecompileError(AssertionError):
    """A guarded region compiled something new."""


@dataclass
class CompileTally:
    """Live view of compilations inside a guard block."""

    _start: int = 0
    allowed: int = 0
    _start_hits: int = 0

    @property
    def count(self) -> int:
        return _compile_count - self._start

    @property
    def cache_hits(self) -> int:
        """Compilations in the block that loaded from the persistent
        compilation cache instead of running XLA."""
        return _cache_hit_count - self._start_hits

    @property
    def uncached(self) -> int:
        """Compilations that actually paid XLA time — the warm-boot
        contract (`utils/compile_cache.py`) pins this at zero on a
        second boot against a populated cache."""
        return max(0, self.count - self.cache_hits)

    @property
    def events(self) -> List[str]:
        """The most recent compile event names (bounded window) — context
        for the error message, not a complete ledger."""
        return list(_recent_events)[-max(self.count, 0):] if self.count else []


@contextlib.contextmanager
def track_compiles() -> Iterator[CompileTally]:
    """Count backend compilations in a block without asserting."""
    _install_listener()
    yield CompileTally(_start=_compile_count, _start_hits=_cache_hit_count)


@contextlib.contextmanager
def assert_no_recompiles(allowed: int = 0) -> Iterator[CompileTally]:
    """Raise `RecompileError` if the block triggers more than `allowed`
    backend compilations (default: zero — the steady-state contract)."""
    _install_listener()
    tally = CompileTally(
        _start=_compile_count, allowed=allowed, _start_hits=_cache_hit_count
    )
    yield tally
    if tally.count > allowed:
        raise RecompileError(
            f"guarded region compiled {tally.count} program(s) "
            f"(allowed {allowed}) — a shape/dtype drifted out of the "
            f"warmup set. Recent compile events: {tally.events}"
        )
