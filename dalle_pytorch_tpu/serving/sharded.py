"""Mesh-sharded continuous serving: one engine across a TPU mesh.

`ContinuousEngine` is single-device: a model whose params don't fit one
chip's HBM — or a slot cache sized for more concurrency than one chip
holds — cannot serve at all. `ShardedContinuousEngine` spreads BOTH over
a `make_mesh` device mesh (jax.sharding / GSPMD, the pjit programming
model of "Scalable Training of Language Models using JAX pjit and
TPUv4", PAPERS.md):

  * params are placed with `NamedSharding` per `parallel/partition.py`'s
    training rules (megatron column/row splits over `tp`, embeddings
    vocab-parallel) — one rule table for train AND serve;
  * the persistent slot state is placed per
    `parallel/serving_partition.py`: KV cache split over attention heads
    on the `tp` axis, pending-logits rows vocab-split, per-row control
    scalars replicated;
  * the steady-state programs (batched prefill, resume, chunk, release,
    pixel decode) are the SAME program bodies the single-device engine
    runs (`models/dalle.py` builders) — re-jitted here with explicit
    `out_shardings` pinned to the canonical state shardings, so the
    sharding of the donated state reaches a fixed point at the FIRST
    dispatch and the warm server's zero-recompile contract survives
    (GSPMD-propagated output shardings drifting between dispatches would
    re-key the jit cache);
  * when the flash-decode kernel is active, `Attention` dispatches it
    through `ops/pallas_decode.py:sharded_flash_decode_attention` —
    shard_map over the mesh's tp axis, heads split, exactly the
    SNIPPETS.md [1] pattern (a Pallas call is a single-device program
    GSPMD cannot partition). `parallel/mesh.py`'s shard_map shim keeps
    this running on jax 0.4.37.

The engine seam is the whole point: `prefill_slots` / `step_chunk` /
`harvest` / `release` keep their signatures, so the continuous batcher,
the HTTP server, tracing, vitals, and warmup/cost-capture all work
unchanged — `serve.py --mesh dp=1,tp=4` is the only switch.

Correctness pin: the head/vocab splits introduce no cross-device
reduction inside attention itself, and the decode-composition-invariance
contract extends across the mesh — a >=2-device CPU mesh
(`--xla_force_host_platform_device_count`) produces bit-identical tokens
to the single-device engine for the same specs/seeds
(tests/test_sharded.py).

`ShardedPagedContinuousEngine` extends the same placement to the paged
layout: the physical page POOL head-splits over `tp` (each shard holds
its heads' slice of every page), while page tables, refcounts, and the
prefix-cache index stay host-side numpy — page bookkeeping is
device-count-independent, so the paged admission/eviction logic runs
verbatim. The whole paged ladder (prefill + sidecar, cached-prefix
admit, resume, chunk, release) is pinned with `out_shardings` like the
slotted programs. The page axis itself must NEVER shard: a page is the
unit of host-side allocation, and splitting it would put half of each
page's tokens on the wrong device (tracelint TL008 flags specs that
try).
"""

from __future__ import annotations

from typing import Optional, Union

import numpy as np

from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    PagedContinuousEngine,
)

#: the 4-axis `make_mesh` vocabulary, re-declared so `parse_mesh_shape`
#: stays importable without paying a jax init (`parallel/mesh.py` imports
#: jax at module top; serve.py validates --mesh at argparse time) —
#: pinned in lockstep with `parallel.mesh.MESH_AXES` by
#: tests/test_sharded.py
MESH_AXES = ("dp", "fsdp", "tp", "sp")


def parse_mesh_shape(spec: Optional[str]) -> dict:
    """`--mesh dp=2,tp=4`-style flag -> {axis: size}. Axes are the
    4-axis `make_mesh` vocabulary (dp, fsdp, tp, sp); omitted axes get
    size 1; at most one size may be -1 to absorb the remaining devices.
    Empty/None defaults to everything on the model axis (tp=-1)."""
    if not spec:
        return {"tp": -1}
    out: dict = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        assert "=" in part, (
            f"mesh axis {part!r} must be axis=size (e.g. dp=1,tp=4)"
        )
        k, v = part.split("=", 1)
        k = k.strip()
        assert k in MESH_AXES, f"unknown mesh axis {k!r}; use one of {MESH_AXES}"
        size = int(v)
        assert size == -1 or size >= 1, (
            f"mesh axis {k}={size}: sizes must be >= 1 (or -1 to absorb "
            "the remaining devices)"
        )
        out[k] = size
    return out


def build_serving_mesh(shape: Union[str, dict, None] = None, devices=None):
    """Resolve a mesh-shape request against the visible devices and build
    the 4-axis mesh. A -1 size absorbs the remaining devices; a product
    smaller than the device count uses the first `product` devices (the
    `make_pp_mesh` convention, so `tp=2` works on an 8-device test
    host)."""
    import jax

    from dalle_pytorch_tpu.parallel.mesh import make_mesh

    shape = dict(
        parse_mesh_shape(shape) if shape is None or isinstance(shape, str)
        else shape
    )
    for k, v in shape.items():  # dict callers bypass parse_mesh_shape
        assert v == -1 or v >= 1, f"mesh axis {k}={v}: sizes must be >= 1"
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    neg = [k for k, v in shape.items() if v == -1]
    assert len(neg) <= 1, f"at most one mesh axis may be -1, got {shape}"
    fixed = 1
    for k, v in shape.items():
        if v != -1:
            fixed *= v
    if neg:
        assert n % fixed == 0, (
            f"{n} devices not divisible by the fixed axes {fixed}"
        )
        shape[neg[0]] = n // fixed
        fixed = n
    assert fixed <= n, f"mesh {shape} needs {fixed} devices, have {n}"
    kw = {a: shape.get(a, 1) for a in MESH_AXES}
    return make_mesh(devices=devices[:fixed], **kw)


class _MeshServingMixin:
    """Mesh plumbing shared by the slotted and paged sharded engines:
    placement at load, state placement, the pinned-program cache, the
    (layout-independent) release program, and the per-shard
    observability block. Each concrete engine supplies its own pinned
    admission/chunk programs — the bodies differ per layout but the jit
    wrapper discipline (donate the state, pin out_shardings to the
    canonical state shardings) is identical."""

    def _init_mesh(self, model, variables, vae_params, mesh, mesh_shape,
                   model_axis):
        """Resolve the mesh, clone the model's decode-kernel mesh handle,
        and place params/VAE — returns the (possibly cloned/placed)
        triple for the engine __init__ to forward to its base class."""
        import jax

        from dalle_pytorch_tpu.parallel.serving_partition import (
            replicated_shardings,
            serving_variables_shardings,
        )

        if mesh is None:
            mesh = build_serving_mesh(mesh_shape)
        self.mesh = mesh
        self.model_axis = model_axis
        assert model_axis in mesh.axis_names, (
            f"mesh {dict(mesh.shape)} lacks the model axis {model_axis!r}"
        )
        #: per-program jitted dispatchers with out_shardings pinned to the
        #: canonical state shardings (built lazily on first dispatch)
        self._sharded_programs: dict = {}
        self._state_shardings = None
        # hand the mesh AND the head axis to the flash-decode dispatch
        # (no-op for models whose cached path stays dense) — the kernel
        # must split over the same axis the KV-cache shardings use;
        # callers that pre-set their own decode_mesh keep it
        if getattr(model, "decode_mesh", None) is None:
            model = model.clone(
                decode_mesh=mesh, decode_heads_axis=model_axis
            )
        # placement at load: params tensor-sharded per partition.py, VAE
        # replicated (the pixel decode is tiny next to the trunk)
        variables = jax.device_put(
            variables, serving_variables_shardings(variables, mesh)
        )
        if vae_params is not None:
            vae_params = jax.device_put(
                vae_params, replicated_shardings(vae_params, mesh)
            )
        return model, variables, vae_params

    # ---------------------------------------------------------- placement

    def _fresh_state(self):
        """Clean decode state placed under the serving_partition
        shardings (KV heads over the model axis — slot lanes and the
        paged pool alike —, control scalars replicated). The paged base
        rebuilds its host-side page tables inside super()._fresh_state();
        they are plain numpy and never placed."""
        import jax

        from dalle_pytorch_tpu.parallel.serving_partition import (
            decode_state_shardings,
        )

        state = super()._fresh_state()
        if self._state_shardings is None:
            self._state_shardings = decode_state_shardings(
                state, self.mesh, self.model_axis
            )
        return jax.device_put(state, self._state_shardings)

    def _sharded_program(self, name: str, build):
        fn = self._sharded_programs.get(name)
        if fn is None:
            fn = build()
            self._sharded_programs[name] = fn
        return fn

    def _replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    # ----------------------------------------------------------- slot ops
    # The program BODIES are models/dalle.py's — only the jit wrapper
    # differs: out_shardings pinned to the canonical state shardings so
    # the donated state's sharding is a fixed point from dispatch one
    # (unpinned, GSPMD may hand back a drifted sharding that re-keys the
    # jit cache on the next dispatch — a silent warm-path recompile).

    def _release_op(self, s, mask):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import _release_builder

        fn = self._sharded_program(
            "release",
            lambda: jax.jit(
                _release_builder(self.model, ()),
                donate_argnums=(0,),
                out_shardings=self._state_shardings,
            ),
        )
        return fn(s, jnp.asarray(mask, jnp.bool_))

    # ------------------------------------------------------ observability

    def mesh_detail(self) -> dict:
        """Mesh geometry + per-device buffer accounting for `/healthz`,
        `state_dump()`, and the bench's JSON line — the block that lets a
        stall event or a capacity dashboard name the SICK SHARD instead
        of "the engine". Host-side metadata reads only; a leaf whose
        buffer was just donated away reports as skipped rather than
        raising (the dump must render while the engine is wedged)."""
        per_dev: dict = {}
        leaves = []
        try:
            import jax

            leaves = jax.tree_util.tree_leaves((self._state, self.variables))
        except Exception:
            pass
        for leaf in leaves:
            shards = getattr(leaf, "addressable_shards", None)
            if shards is None:
                continue
            try:
                for shard in shards:
                    key = f"{shard.device.platform}:{shard.device.id}"
                    nbytes = getattr(shard.data, "nbytes", None)
                    if nbytes is None:
                        nbytes = int(
                            np.prod(shard.data.shape)
                        ) * shard.data.dtype.itemsize
                    per_dev[key] = per_dev.get(key, 0) + int(nbytes)
            except Exception:
                continue  # donated-away buffer mid-dispatch: skip the leaf
        return {
            "axes": {k: int(v) for k, v in dict(self.mesh.shape).items()},
            "devices": int(self.mesh.devices.size),
            "model_axis": self.model_axis,
            "per_device_state_bytes": per_dev,
        }

    def state_dump(self) -> dict:
        out = super().state_dump()
        out["mesh"] = self.mesh_detail()
        return out


class ShardedContinuousEngine(_MeshServingMixin, ContinuousEngine):
    """Continuous batching with params + slot KV cache sharded over a
    device mesh. Same serving surface as `ContinuousEngine` (the batcher,
    server, tracing, and vitals layers don't know the difference); same
    decode numerics (bit-identical tokens — the test-pinned contract).

    `mesh` is a ready `jax.sharding.Mesh`, or pass `mesh_shape` (a
    `parse_mesh_shape` string/dict) to build one over the visible
    devices. `model_axis` names the axis heads/vocab shard over
    (default "tp").
    """

    def __init__(
        self,
        model,
        variables,
        vae=None,
        vae_params=None,
        max_batch: int = 8,
        chunk_tokens: int = 4,
        prefill_batch: int = 4,
        cond_scale: float = 1.0,
        clip=None,
        clip_params=None,
        tokenizer=None,
        registry=None,
        cfg=None,
        mesh=None,
        mesh_shape: Union[str, dict, None] = None,
        model_axis: str = "tp",  # serving_partition.SERVING_MODEL_AXIS
        resume_enabled: bool = False,
        preview_enabled: bool = False,
        kv_dtype=None,
        decode_sparsity: str = "causal",
    ):
        model, variables, vae_params = self._init_mesh(
            model, variables, vae_params, mesh, mesh_shape, model_axis
        )
        super().__init__(
            model=model,
            variables=variables,
            vae=vae,
            vae_params=vae_params,
            max_batch=max_batch,
            chunk_tokens=chunk_tokens,
            prefill_batch=prefill_batch,
            cond_scale=cond_scale,
            clip=clip,
            clip_params=clip_params,
            tokenizer=tokenizer,
            registry=registry,
            cfg=cfg,
            resume_enabled=resume_enabled,
            preview_enabled=preview_enabled,
            kv_dtype=kv_dtype,
            decode_sparsity=decode_sparsity,
        )

    # ----------------------------------------------------------- slot ops

    def _prefill_op(self, s, texts, slots, seeds, temps, keep):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import _prefill_slots_builder

        sparse = self._sparsity is not None
        key = (
            (self.prefill_batch, "sparse") if sparse
            else (self.prefill_batch,)
        )
        fn = self._sharded_program(
            "prefill",
            lambda: jax.jit(
                _prefill_slots_builder(self.model, key),
                donate_argnums=(1,),
                out_shardings=self._state_shardings,
            ),
        )
        args = [
            self.variables, s, jnp.asarray(texts, jnp.int32),
            jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
            jnp.asarray(temps, jnp.float32), jnp.asarray(keep, jnp.int32),
        ]
        if sparse:
            # bitmap rides replicated (it is per-row control data, tiny
            # next to the KV it gates; GSPMD replicates uncommitted hosts
            # arrays) — the per-head split happens inside the shard_map
            args.append(jnp.asarray(
                self._sparsity.prefill_bitmaps(self.prefill_batch),
                jnp.int32,
            ))
        return fn(*args)

    def _resume_op(self, s, texts, img_tokens, img_pos, slots, seeds,
                   temps, keep):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import _resume_slots_builder

        fn = self._sharded_program(
            "resume",
            lambda: jax.jit(
                _resume_slots_builder(self.model, (self.prefill_batch,)),
                donate_argnums=(1,),
                out_shardings=self._state_shardings,
            ),
        )
        return fn(
            self.variables, s, jnp.asarray(texts, jnp.int32),
            jnp.asarray(img_tokens, jnp.int32),
            jnp.asarray(img_pos, jnp.int32),
            jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
            jnp.asarray(temps, jnp.float32), jnp.asarray(keep, jnp.int32),
        )

    def _chunk_op(self, s):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import _chunk_builder

        sparse = self._sparsity is not None
        key = (
            (self.chunk_tokens, "sparse") if sparse
            else (self.chunk_tokens,)
        )
        fn = self._sharded_program(
            "chunk",
            lambda: jax.jit(
                _chunk_builder(self.model, key),
                donate_argnums=(1,),
                out_shardings=self._state_shardings,
            ),
        )
        if sparse:
            return fn(self.variables, s, jnp.asarray(
                self._sparsity.chunk_bitmaps(
                    self._host_pos, self._host_active
                ),
                jnp.int32,
            ))
        return fn(self.variables, s)


class ShardedPagedContinuousEngine(_MeshServingMixin, PagedContinuousEngine):
    """Paged continuous batching over a device mesh: the physical page
    pool head-splits over the model axis (each shard holds its heads'
    slice of EVERY page), page tables / refcounts / the prefix-cache
    index stay host-side numpy and run verbatim. The whole paged program
    ladder — batched prefill (+ sidecar), cached-prefix admit, resume,
    chunk, release — is re-jitted with out_shardings pinned to the
    canonical state shardings, so the warm server's zero-recompile
    contract holds exactly as on the slotted sharded engine.

    The page axis NEVER shards (a page is the host allocator's unit;
    `parallel/serving_partition.py` keeps it whole and tracelint TL008
    flags shard_map specs that split it)."""

    def __init__(
        self,
        model,
        variables,
        vae=None,
        vae_params=None,
        max_batch: int = 8,
        chunk_tokens: int = 4,
        prefill_batch: int = 4,
        cond_scale: float = 1.0,
        clip=None,
        clip_params=None,
        tokenizer=None,
        registry=None,
        cfg=None,
        page_size: int = 32,
        kv_pages: Optional[int] = None,
        prefix_entries: int = 64,
        mesh=None,
        mesh_shape: Union[str, dict, None] = None,
        model_axis: str = "tp",  # serving_partition.SERVING_MODEL_AXIS
        resume_enabled: bool = False,
        preview_enabled: bool = False,
        kv_dtype=None,
        decode_sparsity: str = "causal",
    ):
        model, variables, vae_params = self._init_mesh(
            model, variables, vae_params, mesh, mesh_shape, model_axis
        )
        super().__init__(
            model=model,
            variables=variables,
            vae=vae,
            vae_params=vae_params,
            max_batch=max_batch,
            chunk_tokens=chunk_tokens,
            prefill_batch=prefill_batch,
            cond_scale=cond_scale,
            clip=clip,
            clip_params=clip_params,
            tokenizer=tokenizer,
            registry=registry,
            cfg=cfg,
            page_size=page_size,
            kv_pages=kv_pages,
            prefix_entries=prefix_entries,
            resume_enabled=resume_enabled,
            preview_enabled=preview_enabled,
            kv_dtype=kv_dtype,
            decode_sparsity=decode_sparsity,
        )

    # ----------------------------------------------------------- slot ops
    # Pinned versions of the paged seams. The prefill program returns
    # (state, sidecar): the state pins to the canonical shardings, the
    # sidecar (pending logits + shift rings, consumed host-side by the
    # prefix-cache registration) replicates — a pytree-prefix
    # out_shardings covers both.

    def _paged_prefill_op(self, s, texts, slots, seeds, temps, keep,
                          page_rows, partial_dst):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import (
            _prefill_slots_paged_builder,
        )

        n_text_pages = int(np.asarray(page_rows).shape[1])
        sparse = self._sparsity is not None
        key = (self.prefill_batch, self.page_size, n_text_pages)
        if sparse:
            key = key + ("sparse",)
        fn = self._sharded_program(
            "prefill",
            lambda: jax.jit(
                _prefill_slots_paged_builder(self.model, key),
                donate_argnums=(1,),
                out_shardings=(
                    self._state_shardings, self._replicated_sharding(),
                ),
            ),
        )
        args = [
            self.variables, s, jnp.asarray(texts, jnp.int32),
            jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
            jnp.asarray(temps, jnp.float32), jnp.asarray(keep, jnp.int32),
            jnp.asarray(page_rows, jnp.int32),
            jnp.asarray(partial_dst, jnp.int32),
        ]
        if sparse:
            args.append(jnp.asarray(
                self._sparsity.prefill_bitmaps(self.prefill_batch),
                jnp.int32,
            ))
        return fn(*args)

    def _admit_hit_op(self, s, slot, sidecar, seed, temperature, keep_k,
                      partial_src, partial_dst):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import _admit_prefix_builder

        fn = self._sharded_program(
            "admit_hit",
            lambda: jax.jit(
                _admit_prefix_builder(self.model, (self.page_size,)),
                donate_argnums=(0,),
                out_shardings=self._state_shardings,
            ),
        )
        return fn(
            s, jnp.int32(slot), sidecar, jnp.int32(seed),
            jnp.float32(temperature), jnp.int32(keep_k),
            jnp.int32(partial_src), jnp.int32(partial_dst),
        )

    def _paged_resume_op(self, s, texts, img_tokens, img_pos, slots,
                         seeds, temps, keep, page_rows):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import (
            _resume_slots_paged_builder,
        )

        n_pages_row = int(np.asarray(page_rows).shape[1])
        fn = self._sharded_program(
            "resume",
            lambda: jax.jit(
                _resume_slots_paged_builder(
                    self.model,
                    (self.prefill_batch, self.page_size, n_pages_row),
                ),
                donate_argnums=(1,),
                out_shardings=self._state_shardings,
            ),
        )
        return fn(
            self.variables, s, jnp.asarray(texts, jnp.int32),
            jnp.asarray(img_tokens, jnp.int32),
            jnp.asarray(img_pos, jnp.int32),
            jnp.asarray(slots, jnp.int32), jnp.asarray(seeds, jnp.int32),
            jnp.asarray(temps, jnp.float32), jnp.asarray(keep, jnp.int32),
            jnp.asarray(page_rows, jnp.int32),
        )

    def _chunk_op(self, s):
        import jax
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import _chunk_paged_builder

        sparse = self._sparsity is not None
        key = (
            (self.chunk_tokens, "sparse") if sparse
            else (self.chunk_tokens,)
        )
        fn = self._sharded_program(
            "chunk",
            lambda: jax.jit(
                _chunk_paged_builder(self.model, key),
                donate_argnums=(1,),
                out_shardings=self._state_shardings,
            ),
        )
        args = [self.variables, s, jnp.asarray(self.kv.table, jnp.int32)]
        if sparse:
            args.append(jnp.asarray(
                self._sparsity.chunk_bitmaps(
                    self._host_pos, self._host_active
                ),
                jnp.int32,
            ))
        return fn(*args)
