"""Deterministic fault injection for engine dispatches.

The recovery invariants the serving layer claims — a failed donated
dispatch rebuilds clean state, the block pool / prefix cache / slot
allocator stay consistent, preempted rows survive an engine rebuild — are
only worth anything if tests can MAKE dispatches fail at chosen points.
`FaultInjector` is that seam: every engine dispatch calls
`engine._fault_point(program)` (a no-op until an injector is attached to
`engine.faults`), and the injector fails or stalls the Nth dispatch of a
named program, deterministically.

The failure is raised INSIDE the engine's `_replace_state` try for the
donated slot ops, so the engine's real recovery path runs — state rebuild,
host-manager reset, batcher retry/fail-fast — exactly as it would for an
XLA error. Dispatch counting includes warmup dispatches; tests attach the
injector AFTER warmup so rule indices count serving traffic only.

Strictly a test/chaos seam: nothing in the serving stack constructs one
unless asked (tests set `engine.faults`; the one production-adjacent
hook is the `DALLE_SERVE_CRASH=program:nth` env var `serve.py` honors so
the supervised-restart bench and recovery drills can kill a REAL replica
subprocess at a chosen dispatch).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class InjectedFault(RuntimeError):
    """The deliberate failure a fail-Nth rule raises."""


# tracelint: threads
class FaultInjector:
    """Fail, stall, or CRASH the Nth dispatch of a named engine program,
    and corrupt named compile-cache artifacts before they load.

    Rules are one-shot and deterministic: `fail_nth("chunk", 3)` raises
    `InjectedFault` on the third chunk dispatch after attachment and never
    again; `stall_nth("prefill", 1, seconds=2)` sleeps inside the first
    prefill dispatch (watchdog fodder) then lets it proceed;
    `crash_nth("chunk", 2)` hard-aborts the PROCESS at the second chunk
    dispatch (`os._exit` through the overridable `_abort` seam — no
    cleanup, no drain: the supervisor/router recovery paths must handle
    exactly this). `corrupt_cache("chunk", mode="truncate")` truncates or
    garbles the named AOT cache artifact on disk the Nth time the
    compile cache is about to read it (`utils/compile_cache.py` calls
    `on_artifact_load`), exercising the torn-write reject path. `fired`
    records every rule that triggered, for assertions.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        # program -> {nth: rule dict}; one rule per (program, nth)
        self._rules: Dict[str, Dict[int, dict]] = {}
        # artifact-load rules live in their own namespace: cache reads
        # are a boot-time event stream, not a dispatch stream
        self._cache_counts: Dict[str, int] = {}
        self._cache_rules: Dict[str, Dict[int, dict]] = {}
        self.fired: List[dict] = []

    def fail_nth(self, program: str, nth: int,
                 exc: Optional[BaseException] = None) -> "FaultInjector":
        assert nth >= 1
        with self._lock:
            self._rules.setdefault(program, {})[int(nth)] = {
                "kind": "fail",
                "exc": exc,
            }
        return self

    def stall_nth(self, program: str, nth: int,
                  seconds: float = 0.0, until=None) -> "FaultInjector":
        """Stall the Nth dispatch of `program`. `seconds` sleeps a fixed
        wall time; `until` (a `threading.Event`) holds the dispatch until
        the TEST releases it — the deterministic flavor chaos tests use
        so a wedge can never end early under CPU contention (`seconds`
        then bounds the wait as a leak backstop, default 120s)."""
        assert nth >= 1 and seconds >= 0
        with self._lock:
            self._rules.setdefault(program, {})[int(nth)] = {
                "kind": "stall",
                "seconds": float(seconds),
                "until": until,
            }
        return self

    def crash_nth(self, program: str, nth: int,
                  exit_code: int = 70) -> "FaultInjector":
        """Hard process abort at the Nth dispatch of `program` — the
        replica dies mid-request exactly as a segfaulting runtime or an
        OOM-killed container would. `_abort` is the seam: unit tests
        override it; real chaos (`serve.py` DALLE_SERVE_CRASH, the
        restart bench) lets it `os._exit`."""
        assert nth >= 1
        with self._lock:
            self._rules.setdefault(program, {})[int(nth)] = {
                "kind": "crash",
                "exit_code": int(exit_code),
            }
        return self

    def corrupt_cache(self, artifact: str, nth: int = 1,
                      mode: str = "truncate") -> "FaultInjector":
        """Truncate or garble the named compile-cache artifact the Nth
        time it is about to be read (attach the injector to
        `CompileCache.faults`). `mode="truncate"` cuts the file mid-
        payload (torn write); `mode="garble"` flips payload bytes
        (bit rot) — both must land in the REJECT branch of the boot
        plan, never in a crashed boot."""
        assert nth >= 1 and mode in ("truncate", "garble")
        with self._lock:
            self._cache_rules.setdefault(artifact, {})[int(nth)] = {
                "kind": "corrupt_cache",
                "mode": mode,
            }
        return self

    def dispatches(self, program: str) -> int:
        with self._lock:
            return self._counts.get(program, 0)

    def _abort(self, program: str, nth: int, exit_code: int) -> None:
        """The crash rule's process exit — overridable so unit tests can
        observe the call instead of dying. Deliberately `os._exit`, not
        `sys.exit`: no atexit hooks, no drain, no flushed sockets."""
        import os
        import sys

        print(
            f"[faults] crash rule fired: {program} dispatch #{nth} -> "
            f"os._exit({exit_code})",
            file=sys.stderr, flush=True,
        )
        os._exit(exit_code)

    def on_dispatch(self, program: str) -> None:
        """Called by the engine at every dispatch of `program`. Raises
        `InjectedFault` (or the rule's exception) for a matching fail
        rule; sleeps for a stall rule; aborts the process for a crash
        rule; counts and returns otherwise."""
        with self._lock:
            n = self._counts.get(program, 0) + 1
            self._counts[program] = n
            rule = self._rules.get(program, {}).pop(n, None)
            if rule is not None:
                self.fired.append({"program": program, "nth": n, **rule})
        if rule is None:
            return
        if rule["kind"] == "stall":
            if rule.get("until") is not None:
                rule["until"].wait(rule["seconds"] or 120.0)
            else:
                time.sleep(rule["seconds"])
            return
        if rule["kind"] == "crash":
            self._abort(program, n, rule["exit_code"])
            return  # only reachable with a stubbed _abort
        exc = rule["exc"]
        if exc is None:
            exc = InjectedFault(
                f"injected failure: {program} dispatch #{n}"
            )
        raise exc

    def on_artifact_load(self, artifact: str, path) -> None:
        """Called by `CompileCache` before reading `artifact` at `path`.
        A matching corrupt_cache rule mutates the file ON DISK (missing
        files are left missing — that's the miss branch, not a reject)
        and lets the load proceed into the validator."""
        with self._lock:
            n = self._cache_counts.get(artifact, 0) + 1
            self._cache_counts[artifact] = n
            rule = self._cache_rules.get(artifact, {}).pop(n, None)
            if rule is not None:
                self.fired.append({"artifact": artifact, "nth": n, **rule})
        if rule is None:
            return
        try:
            raw = path.read_bytes()
        except FileNotFoundError:
            return
        if rule["mode"] == "truncate":
            path.write_bytes(raw[: max(1, len(raw) // 2)])
        else:  # garble: flip a run of payload bytes, keep the length
            mid = len(raw) // 2
            garbled = bytearray(raw)
            for i in range(mid, min(mid + 16, len(garbled))):
                garbled[i] ^= 0xFF
            path.write_bytes(bytes(garbled))
