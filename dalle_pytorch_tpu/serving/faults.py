"""Deterministic fault injection for engine dispatches.

The recovery invariants the serving layer claims — a failed donated
dispatch rebuilds clean state, the block pool / prefix cache / slot
allocator stay consistent, preempted rows survive an engine rebuild — are
only worth anything if tests can MAKE dispatches fail at chosen points.
`FaultInjector` is that seam: every engine dispatch calls
`engine._fault_point(program)` (a no-op until an injector is attached to
`engine.faults`), and the injector fails or stalls the Nth dispatch of a
named program, deterministically.

The failure is raised INSIDE the engine's `_replace_state` try for the
donated slot ops, so the engine's real recovery path runs — state rebuild,
host-manager reset, batcher retry/fail-fast — exactly as it would for an
XLA error. Dispatch counting includes warmup dispatches; tests attach the
injector AFTER warmup so rule indices count serving traffic only.

Strictly a test/chaos seam: nothing in the serving stack constructs one
unless asked (`serve.py` has no flag for it; tests set `engine.faults`).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class InjectedFault(RuntimeError):
    """The deliberate failure a fail-Nth rule raises."""


class FaultInjector:
    """Fail or stall the Nth dispatch of a named engine program.

    Rules are one-shot and deterministic: `fail_nth("chunk", 3)` raises
    `InjectedFault` on the third chunk dispatch after attachment and never
    again; `stall_nth("prefill", 1, seconds=2)` sleeps inside the first
    prefill dispatch (watchdog fodder) then lets it proceed. `fired`
    records every rule that triggered, for assertions.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        # program -> {nth: rule dict}; one rule per (program, nth)
        self._rules: Dict[str, Dict[int, dict]] = {}
        self.fired: List[dict] = []

    def fail_nth(self, program: str, nth: int,
                 exc: Optional[BaseException] = None) -> "FaultInjector":
        assert nth >= 1
        with self._lock:
            self._rules.setdefault(program, {})[int(nth)] = {
                "kind": "fail",
                "exc": exc,
            }
        return self

    def stall_nth(self, program: str, nth: int,
                  seconds: float) -> "FaultInjector":
        assert nth >= 1 and seconds >= 0
        with self._lock:
            self._rules.setdefault(program, {})[int(nth)] = {
                "kind": "stall",
                "seconds": float(seconds),
            }
        return self

    def dispatches(self, program: str) -> int:
        with self._lock:
            return self._counts.get(program, 0)

    def on_dispatch(self, program: str) -> None:
        """Called by the engine at every dispatch of `program`. Raises
        `InjectedFault` (or the rule's exception) for a matching fail
        rule; sleeps for a stall rule; counts and returns otherwise."""
        with self._lock:
            n = self._counts.get(program, 0) + 1
            self._counts[program] = n
            rule = self._rules.get(program, {}).pop(n, None)
            if rule is not None:
                self.fired.append({"program": program, "nth": n, **rule})
        if rule is None:
            return
        if rule["kind"] == "stall":
            time.sleep(rule["seconds"])
            return
        exc = rule["exc"]
        if exc is None:
            exc = InjectedFault(
                f"injected failure: {program} dispatch #{n}"
            )
        raise exc
