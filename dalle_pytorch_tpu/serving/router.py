"""Replica fleet router: health-aware routing, failover, hedging, drain.

One admission router in front of N `ServingServer` replicas — the ROADMAP
item 1 scale step (data-parallel across hosts, tensor-parallel within:
each replica may itself be a `--mesh` sharded engine). The router's job
is to make the FLEET survive any single replica being slow, wedged,
restarting, or gone, without client-visible errors — replica failure is
the steady state, not the exception (Vortex-style serving fleets,
PAPERS.md).

Mechanisms, in the order a request meets them:

  * ROUTING POLICY — least-outstanding-rows over the routable replicas,
    healthy replicas preferred over degraded ones, with QoS spillover:
    the "low" class may only use non-degraded replicas, "high"/"normal"
    may claim a degraded one (a degraded replica still serves — its own
    /healthz said so — it just should not absorb background traffic).
    A replica that answered 503 with Retry-After is COOLED for that
    priority class for that long: replica-level backpressure is obeyed
    per class, not fleet-wide (a low-class queue-full must not cool the
    replica for high traffic). A 429 passes through instead — tenant
    quotas are tenant-scoped, and the over-quota tenant must see its
    own 429 + Retry-After rather than making the class unroutable for
    everyone.
  * HEALTH STATE MACHINE — active probing of each replica's /healthz
    drives per-replica state: `healthy` / `degraded` (deprioritized) /
    `ejected` (no traffic). Ejection comes from consecutive probe
    failures OR a rolling dispatch error-rate burst (the circuit
    breaker's closed→open edge). While ejected, probes back off
    exponentially (capped); a probe success half-opens the circuit: ONE
    trial request is let through, its success closes the circuit
    (healthy again), its failure re-ejects with a doubled backoff — a
    flapping replica converges to absorbing one trial per backoff
    window instead of live traffic.
  * FAILOVER + RETRY BUDGET — a failed or timed-out dispatch re-routes
    to the next candidate. Decode is (seed, position)-keyed and the
    router PINS the seed before the first attempt (a client that sent
    no seed gets one assigned here), so a re-dispatched request returns
    bit-identical tokens wherever it lands — failover costs latency,
    never output. Retries draw from a budget that refills as a fraction
    of recent SUCCESSES (Finagle-style token bucket, not a fixed
    per-request count): during a full-fleet outage the budget drains
    and stays empty, so total dispatch attempts are bounded and retries
    cannot amplify the outage against recovering replicas.
  * HEDGING — with `--hedge_after_ms`, a dispatch that has not answered
    within the threshold gets a duplicate sent to the next candidate
    (budget-gated, counted); the first usable answer wins and the
    loser's connection is closed. Tail latency insurance for the p99,
    safe because duplicated execution is bit-identical.
  * GRACEFUL DRAIN — `POST /admin/drain?replica=NAME` stops new
    admissions to that replica, waits out its outstanding rows, then
    marks it `drained` (out of rotation, not probed back in); a rolling
    restart is a zero-error event. `POST /admin/undrain?replica=NAME`
    returns it to rotation. `?propagate=1` additionally drains/undrains
    the replica's own intake (`ServingServer` /admin/drain) so direct
    clients are refused too.
  * POISON-REQUEST QUARANTINE — every replica CRASH (transport failure:
    connect refused, reset, severed mid-response) is an INCIDENT
    attributed to the requests in flight on that replica at the time. A
    request implicated in `quarantine_after` CONSECUTIVE incidents (a
    success absolves) is quarantined: the client gets a terminal 422
    carrying the incident ids, and an identical resubmission is refused
    at ingress — the missing complement to the retry budget, because a
    request that CAUSES crashes would otherwise fail over forever and
    serially kill the fleet, while innocent requests caught in the same
    crashes are cleared by their own failover success. Replica 5xx
    answers deliberately do NOT implicate: the replica survived, and
    request-scoped engine poison is the REPLICA's quarantine (the
    batcher's dispatch-incident ledger -> its own 422, which passes
    through here like any 4xx).

Observability: the router adopts or mints `x-dalle-trace` at ingress and
parents every dispatch span into the caller's context, so its
route/retry/hedge decisions appear in the stitched fleet critical path
(obs/collector.py); each dispatch carries `x-dalle-route`
(`replica;attempt;hedged`) which the replica stamps into its request log
line — a fleet log join attributes every retry. The router exports
`dalle_router_*` metric families, serves its own /healthz (503 only when
NO replica is routable) and `GET /debug/replicas` (full per-replica
state dump), and logs one structured `request` line per routed request
with the routing decision.

Run it: `python -m dalle_pytorch_tpu.serving.router --replicas
http://h1:8000,http://h2:8000 --port 8100` (or `serve.py --router
--replicas ...`). Everything is stdlib; the `_post`/`_probe` seams are
the only socket touches, and the state machine runs off an injectable
clock so chaos tests drive it deterministically.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import queue as queue_mod
import re
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlsplit

from dalle_pytorch_tpu.obs.aggregate import (
    TRACE_HEADER,
    default_site,
    format_trace_header,
    parse_trace_header,
    sanitize_site,
    span_uid_for,
)
from dalle_pytorch_tpu.obs.tracing import Tracer
from dalle_pytorch_tpu.serving.qos import PRIORITY_CLASSES, priority_class
from dalle_pytorch_tpu.serving.streaming import (
    KEEPALIVE,
    SSEParser,
    encode_sse,
)

#: routing-decision header the router stamps on every forwarded dispatch;
#: replicas parse it into their request log lines so a fleet log join can
#: attribute every attempt (satellite of the PR 9 site/pid/host identity)
ROUTE_HEADER = "x-dalle-route"

#: content-identity header the router stamps on every forwarded dispatch:
#: the request fingerprint (quarantine key). Replicas key their
#: crash-spool checkpoints on it, so the supervisor's spool hand-off
#: joins back to the exact in-flight requests the crash interrupted —
#: and log lines across the fleet share one content join key.
REQUEST_KEY_HEADER = "x-dalle-request-key"

_ROUTE_RE = re.compile(r"^([A-Za-z0-9_.\-]{1,64});(\d{1,4});([01])$")

_REQUEST_KEY_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


def parse_request_key(value) -> Optional[str]:
    """Strict/total parse of an inbound `x-dalle-request-key` header;
    None for anything malformed (the key lands in spool files and log
    lines, and garbage must not)."""
    if not value or not isinstance(value, str):
        return None
    value = value.strip()
    return value if _REQUEST_KEY_RE.match(value) else None

MAX_BODY_BYTES = 1 << 20

#: numeric encoding of replica state for the state gauge family
STATE_VALUES = {
    "healthy": 0.0,
    "degraded": 1.0,
    "half_open": 2.0,
    "draining": 3.0,
    "drained": 4.0,
    "ejected": 5.0,
}


def format_route_header(replica: str, attempt: int, hedged: bool) -> str:
    """`x-dalle-route` value for one dispatch: `replica;attempt;hedged`.
    The replica name goes through the same clamp as trace sites so the
    strict parser on the other side always round-trips it."""
    return f"{sanitize_site(replica)};{int(attempt)};{1 if hedged else 0}"


def parse_route_header(value) -> Optional[Dict]:
    """Strict/total parse of an inbound `x-dalle-route` header into
    `{"replica", "attempt", "hedged"}`; None for anything malformed —
    the fields land in request log lines, and garbage must not."""
    if not value or not isinstance(value, str):
        return None
    m = _ROUTE_RE.match(value.strip())
    if not m:
        return None
    return {
        "replica": m.group(1),
        "attempt": int(m.group(2)),
        "hedged": m.group(3) == "1",
    }


def request_fingerprint(body: Dict) -> str:
    """Content identity of one /generate body for quarantine tracking.
    Excludes `timeout_s` (client patience is not content) and `resume`
    (a decode-state checkpoint is transport state — a migrated re-
    dispatch is THE SAME request and must keep its key), and is
    computed BEFORE the router pins a seed, so a seedless client
    re-sending the same poison prompt maps to the same key even though
    each submission would have drawn a fresh seed."""
    import hashlib

    essence = {
        k: v for k, v in body.items() if k not in ("timeout_s", "resume")
    }
    return hashlib.sha256(
        json.dumps(essence, sort_keys=True, default=str).encode()
    ).hexdigest()[:24]


# tracelint: threads
class CheckpointRegistry:
    """Bounded store of decode-state checkpoints keyed by request
    fingerprint — the crash-recovery half of migration. Filled by the
    supervisor's spool hand-off (`POST /admin/spool`) and by migrating
    drains; consumed (at most once) by the failover path, which attaches
    the checkpoint to the re-dispatch so the resuming replica restores
    completed rows instead of re-decoding the whole request. Waiters
    (`wait_for`) park a transport-failed request briefly for the
    restarted replica's spool to arrive."""

    def __init__(self, capacity: int = 256):
        from collections import OrderedDict

        assert capacity >= 1
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._entries: "OrderedDict[str, Dict]" = OrderedDict()
        self.ingested = 0
        self.consumed = 0

    def put(self, key: str, wire: str, source: Optional[str] = None) -> None:
        with self._cond:
            self._entries[key] = {
                "wire": wire, "source": source, "at": time.time(),
            }
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
            self.ingested += 1
            self._cond.notify_all()

    def take(self, key: str) -> Optional[Dict]:
        """Consume the checkpoint for `key` (at most one resume per
        beacon — a second failover starts clean rather than resuming a
        snapshot the first resume already advanced past)."""
        with self._cond:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self.consumed += 1
            return entry

    def wait_for(self, key: str, timeout_s: float) -> Optional[Dict]:
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        with self._cond:
            while True:
                entry = self._entries.pop(key, None)
                if entry is not None:
                    self.consumed += 1
                    return entry
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(timeout=min(remaining, 0.25))

    def discard(self, key: str) -> None:
        with self._cond:
            self._entries.pop(key, None)

    def detail(self) -> Dict:
        with self._lock:
            return {
                "keys": len(self._entries),
                "capacity": self.capacity,
                "ingested": self.ingested,
                "consumed": self.consumed,
            }


# tracelint: threads
class QuarantineTracker:
    """Consecutive-incident accounting per request fingerprint.

    `implicate(key, incident)` charges every request in flight during
    one incident; `absolve(key)` (called on any successful completion)
    resets the streak — so an innocent request that merely shared a
    replica with a poison one is cleared by its own failover success,
    while the poison request's streak only grows. At `after` consecutive
    implications the key is quarantined.

    ONE replica death is ONE incident: transport failures against the
    same replica within `coalesce_window_s` share an incident id (N
    in-flight dispatch threads all report the same severed box), and a
    key is charged at most once per incident — a bystander must not
    reach the threshold off a single crash reported twice (once as a
    bystander, once by its own failed dispatch). Bounded LRU over
    `capacity` keys; incident metadata rides in a bounded ring for
    /debug.
    """

    def __init__(self, after: int = 3, capacity: int = 1024,
                 coalesce_window_s: float = 5.0, ttl_s: float = 600.0,
                 time_fn=time.monotonic):
        assert after >= 1 and capacity >= 1 and ttl_s > 0
        self.after = int(after)
        self.capacity = int(capacity)
        self.coalesce_window_s = float(coalesce_window_s)
        #: implication streaks EXPIRE: quarantine is protection, not a
        #: permanent blocklist. Without a TTL, a fleet-wide transport
        #: blip that walks one request across `after` dead replicas
        #: would brick its fingerprint forever (quarantined keys are
        #: refused at ingress, so the absolve-on-success path can never
        #: run for them). A true replica-killer re-trips within one
        #: failover walk anyway.
        self.ttl_s = float(ttl_s)
        self._now = time_fn
        self._lock = threading.Lock()
        #: key -> {"count": consecutive implications, "incidents": [ids]}
        #: — insertion/refresh ordered, so eviction drops the key with
        #: the OLDEST most-recent implication (absolve just pops; a
        #: side ordering structure would go stale on absolve and evict
        #: live marks)
        from collections import OrderedDict

        self._marks: "OrderedDict" = OrderedDict()
        self._incident_seq = 0
        #: replica -> (incident id, minted at) for coalescing
        self._last_by_replica: Dict[str, Tuple[str, float]] = {}
        self.incidents: deque = deque(maxlen=64)
        self.quarantined_keys = 0

    def mint_incident(self, replica: str, error: str, keys) -> str:
        """New incident id — or the open one for `replica` when its last
        death is younger than the coalesce window."""
        now = self._now()
        with self._lock:
            last = self._last_by_replica.get(replica)
            if last is not None and now - last[1] <= self.coalesce_window_s:
                return last[0]
            self._incident_seq += 1
            inc_id = f"inc-{self._incident_seq:06d}"
            self._last_by_replica[replica] = (inc_id, now)
            self.incidents.append({
                "id": inc_id,
                "replica": replica,
                "error": error,
                "implicated": len(list(keys)),
                "ts": time.time(),
            })
            return inc_id

    def implicate(self, key: str, incident_id: str) -> int:
        """Charge one key with one incident (idempotent per incident);
        returns its consecutive implication count."""
        now = self._now()
        with self._lock:
            mark = self._marks.get(key)
            if mark is not None and now - mark["last_at"] > self.ttl_s:
                self._marks.pop(key)
                mark = None  # expired streak: start fresh
            if mark is None:
                mark = {"count": 0, "incidents": [], "last_at": now}
                self._marks[key] = mark
                while len(self._marks) > self.capacity:
                    # evict the oldest NON-quarantined mark (never the
                    # key being charged right now): a quarantined key is
                    # refused at ingress, so it never refreshes its
                    # position — plain LRU would let churn silently
                    # forget a replica-killer. Only when every OTHER
                    # tracked key is quarantined does the oldest of
                    # those go (bounded memory wins).
                    victim = next(
                        (
                            k for k, m in self._marks.items()
                            if k != key and m["count"] < self.after
                        ),
                        next(k for k in self._marks if k != key),
                    )
                    self._marks.pop(victim)
            else:
                # freshly implicated keys are the ones worth keeping
                self._marks.move_to_end(key)
            mark["last_at"] = now
            if incident_id in mark["incidents"]:
                return mark["count"]
            mark["count"] += 1
            mark["incidents"].append(incident_id)
            if mark["count"] == self.after:
                self.quarantined_keys += 1
            return mark["count"]

    def absolve(self, key: str) -> None:
        """A success ends the streak: the request demonstrably does not
        kill replicas (it was a bystander)."""
        with self._lock:
            self._marks.pop(key, None)

    def is_quarantined(self, key: str) -> bool:
        with self._lock:
            mark = self._marks.get(key)
            if mark is None:
                return False
            if self._now() - mark["last_at"] > self.ttl_s:
                self._marks.pop(key)  # expired: the quarantine lifts
                return False
            return mark["count"] >= self.after

    def incidents_for(self, key: str) -> List[str]:
        with self._lock:
            mark = self._marks.get(key)
            return list(mark["incidents"]) if mark else []

    def detail(self) -> Dict:
        now = self._now()
        with self._lock:
            live = {
                k: m for k, m in self._marks.items()
                if now - m["last_at"] <= self.ttl_s
            }
            quarantined = {
                k: list(m["incidents"])
                for k, m in live.items()
                if m["count"] >= self.after
            }
            return {
                "after": self.after,
                "ttl_s": self.ttl_s,
                "tracked_keys": len(live),
                "quarantined": quarantined,
                "quarantined_total": self.quarantined_keys,
                "recent_incidents": list(self.incidents),
            }


class RetryBudget:
    """Token-bucket retry budget that refills on SUCCESS, not on time.

    `deposit()` is called once per successful dispatch and adds `ratio`
    tokens (capped); `withdraw()` spends one token per retry/hedge and
    returns False when the bucket is empty. The refill-on-success shape
    is the anti-amplification property the chaos tests pin: during a
    full-fleet outage nothing succeeds, the bucket drains to zero, and
    every further request costs exactly ONE attempt — a fleet of
    retrying routers cannot DDoS its own recovering replicas. `initial`
    seeds the bucket so cold-start failover works before the first
    success.
    """

    def __init__(self, ratio: float = 0.2, initial: float = 10.0,
                 cap: float = 100.0):
        assert ratio >= 0 and initial >= 0 and cap >= initial
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._balance = float(initial)
        self._lock = threading.Lock()
        self.withdrawn = 0
        self.denied = 0

    @property
    def balance(self) -> float:
        with self._lock:
            return self._balance

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self.cap, self._balance + self.ratio)

    def withdraw(self) -> bool:
        with self._lock:
            if self._balance < 1.0:
                self.denied += 1
                return False
            self._balance -= 1.0
            self.withdrawn += 1
            return True


class Replica:
    """Per-replica routing state. All mutation happens under the
    router's lock; the dispatch threads only touch it through the
    router's helpers."""

    def __init__(self, name: str, url: str, now: float):
        self.name = name
        self.url = url.rstrip("/")
        parts = urlsplit(self.url)
        assert parts.scheme in ("http", ""), (
            f"replica {name}: only http:// URLs are supported, got {url!r}"
        )
        assert parts.hostname, f"replica {name}: no host in {url!r}"
        self.host = parts.hostname
        self.port = parts.port or 80
        #: admin-controlled lifecycle: active | draining | drained
        self.mode = "active"
        #: probe/breaker-controlled health: healthy | degraded |
        #: half_open | ejected
        self.health = "healthy"
        self.outstanding_rows = 0
        self.inflight = 0
        self.probe_failures = 0
        self.next_probe_at = now
        self.probe_backoff_s = 0.0
        #: consecutive circuit opens — drives the capped exponential
        #: backoff (reset when a trial closes the circuit)
        self.open_count = 0
        #: rolling (ts, ok) dispatch outcomes for the error-rate breaker
        self.window: deque = deque()
        #: priority class index -> monotonic ts until which this replica
        #: is cooled for that class (its own Retry-After, obeyed)
        self.cooldowns: Dict[int, float] = {}
        self.trial_inflight = False
        self.last_error: Optional[str] = None
        self.ejected_reason: Optional[str] = None
        self.requests = 0
        self.failures = 0
        #: request fingerprints currently dispatched here (key -> count)
        #: — the attribution set a crash incident implicates
        self.inflight_keys: Dict[str, int] = {}
        #: bounded LRU of fingerprints recently dispatched here — the
        #: "prefix cache plausibly holds this prompt" signal migration
        #: re-dispatch uses to prefer a cache-warm replica
        from collections import OrderedDict

        self.seen_keys: "OrderedDict[str, float]" = OrderedDict()
        #: requests this replica completed from a migrated resume
        self.resumes = 0
        # ---- restart/crash attribution (supervised-restart visibility):
        #: completed down->up cycles (ejected, then a successful trial)
        self.restarts = 0
        #: when the current outage began (None while up)
        self.down_at: Optional[float] = None
        #: why the most recent outage began ("<reason>: <last_error>")
        self.last_down_reason: Optional[str] = None
        #: ejection-to-recovered wall seconds of the most recent restart
        self.last_rejoin_s: Optional[float] = None

    def state(self) -> str:
        """Single display state: admin mode wins over health."""
        if self.mode != "active":
            return self.mode
        return self.health

    def error_rate(self) -> Tuple[int, float]:
        n = len(self.window)
        if not n:
            return 0, 0.0
        fails = sum(1 for _, ok in self.window if not ok)
        return n, fails / n

    def detail(self, now: float) -> Dict:
        n, rate = self.error_rate()
        return {
            "name": self.name,
            "url": self.url,
            "state": self.state(),
            "mode": self.mode,
            "health": self.health,
            "outstanding_rows": self.outstanding_rows,
            "inflight": self.inflight,
            "requests": self.requests,
            "failures": self.failures,
            "error_window": {"samples": n, "error_rate": round(rate, 3)},
            "probe_failures": self.probe_failures,
            "probe_backoff_s": round(self.probe_backoff_s, 3),
            "next_probe_in_s": round(max(0.0, self.next_probe_at - now), 3),
            "open_count": self.open_count,
            "cooldowns_s": {
                PRIORITY_CLASSES[k]: round(max(0.0, until - now), 3)
                for k, until in self.cooldowns.items()
                if until > now
            },
            "ejected_reason": self.ejected_reason,
            "last_error": self.last_error,
            "restarts": self.restarts,
            "down_for_s": (
                round(now - self.down_at, 3)
                if self.down_at is not None else None
            ),
            "last_down_reason": self.last_down_reason,
            "last_rejoin_s": (
                round(self.last_rejoin_s, 3)
                if self.last_rejoin_s is not None else None
            ),
            "resumes": self.resumes,
        }


class FleetRouter:
    """Routing policy core: replica set, health state machine, failover
    loop. HTTP-free except for the `_post`/`_probe` seams, and clocked by
    the injectable `time_fn` so tests drive probes/backoff/cooldowns
    deterministically while exercising real sockets."""

    def __init__(
        self,
        replicas: Sequence[str],
        registry=None,
        tracer: Optional[Tracer] = None,
        log=None,
        exporter=None,
        site: Optional[str] = None,
        request_timeout_s: float = 120.0,
        attempt_timeout_s: float = 30.0,
        hedge_after_ms: Optional[float] = None,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 2.0,
        eject_after_probe_failures: int = 3,
        error_window_s: float = 30.0,
        error_rate_threshold: float = 0.5,
        error_min_samples: int = 4,
        probe_backoff_s: float = 1.0,
        probe_backoff_max_s: float = 30.0,
        retry_budget_ratio: float = 0.2,
        retry_budget_initial: float = 10.0,
        quarantine_after: int = 3,
        migrate_wait_s: float = 0.0,
        time_fn=time.monotonic,
    ):
        assert replicas, "router needs at least one replica URL"
        self._now = time_fn
        now = self._now()
        self.replicas: List[Replica] = []
        seen = set()
        for i, spec in enumerate(replicas):
            name, sep, url = str(spec).partition("=")
            if not sep:  # bare URL: derive a stable name from host:port
                url = str(spec)
                parts = urlsplit(url)
                name = f"{parts.hostname}-{parts.port or 80}"
            name = sanitize_site(name)
            while name in seen:  # two replicas on one host:port — suffix
                name = f"{name}-{i}"
            seen.add(name)
            self.replicas.append(Replica(name, url, now))
        self.request_timeout_s = float(request_timeout_s)
        self.attempt_timeout_s = float(attempt_timeout_s)
        self.hedge_after_s = (
            None if hedge_after_ms is None else float(hedge_after_ms) / 1e3
        )
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.eject_after_probe_failures = int(eject_after_probe_failures)
        self.error_window_s = float(error_window_s)
        self.error_rate_threshold = float(error_rate_threshold)
        self.error_min_samples = int(error_min_samples)
        self.probe_backoff_base_s = float(probe_backoff_s)
        self.probe_backoff_max_s = float(probe_backoff_max_s)
        self.budget = RetryBudget(
            ratio=retry_budget_ratio, initial=retry_budget_initial
        )
        # poison-request quarantine (0 disables): consecutive crash
        # implications before a request fingerprint is refused outright
        # (tracker shares the injectable clock so chaos tests drive the
        # incident-coalescing window deterministically)
        self.quarantine = (
            QuarantineTracker(after=int(quarantine_after), time_fn=time_fn)
            if int(quarantine_after) > 0 else None
        )
        # decode-state migration (serving/migrate.py): spooled/drained
        # checkpoints keyed by request fingerprint; a transport-failed
        # request may park up to `migrate_wait_s` for the restarted
        # replica's spool hand-off before falling back to a from-scratch
        # re-dispatch (0 = never park: instant failover, crash resumes
        # only when the spool already arrived)
        self.checkpoints = CheckpointRegistry()
        self.migrate_wait_s = float(migrate_wait_s)
        # identity for span UIDs and log lines — the PR 9 clamp, so the
        # router's parent_uid round-trips the header codec
        self.site = sanitize_site(site) if site else default_site()
        self.host = sanitize_site(socket.gethostname() or "localhost")
        self.pid = os.getpid()
        self.tracer = tracer if tracer is not None else Tracer(max_traces=128)
        self.exporter = exporter
        if exporter is not None:
            exporter.attach(self.tracer)
        self.log = log
        self._lock = threading.Lock()
        self._drained = threading.Condition(self._lock)
        self._seed_lock = threading.Lock()
        self._seed_counter = int(time.time()) & 0x7FFFFFFF
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_stop = threading.Event()
        self._started_at = time.time()

        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._m_state = registry.gauge_family(
            "dalle_router_replica_state",
            "per-replica routing state (0 healthy, 1 degraded, 2 "
            "half-open, 3 draining, 4 drained, 5 ejected)",
            label_name="replica",
        )
        self._m_outstanding = registry.gauge_family(
            "dalle_router_outstanding_rows",
            "request rows currently dispatched to each replica",
            label_name="replica",
        )
        self._m_requests = registry.counter_family(
            "dalle_router_requests_total",
            "dispatch attempts per replica (including retries and hedges)",
            label_name="replica",
        )
        self._m_failovers = registry.counter_family(
            "dalle_router_failovers_total",
            "dispatches re-routed to another replica, by failure reason "
            "(transport: connect/timeout/reset; status: replica 5xx; "
            "backpressure: replica 429/503 — cooled, not broken)",
            label_name="reason",
        )
        self._m_hedges = registry.counter(
            "dalle_router_hedges_total",
            "duplicate dispatches launched for the latency tail "
            "(--hedge_after_ms; first usable answer wins)",
        )
        self._m_hedge_wins = registry.counter(
            "dalle_router_hedge_wins_total",
            "hedged duplicates that answered before the primary",
        )
        self._m_ejections = registry.counter_family(
            "dalle_router_ejections_total",
            "replicas ejected from rotation, by reason (probe: "
            "consecutive health-probe failures; error_rate: dispatch "
            "error-rate burst opened the circuit; trial: the half-open "
            "trial request failed)",
            label_name="reason",
        )
        self._m_probes = registry.counter_family(
            "dalle_router_probes_total",
            "health probes by result",
            label_name="result",
        )
        self._m_budget = registry.gauge(
            "dalle_router_retry_budget",
            "retry-budget tokens available (refills on success; empty "
            "during an outage, so retries cannot amplify it)",
        )
        self._m_budget.set(self.budget.balance)
        self._m_unroutable = registry.counter(
            "dalle_router_unroutable_total",
            "requests refused because no replica was routable for their "
            "class (all ejected/draining/cooling)",
        )
        self._m_quarantined = registry.counter(
            "dalle_router_quarantined_total",
            "requests refused as poison: implicated in K consecutive "
            "replica crash incidents (terminal 422 with incident ids "
            "instead of endless failover)",
        )
        self._m_migrations = registry.counter_family(
            "dalle_router_migrations_total",
            "in-flight requests re-dispatched with a decode-state "
            "checkpoint, by source (drain: a migrating drain's 409 "
            "carried it; crash: the restarted replica's spool hand-off)",
            label_name="reason",
        )
        self._m_spool_ingested = registry.counter(
            "dalle_router_spool_checkpoints_total",
            "checkpoints ingested from replica spool hand-offs "
            "(POST /admin/spool)",
        )
        # per-tenant / per-priority usage accounting: every successful
        # dispatch records its replica wall + token usage here; the
        # fleet scraper joins in ProgramCostTable FLOP rates and
        # GET /debug/usage reads it back
        from dalle_pytorch_tpu.obs.fleetmetrics import UsageLedger

        self.usage = UsageLedger(registry=registry)
        for rep in self.replicas:
            self._m_state.labels(rep.name).set(STATE_VALUES[rep.state()])
            self._m_outstanding.labels(rep.name).set(0)

    # ------------------------------------------------------------ identity

    def _span_uid(self, span) -> str:
        # the shared identity format (aggregate.span_uid_for): router
        # dispatch spans must join in the collector exactly like
        # exporter-shipped ones
        return span_uid_for(self.site, self.host, self.pid, span.span_id)

    def next_seed(self, n: int) -> int:
        """Pin a seed BEFORE the first dispatch for requests that didn't
        send one: every retry/hedge forwards the identical payload, so
        duplicated execution returns bit-identical tokens."""
        with self._seed_lock:
            s = self._seed_counter
            self._seed_counter = (self._seed_counter + n) & 0x7FFFFFFF
            return s

    # ------------------------------------------------------- state machine

    def _set_state_gauge(self, rep: Replica) -> None:
        self._m_state.labels(rep.name).set(
            STATE_VALUES.get(rep.state(), 5.0)
        )

    def _eject(self, rep: Replica, reason: str, now: float) -> None:
        """Caller holds the lock. closed→open edge of the breaker."""
        rep.health = "ejected"
        rep.ejected_reason = reason
        if rep.down_at is None:
            # outage start (repeat ejections while flapping keep the
            # ORIGINAL down timestamp — time-to-rejoin measures the
            # whole outage, not the last flap)
            rep.down_at = now
            rep.last_down_reason = (
                f"{reason}: {rep.last_error}" if rep.last_error else reason
            )
        rep.trial_inflight = False
        rep.open_count += 1
        rep.window.clear()
        rep.probe_backoff_s = min(
            self.probe_backoff_base_s * (2 ** (rep.open_count - 1)),
            self.probe_backoff_max_s,
        )
        rep.next_probe_at = now + rep.probe_backoff_s
        self._m_ejections.labels(reason).inc()
        self._set_state_gauge(rep)
        if self.log is not None:
            self.log.event(
                "replica_ejected", replica=rep.name, reason=reason,
                probe_backoff_s=round(rep.probe_backoff_s, 3),
                last_error=rep.last_error,
            )

    def _record_dispatch(self, rep: Replica, ok: bool) -> None:
        """Feed one live-dispatch outcome into the breaker."""
        now = self._now()
        with self._lock:
            rep.requests += 1
            if not ok:
                rep.failures += 1
            if rep.health == "half_open":
                # the one trial request decides the circuit
                rep.trial_inflight = False
                if ok:
                    rep.health = "healthy"
                    rep.open_count = 0
                    rep.probe_failures = 0
                    rep.probe_backoff_s = 0.0
                    rep.ejected_reason = None
                    rep.window.clear()
                    if rep.down_at is not None:
                        # restart attribution: one completed down->up
                        # cycle, measured from the ejection that began
                        # the outage to THIS closing trial
                        rep.restarts += 1
                        rep.last_rejoin_s = now - rep.down_at
                        rep.down_at = None
                    self._set_state_gauge(rep)
                    if self.log is not None:
                        self.log.event(
                            "replica_recovered", replica=rep.name,
                            restarts=rep.restarts,
                            rejoin_s=(
                                round(rep.last_rejoin_s, 3)
                                if rep.last_rejoin_s is not None else None
                            ),
                            down_reason=rep.last_down_reason,
                        )
                else:
                    self._eject(rep, "trial", now)
                return
            rep.window.append((now, ok))
            while rep.window and now - rep.window[0][0] > self.error_window_s:
                rep.window.popleft()
            if not ok and rep.health != "ejected":
                n, rate = rep.error_rate()
                if (
                    n >= self.error_min_samples
                    and rate >= self.error_rate_threshold
                ):
                    self._eject(rep, "error_rate", now)

    def _cool(self, rep: Replica, klass: int, retry_after_s: float) -> None:
        """Obey a replica's own Retry-After for one priority class."""
        until = self._now() + max(0.0, float(retry_after_s))
        with self._lock:
            rep.cooldowns[klass] = max(rep.cooldowns.get(klass, 0.0), until)

    # -------------------------------------------------------------- probes

    def _probe(self, rep: Replica) -> Tuple[int, Dict]:
        """The one probe socket touch (stubbed in tests): GET /healthz.
        Returns (status, parsed detail); raises on transport failure."""
        req = urllib.request.Request(rep.url + "/healthz", method="GET")
        try:
            with urllib.request.urlopen(
                req, timeout=self.probe_timeout_s
            ) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:  # 503 is an answer, not
            return exc.code, {}  # a transport failure

    def _probe_one(self, rep: Replica, now: float) -> None:
        try:
            status, detail = self._probe(rep)
        except Exception as exc:
            self._on_probe(rep, None, {}, now, error=exc)
        else:
            self._on_probe(rep, status, detail, now)

    def probe_once(self, now: Optional[float] = None) -> None:
        """One probe sweep over every due replica — the probe thread's
        body, callable directly (tests drive it with a stubbed clock).
        Due replicas are probed CONCURRENTLY: sweep time is the max of
        the probe latencies, not the sum, so one dark replica's connect
        timeout cannot delay failure detection on the others."""
        now = self._now() if now is None else now
        due = []
        with self._lock:
            for rep in self.replicas:
                if now >= rep.next_probe_at and rep.mode == "active":
                    due.append(rep)
        if not due:
            return
        if len(due) == 1:
            self._probe_one(due[0], now)
            return
        threads = [
            threading.Thread(
                target=self._probe_one, args=(rep, now),
                name="dalle-router-probe-one", daemon=True,
            )
            for rep in due
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.probe_timeout_s + 5.0)

    def _on_probe(self, rep: Replica, status: Optional[int], detail: Dict,
                  now: float, error: Optional[BaseException] = None) -> None:
        ok = status == 200
        self._m_probes.labels("ok" if ok else "fail").inc()
        with self._lock:
            if ok:
                rep.probe_failures = 0
                tier = (detail or {}).get("status", "ok")
                if rep.health == "ejected":
                    # open→half-open: admit ONE trial request; live
                    # traffic (not the probe) closes the circuit
                    rep.health = "half_open"
                    rep.trial_inflight = False
                elif rep.health != "half_open":
                    rep.health = (
                        "degraded" if tier == "degraded" else "healthy"
                    )
                rep.next_probe_at = now + self.probe_interval_s
            else:
                rep.last_error = (
                    repr(error) if error is not None else f"healthz {status}"
                )
                rep.probe_failures += 1
                if rep.health == "ejected":
                    # stay open; keep backing off (capped)
                    rep.probe_backoff_s = min(
                        max(
                            rep.probe_backoff_s * 2,
                            self.probe_backoff_base_s,
                        ),
                        self.probe_backoff_max_s,
                    )
                    rep.next_probe_at = now + rep.probe_backoff_s
                elif rep.probe_failures >= self.eject_after_probe_failures:
                    self._eject(rep, "probe", now)
                else:
                    rep.next_probe_at = now + self.probe_interval_s
            self._set_state_gauge(rep)

    def start_probes(self) -> "FleetRouter":
        if self._probe_thread is None:
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="dalle-router-probe",
                daemon=True,
            )
            self._probe_thread.start()
        return self

    def _probe_loop(self) -> None:
        while not self._probe_stop.is_set():
            try:
                self.probe_once()
            except Exception as exc:  # the probe thread must never die;
                if self.log is not None:  # next tick retries — the stop
                    self.log.event(  # wait below is its backoff
                        "probe_sweep_error", error=repr(exc)
                    )
            self._probe_stop.wait(self.probe_interval_s)

    def stop_probes(self) -> None:
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=self.probe_timeout_s + 5.0)
            self._probe_thread = None

    # ----------------------------------------------------------- selection

    def _routable(self, klass: int, exclude) -> List[Replica]:
        """Candidate replicas for one attempt, best-first: healthy before
        degraded/half-open (deprioritized, not excluded — except for the
        low class, which may not touch a degraded replica at all), then
        least outstanding rows, then name for determinism."""
        now = self._now()
        out = []
        with self._lock:
            for rep in self.replicas:
                if rep.name in exclude or rep.mode != "active":
                    continue
                if rep.health == "ejected":
                    continue
                if rep.health == "half_open" and rep.trial_inflight:
                    continue
                if (
                    rep.health == "degraded"
                    and klass >= priority_class("low")
                ):
                    continue
                if rep.cooldowns.get(klass, 0.0) > now:
                    continue
                out.append(rep)
            # half_open ranks WITH healthy: the circuit only closes when
            # the trial request runs, and trial_inflight already caps a
            # recovering replica at one live request — deprioritizing it
            # below healthy would starve the trial forever on a fleet
            # with any healthy capacity
            out.sort(key=lambda r: (
                0 if r.health in ("healthy", "half_open") else 1,
                r.outstanding_rows,
                r.requests,  # tie-break: an idle fleet round-robins
                r.name,  # instead of pinning the first name
            ))
        return out

    def _prefer_cache_warm(self, cands: List[Replica],
                           key: str) -> List[Replica]:
        """Stable re-rank of one attempt's candidates: replicas that
        recently dispatched this fingerprint first — their prefix cache
        plausibly still holds the prompt, so a migrated resume's
        re-prefill is a near-zero-cost cache hit. Health/occupancy order
        is preserved within each partition (this is a tiebreak, not an
        override)."""
        with self._lock:
            warm = [r for r in cands if key in r.seen_keys]
        if not warm:
            return cands
        warm_set = set(id(r) for r in warm)
        return warm + [r for r in cands if id(r) not in warm_set]

    def ingest_spool(self, replica: Optional[str],
                     checkpoints: Dict[str, str]) -> int:
        """POST /admin/spool: a restarted replica's crash-beacon journal,
        handed over by its supervisor. Each entry lands in the checkpoint
        registry keyed by request fingerprint; in-flight failovers (and
        parked `migrate_wait_s` waiters) pick them up."""
        n = 0
        for key, wire in checkpoints.items():
            key = parse_request_key(key)
            if key is None or not isinstance(wire, str):
                continue
            self.checkpoints.put(key, wire, source=replica)
            n += 1
        if n:
            self._m_spool_ingested.inc(n)
            if self.log is not None:
                self.log.event(
                    "spool_ingested", replica=replica, checkpoints=n,
                )
        return n

    def _retry_after_s(self, klass: int) -> float:
        """Retry-After for an unroutable request: the soonest a replica
        could return (cooldown expiry or next probe), clamped to [1, 30]."""
        now = self._now()
        etas = []
        with self._lock:
            for rep in self.replicas:
                if rep.mode != "active":
                    continue
                if rep.health == "ejected":
                    etas.append(rep.next_probe_at - now)
                else:
                    etas.append(rep.cooldowns.get(klass, now) - now)
        eta = min((e for e in etas if e > 0), default=1.0)
        return min(max(1.0, eta), 30.0)

    # ------------------------------------------------------------ dispatch

    def _claim(self, cands: List[Replica]) -> Tuple[
        Optional[Replica], List[Replica]
    ]:
        """Atomically pick the primary from an ordered candidate list.
        A half-open replica is claimed as THE trial under the same lock
        that read `trial_inflight` (closing the select-then-dispatch
        race that would send a burst of live traffic at a still-sick
        replica); the hedge pool excludes half-open replicas entirely —
        a duplicate dispatch is load, not a trial."""
        with self._lock:
            for i, rep in enumerate(cands):
                if rep.health == "half_open":
                    if rep.trial_inflight:
                        continue  # lost the claim race: not a candidate
                    rep.trial_inflight = True
                return rep, [
                    r for r in cands[i + 1:] if r.health != "half_open"
                ]
        return None, []

    def _begin_attempt(self, rep: Replica, rows: int,
                       key: Optional[str] = None) -> None:
        with self._lock:
            rep.outstanding_rows += rows
            rep.inflight += 1
            if key is not None:
                rep.inflight_keys[key] = rep.inflight_keys.get(key, 0) + 1
                # affinity memory: this replica's prefix cache plausibly
                # holds this prompt now (bounded LRU; migration
                # re-dispatch prefers cache-warm replicas)
                rep.seen_keys[key] = self._now()
                rep.seen_keys.move_to_end(key)
                while len(rep.seen_keys) > 512:
                    rep.seen_keys.popitem(last=False)
            self._m_outstanding.labels(rep.name).set(rep.outstanding_rows)
        self._m_requests.labels(rep.name).inc()

    def _end_attempt(self, rep: Replica, rows: int,
                     key: Optional[str] = None) -> None:
        with self._lock:
            rep.outstanding_rows = max(0, rep.outstanding_rows - rows)
            rep.inflight = max(0, rep.inflight - 1)
            if key is not None:
                n = rep.inflight_keys.get(key, 0) - 1
                if n <= 0:
                    rep.inflight_keys.pop(key, None)
                else:
                    rep.inflight_keys[key] = n
            self._m_outstanding.labels(rep.name).set(rep.outstanding_rows)
            if rep.mode == "draining" and rep.outstanding_rows == 0:
                rep.mode = "drained"
                self._set_state_gauge(rep)
                self._drained.notify_all()
                if self.log is not None:
                    self.log.event("replica_drained", replica=rep.name)

    def _post(self, rep: Replica, payload: bytes, headers: Dict[str, str],
              timeout_s: float, conns: List) -> Tuple[int, bytes, Dict]:
        """The one dispatch socket touch: POST /generate on `rep`. The
        connection object is appended to `conns` BEFORE the request so a
        hedging winner can close the loser mid-flight. Raises on
        transport failure."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=timeout_s
        )
        conns.append(conn)
        try:
            conn.request(
                "POST", "/generate", body=payload,
                headers={"Content-Type": "application/json", **headers},
            )
            resp = conn.getresponse()
            data = resp.read()
            keep = {}
            ra = resp.getheader("Retry-After")
            if ra is not None:
                keep["Retry-After"] = ra
            return resp.status, data, keep
        finally:
            conn.close()

    def _classify(self, res: Dict, klass: int) -> str:
        """One dispatch result -> `pass` (return to client), `failover`
        (breaker error, try elsewhere), `cooled` (replica-level
        backpressure: obey Retry-After for this class, try elsewhere) or
        `migrate` (the replica exported this request's decode state at a
        chunk boundary — re-dispatch it WITH the checkpoint; a healthy,
        deliberate hand-off, not a failure). 429 passes THROUGH: it is
        tenant-scoped (quota), and cooling the replica for the whole
        class would let one over-quota tenant make the class unroutable
        for everyone — the offending tenant must see its own 429 +
        Retry-After instead (the PR 11 isolation contract: a flooding
        tenant degrades only itself)."""
        if res["kind"] == "error":
            return "failover"
        status = res["status"]
        if status == 409:
            # only a replica's migrating drain answers 409 on /generate;
            # parse (and cache) the checkpoint off the body — an
            # unparseable body degrades to pass (the client sees the 409)
            ckpt = self._migrated_checkpoint(res)
            if ckpt is not None:
                return "migrate"
        if status == 503:
            return "cooled"
        if status >= 500 and status != 504:
            return "failover"
        # 2xx, 4xx (incl. the tenant-scoped 429), and 504 (the request
        # consumed its own deadline — retrying cannot meet it) pass
        return "pass"

    @staticmethod
    def _migrated_checkpoint(res: Dict) -> Optional[Dict]:
        """Parse a 409 body's migration payload once, memoized on the
        result dict; None unless it is a well-formed migrated response."""
        if "migrated_payload" not in res:
            payload = None
            try:
                obj = json.loads(res.get("body") or b"{}")
                if (
                    isinstance(obj, dict)
                    and obj.get("migrated") is True
                    and isinstance(obj.get("checkpoint"), str)
                ):
                    payload = obj
            except Exception:
                payload = None
            res["migrated_payload"] = payload
        return res["migrated_payload"]

    def _implicate_crash(self, rep: Replica, key: Optional[str],
                         error: str) -> None:
        """Quarantine attribution for one TRANSPORT failure: it reads as
        a replica crash/severed connection and implicates every request
        in flight there at that moment — the crash took them all down,
        and only repetition across incidents separates the cause from
        the bystanders. Replica 5xx answers never reach here (the
        replica survived; request-scoped poison is the replica's own
        batcher-side quarantine). Caller does NOT hold the lock."""
        if self.quarantine is None:
            return
        with self._lock:
            keys = set(rep.inflight_keys)
        if key is not None:
            keys.add(key)  # own attempt already _end_attempt-ed
        if not keys:
            return
        inc_id = self.quarantine.mint_incident(rep.name, error, keys)
        counts = {k: self.quarantine.implicate(k, inc_id) for k in keys}
        if self.log is not None:
            self.log.event(
                "crash_incident", incident=inc_id, replica=rep.name,
                error=error, implicated=len(keys),
                quarantined=[
                    k for k, c in counts.items()
                    if c >= self.quarantine.after
                ],
            )

    def _settle(self, res: Dict, rep: Replica, klass: int,
                key: Optional[str] = None) -> str:
        """Record one arrived result into the breaker/cooldowns (and the
        quarantine ledger); returns its classification."""
        kind = self._classify(res, klass)
        if kind == "failover":
            transport = res["kind"] == "error"
            error = (
                repr(res["error"]) if transport else f"http {res['status']}"
            )
            with self._lock:
                rep.last_error = error
            if (
                transport
                and not isinstance(res.get("error"), TimeoutError)
                and not res.get("cancelled")
            ):
                # crash evidence only: connect refused / reset / severed
                # mid-response. A client-side SOCKET TIMEOUT means the
                # replica was slow, not dead (socket.timeout is a
                # TimeoutError alias), and a hedge-win CANCELLATION
                # means WE closed the loser's connection — implicating
                # on either would let a slow spell or routine hedging
                # quarantine innocent prompts against healthy replicas.
                self._implicate_crash(rep, key, error)
            self._record_dispatch(rep, ok=False)
        elif kind == "cooled":
            try:
                ra = float(res.get("headers", {}).get("Retry-After", 1.0))
            except (TypeError, ValueError):
                ra = 1.0
            self._cool(rep, klass, ra)
            # explicit backpressure is a HEALTHY refusal: it must not
            # open the circuit (a queue-full burst would otherwise eject
            # the exact replica that is correctly protecting itself)
            self._record_dispatch(rep, ok=True)
        elif kind == "migrate":
            # a migrating drain is a deliberate, healthy hand-off: no
            # breaker evidence, no cooldown (the drain itself already
            # removed the replica from rotation), no implication
            self._record_dispatch(rep, ok=True)
        else:
            self._record_dispatch(rep, ok=res["status"] < 500)
            if res["status"] == 200:
                self.budget.deposit()
                if self.quarantine is not None and key is not None:
                    # a completed request demonstrably doesn't kill
                    # replicas: end its implication streak
                    self.quarantine.absolve(key)
        self._m_budget.set(self.budget.balance)
        return kind

    def _dispatch_hedged(
        self, primary: Replica, hedge_pool: List[Replica], payload: bytes,
        trace, attempt: int, rows: int, klass: int, timeout_s: float,
        key: Optional[str] = None,
    ) -> Tuple[Dict, str, bool]:
        """One routing attempt: dispatch to `primary`, optionally hedge
        to the best of `hedge_pool` after `hedge_after_s`, first usable
        answer wins (loser's connection closed). Returns (winning
        result, its classification, hedged?). Each dispatch thread
        settles its OWN result into the breaker/cooldowns/budget before
        queueing it — a result abandoned after a hedge win (or an
        orchestrator timeout) still does its bookkeeping exactly once,
        so a half-open trial can never be left claimed forever."""
        results: "queue_mod.Queue[Dict]" = queue_mod.Queue()
        conns: List = []
        #: set by the winner BEFORE it closes the loser's connection, so
        #: the loser's resulting transport error reads as CANCELLATION —
        #: not crash evidence against a healthy replica (the quarantine
        #: ledger must never fill with hedge-win artifacts)
        won = threading.Event()

        def run(rep: Replica, hedged: bool) -> None:
            span = trace.begin(
                "dispatch", replica=rep.name, attempt=attempt,
                hedged=hedged,
            )
            headers = {ROUTE_HEADER: format_route_header(
                rep.name, attempt, hedged
            )}
            if key is not None:
                # content join key: the replica keys its crash-spool
                # checkpoints (and its log lines) on it
                headers[REQUEST_KEY_HEADER] = key
            if trace:
                headers[TRACE_HEADER] = format_trace_header(
                    trace.trace_id, self._span_uid(span)
                )
            self._begin_attempt(rep, rows, key=key)
            try:
                try:
                    status, data, keep = self._post(
                        rep, payload, headers, timeout_s, conns
                    )
                except Exception as exc:
                    trace.end(span, error=repr(exc))
                    res = {
                        "kind": "error", "replica": rep, "error": exc,
                        "hedged": hedged, "cancelled": won.is_set(),
                    }
                else:
                    trace.end(span, status=status)
                    res = {
                        "kind": "http", "replica": rep, "status": status,
                        "body": data, "headers": keep, "hedged": hedged,
                    }
            finally:
                self._end_attempt(rep, rows, key=key)
            res["disposition"] = self._settle(res, rep, klass, key=key)
            results.put(res)

        threading.Thread(
            target=run, args=(primary, False),
            name="dalle-router-dispatch", daemon=True,
        ).start()
        launched = 1
        hedged_used = False
        first: Optional[Dict] = None
        if self.hedge_after_s is not None and hedge_pool:
            try:
                first = results.get(timeout=self.hedge_after_s)
            except queue_mod.Empty:
                # primary is slow: duplicate to the next candidate if the
                # budget allows (hedges draw from the same budget as
                # retries — tail insurance must not amplify an outage)
                if self.budget.withdraw():
                    self._m_budget.set(self.budget.balance)
                    self._m_hedges.inc()
                    hedged_used = True
                    threading.Thread(
                        target=run, args=(hedge_pool[0], True),
                        name="dalle-router-hedge", daemon=True,
                    ).start()
                    launched += 1
        best: Optional[Tuple[Dict, str]] = None
        for _ in range(launched):
            if first is not None:
                res, first = first, None
            else:
                try:
                    # generous wall bound: each attempt's socket timeout
                    # already caps it; this is belt-and-braces against a
                    # lost thread
                    res = results.get(timeout=timeout_s + 10.0)
                except queue_mod.Empty:
                    break
            kind = res["disposition"]  # settled by the dispatch thread
            if kind == "pass":
                if res["hedged"]:
                    self._m_hedge_wins.inc()
                won.set()  # before the close: the loser's error is a
                for conn in conns:  # cancellation, not crash evidence
                    try:
                        conn.close()
                    except Exception:
                        pass
                return res, kind, hedged_used
            best = (res, kind)  # keep waiting for a better answer
        if best is None:  # every dispatch thread got lost past its own
            res = {  # socket timeout: treat as a transport failure —
                "kind": "error", "replica": primary,  # NOT settled (the
                "error": TimeoutError("dispatch produced no result"),
                "hedged": False,  # lost thread will settle its own)
                "disposition": "failover",
            }
            return res, "failover", hedged_used
        return best[0], best[1], hedged_used

    # ------------------------------------------------------------ requests

    def _record_usage(self, body: Dict, res: Dict, wall_s: float) -> None:
        """Attribute one successful dispatch to the usage ledger:
        replica-reported wall (`latency_ms`, the chip-second basis) and
        the response's `usage` token block, falling back to router-side
        wall when the body carries neither. Accounting only — a broken
        body must never fail the reply it is accounting for."""
        try:
            usage: Dict = {}
            latency_ms = None
            try:
                payload = json.loads(res.get("body") or b"{}")
                if isinstance(payload, dict):
                    u = payload.get("usage")
                    usage = u if isinstance(u, dict) else {}
                    latency_ms = payload.get("latency_ms")
            except Exception:
                pass
            wall = (
                float(latency_ms) / 1000.0
                if isinstance(latency_ms, (int, float)) else float(wall_s)
            )
            rep = res.get("replica")
            self.usage.record(
                tenant=body.get("tenant"),
                priority=str(body.get("priority", "normal")),
                rows=int(body.get("num_images", 1) or 1),
                wall_s=wall,
                decoded_tokens=int(usage.get("decoded_tokens") or 0),
                resumed_tokens=int(usage.get("resumed_tokens") or 0),
                replica=rep.name if rep is not None else None,
            )
        except Exception:
            pass

    def handle_generate(self, raw: bytes, inbound_headers) -> Tuple[
        int, bytes, List[Tuple[str, str]]
    ]:
        """Route one client /generate body through the fleet. Returns
        (status, response body, extra headers) for the HTTP layer."""
        try:
            body = json.loads(raw)
            assert isinstance(body, dict), "body must be a JSON object"
            priority = body.get("priority", "normal")
            assert priority in PRIORITY_CLASSES, (
                f"priority must be one of {list(PRIORITY_CLASSES)}"
            )
            rows = int(body.get("num_images", 1))
            assert rows >= 1, "num_images must be >= 1"
            timeout_s = float(body.get("timeout_s", self.request_timeout_s))
            assert 0.0 < timeout_s <= self.request_timeout_s, (
                f"timeout_s must be in (0, {self.request_timeout_s}]"
            )
        except Exception as exc:
            return 400, json.dumps(
                {"error": f"bad request: {exc}"}
            ).encode(), []
        klass = priority_class(priority)
        # quarantine key BEFORE the seed pin: content identity, so an
        # identical resubmission (which would draw a fresh seed) is still
        # recognized as the same poison request
        qkey = (
            request_fingerprint(body) if self.quarantine is not None
            else None
        )
        if qkey is not None and self.quarantine.is_quarantined(qkey):
            self._m_quarantined.inc()
            incidents = self.quarantine.incidents_for(qkey)
            if self.log is not None:
                self.log.event(
                    "quarantine_refused", key=qkey, incidents=incidents,
                )
            return 422, json.dumps({
                "error": "request quarantined: implicated in "
                f"{len(incidents)} consecutive replica crash incidents",
                "incidents": incidents,
            }).encode(), []
        if body.get("seed") is None:
            body["seed"] = self.next_seed(rows)
        payload = json.dumps(body).encode("utf-8")

        ctx = parse_trace_header(inbound_headers.get(TRACE_HEADER))
        trace = self.tracer.start_trace(
            "route",
            trace_id=ctx[0] if ctx else None,
            parent_uid=ctx[1] if ctx else None,
            rows=rows, priority=priority,
        )
        t0 = self._now()
        deadline = t0 + timeout_s
        tried: set = set()
        attempt = 0
        last: Optional[Tuple[Dict, str]] = None
        hedged_any = False
        # migration state: once a checkpoint is attached (drain 409 or
        # crash-spool hit) every further dispatch of this request is a
        # RESUME — the target replica restores completed rows verbatim
        free_attempts = 0  # migrate re-dispatches don't draw retry budget
        resume_reason: Optional[str] = None
        migrated_from: Optional[str] = None
        resumed_at_chunk: Optional[int] = None

        def mig_fields() -> Dict:
            if resume_reason is None:
                return {}
            out = {"migrated_from": migrated_from, "resume": resume_reason}
            if resumed_at_chunk is not None:
                out["resumed_at_chunk"] = resumed_at_chunk
            return out

        def closed_out(outcome: str, status: int, replica=None, **fields):
            trace.finish(outcome=outcome)
            if self.log is not None:
                self.log.request(
                    trace_id=trace.trace_id if trace else None,
                    outcome=outcome, status=status,
                    latency_ms=round((self._now() - t0) * 1e3, 2),
                    stages=trace.stage_seconds(),
                    replica=replica, attempt=attempt, hedged=hedged_any,
                    priority=priority, rows=rows,
                    **mig_fields(), **fields,
                )

        while True:
            now = self._now()
            if now >= deadline:
                closed_out("timeout", 504)
                return 504, json.dumps({
                    "error": "router exhausted the request deadline "
                    "across failover attempts"
                }).encode(), []
            cands = self._routable(klass, tried)
            if not cands and tried:
                # nothing NEW to try: fall back to the full candidate
                # set (a flapping fleet beats an instant give-up when
                # the budget still allows a retry)
                cands = self._routable(klass, frozenset())
            if not cands:
                self._m_unroutable.inc()
                retry = self._retry_after_s(klass)
                closed_out(
                    "unroutable", 503,
                    replica=last[0]["replica"].name if last else None,
                )
                err = (
                    "no replica routable for priority "
                    f"{priority!r} (all ejected, draining, or cooling)"
                )
                return 503, json.dumps({"error": err}).encode(), [
                    ("Retry-After", str(int(round(retry))))
                ]
            if resume_reason is not None and qkey is not None:
                # resume re-dispatch: prefer replicas that recently saw
                # this fingerprint — their prefix cache plausibly holds
                # the prompt, so the resume's re-prefill is a cache hit
                cands = self._prefer_cache_warm(cands, qkey)
            if attempt - free_attempts > 0 and not self.budget.withdraw():
                # budget empty: surface the LAST failure instead of
                # hammering recovering replicas with more attempts
                # (migrate re-dispatches are exempt — a rolling drain is
                # deliberate fleet maintenance, not failure retry, and
                # must not be starved by an unrelated outage's drained
                # budget). (Checked BEFORE the trial claim below, so an
                # early return can never leak a claimed half-open trial.)
                self._m_budget.set(self.budget.balance)
                closed_out(
                    "budget_exhausted", 503,
                    replica=last[0]["replica"].name if last else None,
                )
                return 503, json.dumps({
                    "error": "retry budget exhausted (fleet-wide "
                    "failures; no retry capacity left)"
                }).encode(), [("Retry-After", "1")]
            self._m_budget.set(self.budget.balance)
            primary, hedge_pool = self._claim(cands)
            if primary is None:
                # every remaining candidate is a half-open replica whose
                # trial another request just claimed: brief condition,
                # tell the client to come right back
                self._m_unroutable.inc()
                closed_out(
                    "unroutable", 503,
                    replica=last[0]["replica"].name if last else None,
                )
                return 503, json.dumps({
                    "error": "all routable replicas are mid-trial "
                    "(recovering); retry shortly"
                }).encode(), [("Retry-After", "1")]
            timeout_attempt = min(
                self.attempt_timeout_s, max(0.1, deadline - now)
            )
            res, kind, hedged = self._dispatch_hedged(
                primary, hedge_pool, payload, trace, attempt, rows,
                klass, timeout_attempt, key=qkey,
            )
            hedged_any = hedged_any or hedged
            if kind == "migrate":
                # the draining replica exported this request's decode
                # state at a chunk boundary: re-dispatch THE SAME request
                # (same key, same trace, same seed) with the checkpoint
                # attached so the next replica resumes instead of
                # restarting from scratch
                payload409 = res["migrated_payload"]
                body["resume"] = payload409["checkpoint"]
                payload = json.dumps(body).encode("utf-8")
                migrated_from = res["replica"].name
                resume_reason = "drain"
                rc = payload409.get("resumed_at_chunk")
                resumed_at_chunk = int(rc) if rc is not None else None
                self._m_migrations.labels("drain").inc()
                if self.log is not None:
                    self.log.event(
                        "request_migrated", reason="drain",
                        replica=res["replica"].name, key=qkey,
                        resumed_at_chunk=resumed_at_chunk,
                        checkpoint_bytes=len(payload409["checkpoint"]),
                    )
                free_attempts += 1
                tried.add(res["replica"].name)
                last = (res, kind)
                attempt += 1
                continue
            if kind == "pass":
                status = res["status"]
                outcome = "ok" if status == 200 else "replica_status"
                if status == 200 and resume_reason is not None:
                    with self._lock:
                        res["replica"].resumes += 1
                if status == 200:
                    # usage accounting off the reply's own metadata
                    # (never fails the reply; tenant rides the body)
                    self._record_usage(body, res, self._now() - t0)
                closed_out(
                    outcome, status, replica=res["replica"].name,
                )
                extra = [("x-dalle-replica", res["replica"].name)]
                extra.extend(res.get("headers", {}).items())
                return status, res["body"], extra
            if (
                qkey is not None
                and self.quarantine.is_quarantined(qkey)
            ):
                # THIS request's implication streak just crossed the
                # threshold: stop failing over — re-dispatching a
                # replica-killer serially takes down the fleet
                self._m_quarantined.inc()
                incidents = self.quarantine.incidents_for(qkey)
                closed_out(
                    "quarantined", 422, replica=res["replica"].name,
                    incidents=incidents,
                )
                return 422, json.dumps({
                    "error": "request quarantined: implicated in "
                    f"{len(incidents)} consecutive replica crash "
                    "incidents",
                    "incidents": incidents,
                }).encode(), []
            # failover: count it, exclude the loser, loop (bounded by
            # the retry budget withdrawn at the top of the loop)
            reason = (
                "transport" if res["kind"] == "error"
                else "backpressure" if kind == "cooled"
                else "status"
            )
            if (
                reason == "transport" and qkey is not None
                and resume_reason is None
            ):
                # crash path: a spooled checkpoint for this request (the
                # supervisor hands the dead replica's journal over on
                # restart) turns the from-scratch re-dispatch into a
                # resume — optionally parking up to migrate_wait_s for
                # the hand-off to arrive
                entry = self.checkpoints.take(qkey)
                if entry is None and self.migrate_wait_s > 0:
                    entry = self.checkpoints.wait_for(
                        qkey,
                        min(self.migrate_wait_s,
                            max(0.0, deadline - self._now())),
                    )
                if entry is not None:
                    body["resume"] = entry["wire"]
                    payload = json.dumps(body).encode("utf-8")
                    migrated_from = entry.get("source")
                    resume_reason = "crash"
                    self._m_migrations.labels("crash").inc()
                    if self.log is not None:
                        self.log.event(
                            "request_migrated", reason="crash",
                            replica=res["replica"].name, key=qkey,
                            source=entry.get("source"),
                            checkpoint_bytes=len(entry["wire"]),
                        )
            self._m_failovers.labels(reason).inc()
            tried.add(res["replica"].name)
            last = (res, kind)
            attempt += 1

    # ---------------------------------------------------------- streaming

    #: seconds of upstream silence before a streaming dispatch reads as
    #: wedged and fails over — replicas keep-alive every ~10s, so this is
    #: three missed heartbeats, not one slow chunk
    stream_read_timeout_s: float = 30.0
    #: idle keep-alive cadence toward the CLIENT while splicing (covers
    #: seams where upstream bytes arrive but nothing new is forwardable)
    stream_keepalive_s: float = 10.0

    def handle_generate_stream(self, raw: bytes, inbound_headers,
                               write) -> Optional[Tuple[
                                   int, bytes, List[Tuple[str, str]]
                               ]]:
        """Route one STREAMING /generate through the fleet, splicing the
        replicas' SSE event streams into ONE continuous client stream.

        `write(bytes)` ships frames to the client (the HTTP layer sends
        the SSE response head lazily on the first call). Returns a
        `(status, body, headers)` tuple only while NOTHING has been
        written yet (plain JSON error reply); returns None once the
        stream started — every later failure reaches the client as an
        `error` event, and a migrated/failed-over request is
        re-dispatched (resume checkpoint attached, same key/seed/trace)
        with the new replica's events spliced on. The splice is
        content-addressed: progress/preview events carry the
        request-level chunk index, and only an index ABOVE the client's
        high water is forwarded — a resumed replica re-announcing chunks
        the client has seen (or a non-resume restart replaying from 0)
        is swallowed, so the client observes a gapless, duplicate-free
        sequence across every seam. Client-facing `id:` sequence numbers
        are the router's own (upstream streams restart per replica).

        No hedging for streams: a duplicated stream would double-decode
        for its whole lifetime, not just the tail."""
        try:
            body = json.loads(raw)
            assert isinstance(body, dict), "body must be a JSON object"
            assert body.get("stream") is True, "not a streaming request"
            priority = body.get("priority", "normal")
            assert priority in PRIORITY_CLASSES, (
                f"priority must be one of {list(PRIORITY_CLASSES)}"
            )
            rows = int(body.get("num_images", 1))
            assert rows >= 1, "num_images must be >= 1"
            timeout_s = float(body.get("timeout_s", self.request_timeout_s))
            assert 0.0 < timeout_s <= self.request_timeout_s, (
                f"timeout_s must be in (0, {self.request_timeout_s}]"
            )
        except Exception as exc:
            return 400, json.dumps(
                {"error": f"bad request: {exc}"}
            ).encode(), []
        klass = priority_class(priority)
        qkey = (
            request_fingerprint(body) if self.quarantine is not None
            else None
        )
        if qkey is not None and self.quarantine.is_quarantined(qkey):
            self._m_quarantined.inc()
            incidents = self.quarantine.incidents_for(qkey)
            return 422, json.dumps({
                "error": "request quarantined: implicated in "
                f"{len(incidents)} consecutive replica crash incidents",
                "incidents": incidents,
            }).encode(), []
        if body.get("seed") is None:
            # seed pinned before attempt one: re-dispatches decode
            # bit-identical tokens, which is what makes the chunk-index
            # dedup below CORRECT and not just tidy
            body["seed"] = self.next_seed(rows)
        payload = json.dumps(body).encode("utf-8")

        ctx = parse_trace_header(inbound_headers.get(TRACE_HEADER))
        trace = self.tracer.start_trace(
            "route",
            trace_id=ctx[0] if ctx else None,
            parent_uid=ctx[1] if ctx else None,
            rows=rows, priority=priority, streamed=True,
        )
        t0 = self._now()
        deadline = t0 + timeout_s
        tried: set = set()
        attempt = 0
        free_attempts = 0
        resume_reason: Optional[str] = None
        migrated_from: Optional[str] = None
        resumed_at_chunk: Optional[int] = None
        last: Optional[Dict] = None

        # client-facing splice state: one outgoing sequence, one chunk
        # high water per event type, one `open` ever
        out_seq = 0
        progress_hw = -1
        preview_hw = -1
        opened = False
        started = False  # any byte reached the client

        def forward(etype: str, data: dict) -> None:
            nonlocal out_seq, started
            write(encode_sse(etype, data, seq=out_seq))
            out_seq += 1
            started = True

        def mig_fields() -> Dict:
            if resume_reason is None:
                return {}
            out = {"migrated_from": migrated_from, "resume": resume_reason}
            if resumed_at_chunk is not None:
                out["resumed_at_chunk"] = resumed_at_chunk
            return out

        def closed_out(outcome: str, status: int, replica=None, **fields):
            trace.finish(outcome=outcome)
            if self.log is not None:
                self.log.request(
                    trace_id=trace.trace_id if trace else None,
                    outcome=outcome, status=status,
                    latency_ms=round((self._now() - t0) * 1e3, 2),
                    stages=trace.stage_seconds(),
                    replica=replica, attempt=attempt, hedged=False,
                    priority=priority, rows=rows, streamed=True,
                    stream_events=out_seq,
                    **mig_fields(), **fields,
                )

        def fail(outcome: str, status: int, err: dict, extra=(),
                 replica=None, **fields):
            """One exit for every routing failure: JSON reply while the
            stream hasn't started, a terminal `error` event once it
            has."""
            closed_out(outcome, status, replica=replica, **fields)
            if not started:
                return status, json.dumps(err).encode(), list(extra)
            forward("error", dict(err, status=status))
            return None

        def run_attempt(rep: Replica) -> Tuple[Dict, Tuple[str, object]]:
            """One streaming dispatch to `rep`. Returns (res, marker):
            `res` feeds `_settle`; marker is ("done", status) — terminal
            forwarded, stream complete; ("migrated", event data) — the
            replica handed back a checkpoint mid-stream; ("http", None)
            — non-SSE answer, classify like the buffered path;
            ("deadline", None); or ("retry", None) — transport/5xx
            failure, try elsewhere. Client-socket write failures
            propagate (the caller severs upstream, which makes the
            replica orphan the stream and cancel the decode)."""
            nonlocal opened, progress_hw, preview_hw, started
            span = trace.begin(
                "dispatch", replica=rep.name, attempt=attempt,
                streamed=True,
            )
            headers = {
                "Content-Type": "application/json",
                ROUTE_HEADER: format_route_header(rep.name, attempt, False),
            }
            if qkey is not None:
                headers[REQUEST_KEY_HEADER] = qkey
            if trace:
                headers[TRACE_HEADER] = format_trace_header(
                    trace.trace_id, self._span_uid(span)
                )
            self._begin_attempt(rep, rows, key=qkey)
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.stream_read_timeout_s
            )
            try:
                try:
                    conn.request(
                        "POST", "/generate", body=payload, headers=headers
                    )
                    resp = conn.getresponse()
                except Exception as exc:
                    trace.end(span, error=repr(exc))
                    return {
                        "kind": "error", "replica": rep, "error": exc,
                        "hedged": False, "cancelled": False,
                    }, ("retry", None)
                if resp.status != 200 or "text/event-stream" not in (
                    resp.getheader("Content-Type") or ""
                ):
                    data = resp.read()
                    keep = {}
                    ra = resp.getheader("Retry-After")
                    if ra is not None:
                        keep["Retry-After"] = ra
                    trace.end(span, status=resp.status)
                    return {
                        "kind": "http", "replica": rep,
                        "status": resp.status, "body": data,
                        "headers": keep, "hedged": False,
                    }, ("http", None)
                parser = SSEParser()
                last_write = self._now()
                while True:
                    if self._now() >= deadline:
                        trace.end(span, error="deadline")
                        return {
                            "kind": "http", "replica": rep, "status": 504,
                            "body": b"", "headers": {}, "hedged": False,
                        }, ("deadline", None)
                    try:
                        chunk = resp.read1(65536)
                    except Exception as exc:  # incl. socket timeouts
                        trace.end(span, error=repr(exc))
                        return {
                            "kind": "error", "replica": rep, "error": exc,
                            "hedged": False, "cancelled": False,
                        }, ("retry", None)
                    if not chunk:
                        # EOF without a terminal event: severed stream
                        # (hard kill mid-decode) — crash-grade evidence
                        exc = ConnectionError(
                            "replica stream ended without a terminal event"
                        )
                        trace.end(span, error=repr(exc))
                        return {
                            "kind": "error", "replica": rep, "error": exc,
                            "hedged": False, "cancelled": False,
                        }, ("retry", None)
                    forwarded = False
                    for etype, data, _seq in parser.feed(chunk):
                        if etype == "open":
                            if not opened:
                                opened = True
                                forward("open", data)
                                forwarded = True
                            continue
                        if etype in ("progress", "preview"):
                            c = int(data.get("chunk", -1))
                            if etype == "progress":
                                if c <= progress_hw:
                                    continue  # replayed chunk: swallow
                                progress_hw = c
                            else:
                                if c <= preview_hw:
                                    continue
                                preview_hw = c
                            forward(etype, data)
                            forwarded = True
                            continue
                        if etype == "migrated":
                            trace.end(span, status=409)
                            # settle EXACTLY like a buffered 409: the
                            # synthetic body keys the migrate disposition
                            return {
                                "kind": "http", "replica": rep,
                                "status": 409,
                                "body": json.dumps(
                                    dict(data, migrated=True)
                                ).encode(),
                                "headers": {}, "hedged": False,
                            }, ("migrated", data)
                        if etype == "error":
                            status = int(data.get("status", 500))
                            if status >= 500 and status != 504:
                                # replica-side failure terminal: NOT
                                # forwarded — fail over (a resume may
                                # still rescue the decode)
                                trace.end(span, status=status)
                                return {
                                    "kind": "http", "replica": rep,
                                    "status": status,
                                    "body": json.dumps(data).encode(),
                                    "headers": {}, "hedged": False,
                                }, ("retry", None)
                            forward("error", data)
                            trace.end(span, status=status)
                            return {
                                "kind": "http", "replica": rep,
                                "status": status, "body": b"",
                                "headers": {}, "hedged": False,
                            }, ("done", status)
                        if etype == "result":
                            forward("result", data)
                            trace.end(span, status=200)
                            return {
                                "kind": "http", "replica": rep,
                                "status": 200, "body": b"",
                                "headers": {}, "hedged": False,
                            }, ("done", 200)
                        forward(etype, data)  # unknown types pass through
                        forwarded = True
                    if forwarded:
                        last_write = self._now()
                    elif (
                        self._now() - last_write >= self.stream_keepalive_s
                    ):
                        write(KEEPALIVE)
                        started = True  # response head is on the wire now
                        last_write = self._now()
            finally:
                # severing the upstream connection on ANY exit makes the
                # abandoned replica handler orphan its stream and cancel
                # the decode at the next chunk boundary
                conn.close()
                self._end_attempt(rep, rows, key=qkey)

        while True:
            now = self._now()
            if now >= deadline:
                return fail(
                    "timeout", 504,
                    {"error": "router exhausted the request deadline "
                     "across failover attempts"},
                    replica=last["replica"].name if last else None,
                )
            cands = self._routable(klass, tried)
            if not cands and tried:
                cands = self._routable(klass, frozenset())
            if not cands:
                self._m_unroutable.inc()
                retry = self._retry_after_s(klass)
                return fail(
                    "unroutable", 503,
                    {"error": "no replica routable for priority "
                     f"{priority!r} (all ejected, draining, or cooling)"},
                    extra=[("Retry-After", str(int(round(retry))))],
                    replica=last["replica"].name if last else None,
                )
            if resume_reason is not None and qkey is not None:
                cands = self._prefer_cache_warm(cands, qkey)
            if attempt - free_attempts > 0 and not self.budget.withdraw():
                self._m_budget.set(self.budget.balance)
                return fail(
                    "budget_exhausted", 503,
                    {"error": "retry budget exhausted (fleet-wide "
                     "failures; no retry capacity left)"},
                    extra=[("Retry-After", "1")],
                    replica=last["replica"].name if last else None,
                )
            self._m_budget.set(self.budget.balance)
            primary, _hedge_pool = self._claim(cands)
            if primary is None:
                self._m_unroutable.inc()
                return fail(
                    "unroutable", 503,
                    {"error": "all routable replicas are mid-trial "
                     "(recovering); retry shortly"},
                    extra=[("Retry-After", "1")],
                    replica=last["replica"].name if last else None,
                )
            try:
                res, (marker, minfo) = run_attempt(primary)
            except (BrokenPipeError, ConnectionResetError):
                # OUR client went away mid-stream: upstream is already
                # severed (run_attempt's finally), which cancels the
                # decode on the replica — nothing left to route
                closed_out(
                    "disconnected", 200, replica=primary.name,
                )
                return None
            kind = self._settle(res, primary, klass, key=qkey)
            last = res
            if marker == "done":
                if int(minfo) == 200 and resume_reason is not None:
                    with self._lock:
                        primary.resumes += 1
                if int(minfo) == 200:
                    # streamed bytes passed through unparsed: record the
                    # wall-clock side of the usage row (token counts ride
                    # only the buffered path's usage block)
                    self._record_usage(
                        body, {"replica": primary}, self._now() - t0,
                    )
                closed_out(
                    "ok" if int(minfo) == 200 else "replica_status",
                    int(minfo), replica=primary.name,
                )
                return None
            if marker == "deadline":
                return fail(
                    "timeout", 504,
                    {"error": "router exhausted the request deadline "
                     "mid-stream"},
                    replica=primary.name,
                )
            if marker == "migrated" or kind == "migrate":
                # checkpoint hand-off (mid-stream terminal event, or a
                # buffered-style 409): re-dispatch THE SAME request as a
                # resume; its replayed chunks fall below the high water
                payload409 = (
                    dict(minfo) if marker == "migrated"
                    else self._migrated_checkpoint(res)
                )
                body["resume"] = payload409["checkpoint"]
                payload = json.dumps(body).encode("utf-8")
                migrated_from = payload409.get("migrated_from") or (
                    res["replica"].name
                )
                resume_reason = "drain"
                rc = payload409.get("resumed_at_chunk")
                resumed_at_chunk = int(rc) if rc is not None else None
                self._m_migrations.labels("drain").inc()
                if self.log is not None:
                    self.log.event(
                        "request_migrated", reason="drain", streamed=True,
                        replica=res["replica"].name, key=qkey,
                        resumed_at_chunk=resumed_at_chunk,
                        checkpoint_bytes=len(payload409["checkpoint"]),
                    )
                free_attempts += 1
                tried.add(res["replica"].name)
                attempt += 1
                continue
            if marker == "http" and kind == "pass":
                # non-SSE replica answer (400/422/429/504...): surface it
                status = res["status"]
                if not started:
                    closed_out(
                        "replica_status", status, replica=primary.name,
                    )
                    extra = [("x-dalle-replica", primary.name)]
                    extra.extend(res.get("headers", {}).items())
                    return status, res["body"], extra
                try:
                    err = json.loads(res["body"] or b"{}")
                    assert isinstance(err, dict)
                except Exception:
                    err = {"error": f"replica answered {status}"}
                return fail(
                    "replica_status", status, err, replica=primary.name,
                )
            if (
                qkey is not None
                and self.quarantine.is_quarantined(qkey)
            ):
                self._m_quarantined.inc()
                incidents = self.quarantine.incidents_for(qkey)
                return fail(
                    "quarantined", 422,
                    {"error": "request quarantined: implicated in "
                     f"{len(incidents)} consecutive replica crash "
                     "incidents",
                     "incidents": incidents},
                    replica=primary.name, incidents=incidents,
                )
            # failover: transport failure, severed stream, 5xx terminal,
            # or cooled backpressure — identical bookkeeping to the
            # buffered path, including the crash-spool resume rescue
            reason = (
                "transport" if res["kind"] == "error"
                else "backpressure" if kind == "cooled"
                else "status"
            )
            if (
                reason == "transport" and qkey is not None
                and resume_reason is None
            ):
                entry = self.checkpoints.take(qkey)
                if entry is None and self.migrate_wait_s > 0:
                    entry = self.checkpoints.wait_for(
                        qkey,
                        min(self.migrate_wait_s,
                            max(0.0, deadline - self._now())),
                    )
                if entry is not None:
                    body["resume"] = entry["wire"]
                    payload = json.dumps(body).encode("utf-8")
                    migrated_from = entry.get("source")
                    resume_reason = "crash"
                    self._m_migrations.labels("crash").inc()
                    if self.log is not None:
                        self.log.event(
                            "request_migrated", reason="crash",
                            streamed=True, replica=res["replica"].name,
                            key=qkey, source=entry.get("source"),
                            checkpoint_bytes=len(entry["wire"]),
                        )
            self._m_failovers.labels(reason).inc()
            tried.add(res["replica"].name)
            attempt += 1

    # --------------------------------------------------------------- admin

    def _find(self, name: str) -> Optional[Replica]:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        return None

    def _propagate_admin(self, rep: Replica, action: str,
                         query: str = ""):
        """Best-effort POST of the replica's own /admin/<action> so
        direct clients are refused during the drain window too. Returns
        (error string | None, parsed response body | None) — the body is
        a plain return value, never shared state, so concurrent admin
        drains cannot read each other's bundles."""
        try:
            req = urllib.request.Request(
                rep.url + f"/admin/{action}" + (f"?{query}" if query else ""),
                data=b"", method="POST",
            )
            with urllib.request.urlopen(
                req, timeout=max(self.probe_timeout_s, 35.0 if query else 0)
            ) as resp:
                raw = resp.read()
            try:
                body = json.loads(raw or b"{}")
            except Exception:
                body = None
            return None, body
        except Exception as exc:
            return repr(exc), None

    def drain(self, name: str, wait_s: float = 0.0,
              propagate: bool = False,
              migrate: bool = False) -> Optional[Dict]:
        """Stop new admissions to `name`, wait out its outstanding rows
        (up to `wait_s`), eject it from rotation as `drained`. Returns
        the replica's state dict, or None for an unknown name.

        `migrate=True` (implies propagate) makes it a ZERO-LOST-WORK
        drain: the replica exports every queued + in-flight request as a
        decode-state checkpoint at its next chunk boundary — the blocked
        dispatch threads get 409s and re-dispatch each request as a
        resume on a healthy replica — so the drain completes in roughly
        one chunk instead of one full decode, re-decoding only the
        unfinished rows. The returned bundle is also ingested into the
        checkpoint registry (belt and braces for direct-client
        requests)."""
        rep = self._find(name)
        if rep is None:
            return None
        with self._lock:
            if rep.mode == "active":
                rep.mode = "draining"
                if rep.outstanding_rows == 0:
                    rep.mode = "drained"
            self._set_state_gauge(rep)
        if self.log is not None:
            self.log.event(
                "replica_drain", replica=name, mode=rep.mode,
                migrate=migrate,
                outstanding_rows=rep.outstanding_rows,
            )
        if propagate or migrate:
            err, body = self._propagate_admin(
                rep, "drain", query="migrate=1" if migrate else ""
            )
            if err and self.log is not None:
                self.log.event(
                    "replica_drain_propagate_failed", replica=name,
                    error=err,
                )
            if migrate and not err:
                bundle = (
                    (body or {}).get("migrate") or {}
                ).get("checkpoints") or {}
                for key, wire in bundle.items():
                    key = parse_request_key(key)
                    if key is not None and isinstance(wire, str):
                        self.checkpoints.put(key, wire, source=name)
        if wait_s > 0:
            # injectable clock like every other timing path, so a
            # stubbed-clock chaos test can expire the wait
            # deterministically (real waits still tick via the
            # 0.1s-capped condition timeout)
            deadline = self._now() + wait_s
            with self._lock:
                while rep.mode == "draining":
                    remaining = deadline - self._now()
                    if remaining <= 0:
                        break
                    self._drained.wait(timeout=min(remaining, 0.1))
        return rep.detail(self._now())

    def undrain(self, name: str, propagate: bool = False) -> Optional[Dict]:
        """Return a drained/draining replica to rotation (health resets
        to half-open so live traffic must prove it before it carries
        full load; the next probe runs immediately)."""
        rep = self._find(name)
        if rep is None:
            return None
        now = self._now()
        with self._lock:
            rep.mode = "active"
            # a replica coming back from a restart proves itself like a
            # recovering one: one trial closes the circuit
            rep.health = "half_open"
            rep.trial_inflight = False
            rep.probe_failures = 0
            rep.next_probe_at = now
            self._set_state_gauge(rep)
        if propagate:
            err, _ = self._propagate_admin(rep, "undrain")
            if err and self.log is not None:
                self.log.event(
                    "replica_undrain_propagate_failed", replica=name,
                    error=err,
                )
        if self.log is not None:
            self.log.event("replica_undrain", replica=name)
        return rep.detail(now)

    # --------------------------------------------------------------- views

    def health(self) -> Tuple[bool, Dict]:
        now = self._now()
        with self._lock:
            states = {rep.name: rep.state() for rep in self.replicas}
        n_healthy = sum(1 for s in states.values() if s == "healthy")
        n_routable = n_healthy + sum(
            1 for s in states.values() if s in ("degraded", "half_open")
        )
        if n_healthy:
            status = "ok"
        elif n_routable:
            status = "degraded"
        else:
            status = "unhealthy"
        detail = {
            "status": status,
            "role": "router",
            "uptime_s": round(time.time() - self._started_at, 1),
            "replicas": states,
            "routable": n_routable,
            "retry_budget": round(self.budget.balance, 2),
        }
        return status != "unhealthy", detail

    def detail(self) -> Dict:
        now = self._now()
        return {
            "site": self.site,
            "pid": self.pid,
            "host": self.host,
            "replicas": [rep.detail(now) for rep in self.replicas],
            "retry_budget": {
                "balance": round(self.budget.balance, 2),
                "ratio": self.budget.ratio,
                "withdrawn": self.budget.withdrawn,
                "denied": self.budget.denied,
            },
            "hedge_after_ms": (
                None if self.hedge_after_s is None
                else self.hedge_after_s * 1e3
            ),
            "quarantine": (
                self.quarantine.detail()
                if self.quarantine is not None else {"after": 0}
            ),
            "migration": {
                "migrate_wait_s": self.migrate_wait_s,
                "registry": self.checkpoints.detail(),
                "migrations": {
                    label: int(c.value)
                    for label, c in self._m_migrations.items()
                },
                "resumes_by_replica": {
                    rep.name: rep.resumes
                    for rep in self.replicas if rep.resumes
                },
            },
        }


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    timeout = 120

    def log_message(self, fmt, *args):
        if self.server.owner.verbose:
            super().log_message(fmt, *args)

    def _reply(self, code: int, payload, extra_headers=()) -> None:
        body = (
            payload if isinstance(payload, (bytes, bytearray))
            else json.dumps(payload, default=str).encode("utf-8")
        )
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code >= 400:
            self.send_header("Connection", "close")
            self.close_connection = True
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def do_GET(self):
        router = self.server.owner.router
        path, _, _query = self.path.partition("?")
        if path == "/healthz":
            healthy, detail = router.health()
            self._reply(200 if healthy else 503, detail)
        elif path == "/metrics":
            text = router.registry.render().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            try:
                self.wfile.write(text)
            except (BrokenPipeError, ConnectionResetError):
                pass
        elif path == "/debug/replicas":
            self._reply(200, router.detail())
        elif path == "/fleet/metrics":
            fleet = self.server.owner.fleet
            if fleet is None:
                self._reply(404, {
                    "error": "fleet metrics disabled (--no_fleet_metrics)"
                })
                return
            text = fleet.federated_render().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            try:
                self.wfile.write(text)
            except (BrokenPipeError, ConnectionResetError):
                pass
        elif path == "/debug/fleet":
            fleet = self.server.owner.fleet
            if fleet is None:
                self._reply(404, {
                    "error": "fleet metrics disabled (--no_fleet_metrics)"
                })
                return
            self._reply(200, fleet.fleet_detail())
        elif path == "/debug/usage":
            self._reply(200, router.usage.summary())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        router = self.server.owner.router
        path, _, query = self.path.partition("?")
        if path == "/admin/spool":
            # supervisor spool hand-off: {"replica": name?,
            # "checkpoints": {key: wire}} — malformed entries are
            # silently skipped (parse_request_key), the count returns
            try:
                length = int(self.headers.get("Content-Length", "0"))
                if not 0 < length <= MAX_BODY_BYTES:
                    raise ValueError(f"bad Content-Length {length}")
                obj = json.loads(self.rfile.read(length))
                assert isinstance(obj, dict), "body must be a JSON object"
                cps = obj.get("checkpoints")
                assert isinstance(cps, dict), "checkpoints must be a dict"
            except Exception as exc:
                self._reply(400, {"error": f"bad request: {exc}"})
                return
            n = router.ingest_spool(obj.get("replica"), cps)
            self._reply(200, {"ingested": n})
            return
        if path in ("/admin/drain", "/admin/undrain"):
            params = parse_qs(query)
            name = params.get("replica", [None])[0]
            if not name:
                self._reply(400, {"error": "missing ?replica=NAME"})
                return
            propagate = params.get("propagate", ["0"])[0] in ("1", "true")
            if path == "/admin/drain":
                try:
                    wait_s = float(params.get("wait_s", ["0"])[0])
                except (TypeError, ValueError):
                    self._reply(400, {"error": "wait_s must be a number"})
                    return
                migrate = params.get("migrate", ["0"])[0] in ("1", "true")
                detail = router.drain(
                    name, wait_s=wait_s, propagate=propagate,
                    migrate=migrate,
                )
            else:
                detail = router.undrain(name, propagate=propagate)
            if detail is None:
                self._reply(404, {"error": f"unknown replica {name!r}"})
                return
            self._reply(200, detail)
            return
        if path != "/generate":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if not 0 < length <= MAX_BODY_BYTES:
                raise ValueError(f"bad Content-Length {length}")
        except ValueError as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return
        raw = self.rfile.read(length)
        stream_req = False
        try:
            obj = json.loads(raw)
            stream_req = isinstance(obj, dict) and bool(obj.get("stream"))
        except Exception:
            pass  # malformed body: handle_generate's 400 covers it
        if stream_req:
            # streaming splice: the router owns the socket for the whole
            # stream; the SSE response head goes out lazily on the first
            # forwarded frame so pre-stream failures stay JSON replies
            started = {"v": False}

            def write(data: bytes) -> None:
                if not started["v"]:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.close_connection = True
                    self.end_headers()
                    started["v"] = True
                self.wfile.write(data)
                self.wfile.flush()

            try:
                out = router.handle_generate_stream(
                    raw, self.headers, write
                )
            except (BrokenPipeError, ConnectionResetError):
                return  # client went away; upstream was already severed
            except Exception as exc:
                if started["v"]:
                    return  # a live event stream can't become a 500
                self._reply(500, {"error": f"router failure: {exc}"})
                return
            if out is not None:
                status, body, extra = out
                self._reply(status, body, extra)
            return
        try:
            status, body, extra = router.handle_generate(raw, self.headers)
        except Exception as exc:  # router bug: an orderly 500 beats a
            self._reply(500, {  # silently dropped connection
                "error": f"router failure: {exc}"
            })
            return
        self._reply(status, body, extra)


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, owner: "RouterServer"):
        self.owner = owner
        super().__init__(addr, _RouterHandler)


class RouterServer:
    """HTTP front for a `FleetRouter` with the same lifecycle surface as
    `ServingServer`: `start()` serves on a background thread (port 0
    picks a free one), `shutdown()` stops the probe loop, the listener,
    and the trace exporter."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 8100, verbose: bool = False,
                 probes: bool = True, fleet: Optional[object] = None):
        self.router = router
        self.verbose = verbose
        #: optional FleetScraper (obs/fleetmetrics.py) behind
        #: GET /fleet/metrics and /debug/fleet — owned here so its
        #: thread lifecycle matches the probe loop's, never the
        #: dispatch path's
        self.fleet = fleet
        self._httpd = _HTTPServer((host, port), self)
        self._thread: Optional[threading.Thread] = None
        self._serving = False
        self._closed = False
        if probes:
            router.start_probes()
        if fleet is not None:
            fleet.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "RouterServer":
        assert self._thread is None, "already started"
        self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="dalle-router-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        if self._closed:
            return
        self._serving = True
        self._httpd.serve_forever(poll_interval=0.05)

    def shutdown(self) -> None:
        self.router.stop_probes()
        if self.fleet is not None:
            self.fleet.stop()
        first_close = not self._closed
        self._closed = True
        if self._serving:
            self._httpd.shutdown()
            self._serving = False
        if first_close:
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.router.exporter is not None and first_close:
            self.router.exporter.stop()
        if first_close and self.router.log is not None:
            self.router.log.event("router_shutdown")


def add_router_args(p: argparse.ArgumentParser,
                    require_replicas: bool = True) -> None:
    """Router-specific flags, shared by `python -m ...serving.router`
    and `serve.py --router` (which validates --replicas itself, since
    the flag only applies when --router is set)."""
    p.add_argument("--replicas", type=str, required=require_replicas,
                   default=None, metavar="URLS",
                   help="comma-separated replica base URLs, optionally "
                   "named: 'http://h1:8000,west=http://h2:8000'")
    p.add_argument("--attempt_timeout_s", type=float, default=30.0,
                   help="per-dispatch socket timeout; a slower replica "
                   "attempt is failed over (the client's own timeout_s "
                   "still bounds the whole request)")
    p.add_argument("--hedge_after_ms", type=float, default=None,
                   help="duplicate a dispatch to the next replica when "
                   "the primary has not answered within this threshold "
                   "(first usable answer wins; drawn from the retry "
                   "budget; default: no hedging)")
    p.add_argument("--probe_interval_s", type=float, default=1.0,
                   help="seconds between /healthz probes per replica")
    p.add_argument("--eject_after", type=int, default=3,
                   help="consecutive probe failures that eject a replica")
    p.add_argument("--error_rate_threshold", type=float, default=0.5,
                   help="rolling dispatch error rate that opens the "
                   "circuit (with at least --error_min_samples)")
    p.add_argument("--error_min_samples", type=int, default=4,
                   help="dispatch outcomes required before the error-"
                   "rate breaker may open")
    p.add_argument("--retry_budget_ratio", type=float, default=0.2,
                   help="retry-budget tokens added per successful "
                   "dispatch (the sustained retry fraction)")
    p.add_argument("--retry_budget_initial", type=float, default=10.0,
                   help="retry-budget tokens at startup (cold-start "
                   "failover headroom)")
    p.add_argument("--quarantine_after", type=int, default=3,
                   help="consecutive replica-crash incidents a request "
                   "may be implicated in before it is quarantined "
                   "(terminal 422 with incident ids; a success clears "
                   "the streak; 0 disables the quarantine)")
    p.add_argument("--migrate_wait_s", type=float, default=0.0,
                   help="seconds a transport-failed request may park "
                   "waiting for the crashed replica's checkpoint spool "
                   "to arrive (supervisor hand-off) before failing over "
                   "from scratch; 0 = never park (spooled resumes still "
                   "apply when the hand-off already landed)")
    p.add_argument("--fleet_scrape_interval_s", type=float, default=2.0,
                   help="seconds between fleet telemetry sweeps "
                   "(/metrics + /debug/vitals + /healthz per replica) "
                   "feeding GET /fleet/metrics and /debug/fleet")
    p.add_argument("--no_fleet_metrics", action="store_true",
                   help="disable the fleet telemetry scraper "
                   "(/fleet/metrics and /debug/fleet answer 404; "
                   "per-tenant /debug/usage still works from the "
                   "router's own accounting)")


def router_from_args(args, registry=None, log=None) -> FleetRouter:
    """Build a `FleetRouter` from parsed CLI args (shared by both CLIs).
    Tracing/export flags follow serve.py's."""
    exporter = None
    if getattr(args, "trace_export", None):
        from dalle_pytorch_tpu.obs.aggregate import TraceExporter

        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        exporter = TraceExporter(
            args.trace_export, site=getattr(args, "trace_site", None),
            registry=registry,
        )
    return FleetRouter(
        [r for r in args.replicas.split(",") if r],
        registry=registry,
        tracer=Tracer(
            enabled=not getattr(args, "no_tracing", False),
            max_traces=getattr(args, "trace_ring", 256),
        ),
        log=log,
        exporter=exporter,
        site=getattr(args, "trace_site", None),
        request_timeout_s=getattr(args, "request_timeout_s", 120.0),
        attempt_timeout_s=args.attempt_timeout_s,
        hedge_after_ms=args.hedge_after_ms,
        probe_interval_s=args.probe_interval_s,
        eject_after_probe_failures=args.eject_after,
        error_rate_threshold=args.error_rate_threshold,
        error_min_samples=args.error_min_samples,
        retry_budget_ratio=args.retry_budget_ratio,
        retry_budget_initial=args.retry_budget_initial,
        quarantine_after=getattr(args, "quarantine_after", 3),
        migrate_wait_s=getattr(args, "migrate_wait_s", 0.0),
    )


def fleet_scraper_from_args(args, router: FleetRouter, log=None):
    """Build the fleet telemetry scraper for a router CLI boot (None
    when --no_fleet_metrics): scrapes the SAME replica set the router
    routes to, shares its registry (so /metrics carries the
    dalle_fleet_* gauges) and its usage ledger."""
    if getattr(args, "no_fleet_metrics", False):
        return None
    from dalle_pytorch_tpu.obs.fleetmetrics import FleetScraper

    return FleetScraper(
        [(rep.name, rep.url) for rep in router.replicas],
        registry=router.registry,
        usage=router.usage,
        interval_s=getattr(args, "fleet_scrape_interval_s", 2.0),
        log=log,
    )


def run_router_server(args, log=None) -> int:
    """The shared CLI run loop: build the router from parsed args, serve
    in the foreground with double-signal handling. Both entrypoints
    (`python -m ...serving.router` and `serve.py --router`) call this so
    their lifecycle behavior cannot drift."""
    import signal

    router = router_from_args(args, log=log)
    server = RouterServer(
        router, host=args.host, port=args.port,
        verbose=getattr(args, "verbose", False),
        fleet=fleet_scraper_from_args(args, router, log=log),
    )

    stopping = threading.Event()

    def _stop(signum, frame):
        if stopping.is_set():  # second signal: shutdown is wedged
            print("[router] second signal: exiting immediately", flush=True)
            os._exit(1)
        stopping.set()
        print(f"[router] signal {signum}: shutting down", flush=True)
        # shutdown joins the serve loop; run it off the main thread,
        # which is blocked inside serve_forever
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)

    # parseable readiness line: tests and orchestrators wait for it
    print(f"[router] listening on http://{args.host}:{server.port} "
          f"(replicas={[r.name for r in router.replicas]})", flush=True)
    server.serve_forever()
    print("[router] shutdown complete", flush=True)
    return 0


def main(argv=None) -> int:
    from dalle_pytorch_tpu.obs.logging import StructuredLog

    p = argparse.ArgumentParser(description=__doc__)
    add_router_args(p)
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100,
                   help="0 picks a free port")
    p.add_argument("--request_timeout_s", type=float, default=120.0)
    p.add_argument("--trace_export", type=str, default=None, metavar="URL")
    p.add_argument("--trace_site", type=str, default=None, metavar="NAME")
    p.add_argument("--trace_ring", type=int, default=256)
    p.add_argument("--no_tracing", action="store_true")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)

    log = StructuredLog(component="dalle.router", site=args.trace_site)
    return run_router_server(args, log=log)


if __name__ == "__main__":
    import sys

    sys.exit(main())
