"""Host-side block-paged KV allocation + content-hash prefix caching.

The slotted continuous engine pins `max_batch * (total_seq_len + 1)` cache
positions in HBM whether or not a row holds tokens: worst-case padding —
not actual tokens held — bounds concurrency (ROADMAP item 2). This module
is the host half of the fix (the device half is the paged model ops in
`models/dalle.py` + the paged attention paths in `models/attention.py` /
`ops/pallas_decode.py`):

  * `BlockPool` — refcounted physical-page allocator. Page 0 is RESERVED
    as the garbage page: released rows' page-table entries point at it, so
    a stale in-flight write (inactive rows compute along as padding in the
    fixed-shape chunk program) can never corrupt a page that has been
    reallocated to another row.
  * `PrefixCache` — content-hash cache of immutable text-prefill pages.
    Chain hashes (hash of the token prefix through each FULL block) give
    longest-cached-prefix lookup: matched blocks are MAPPED into a new
    row's page table (refcount++, HBM deduplication) instead of allocated;
    a FULL-prompt hit additionally carries a sidecar (pending logits +
    token-shift rings) that lets admission skip the transformer prefill
    entirely (`models/dalle.py:admit_cached_prefix`). The divergence block
    (a text prefix rarely ends exactly on a page boundary) is
    copy-on-write: the cache keeps an immutable snapshot page, each hit
    gets a private copy to decode into. Eviction is LRU over entries whose
    pages the refcounts then settle: pages shared with live rows stay
    resident until those rows release.
  * `PagedKVManager` — per-row page tables + reservation accounting over
    the pool. Admission RESERVES a row's worst-case remaining pages
    (`pages_per_row - shared prefix blocks`) so lazy per-chunk allocation
    (`ensure`) can never deadlock mid-decode; `can_admit` counts
    cache-only pages as reclaimable (eviction on demand), so a full cache
    never blocks admission it could make room for.

Everything here is plain numpy/host state mutated only by the batcher's
single worker thread (same threading contract as `SlotAllocator`); the
device sees page tables only as traced `[max_batch, pages_per_row]` int32
arguments, so no allocation decision ever triggers a recompile.
"""

from __future__ import annotations

import base64
import hashlib
import heapq
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

#: physical page 0 is never allocated; released/unmapped table entries
#: point here so stale fixed-shape writes land harmlessly
GARBAGE_PAGE = 0


class BlockPool:
    """Refcounted allocator over `n_pages` physical pages (page 0 reserved).

    `alloc` hands out the lowest free page (deterministic, test-friendly —
    same convention as `SlotAllocator`); `share` adds a reference to a
    live page (prefix blocks mapped into another row / retained by the
    cache); `release` drops one reference and returns the page to the free
    list at zero. Exhaustion returns None — callers decide whether to
    evict (prefix cache) or keep the request queued (admission).
    """

    def __init__(self, n_pages: int):
        assert n_pages >= 2, "pool needs the garbage page plus >= 1 usable"
        self.n_pages = int(n_pages)
        # min-heap: ascending range is already heap-ordered
        self._free = list(range(1, self.n_pages))
        self._ref = np.zeros(self.n_pages, np.int32)
        self.peak_allocated = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_allocated(self) -> int:
        return self.n_pages - 1 - len(self._free)

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def refcounts(self) -> Dict[int, int]:
        """{page: refcount} of every live page — the `/debug/state` view
        of who is pinning HBM (rows vs prefix-cache references)."""
        (live,) = np.nonzero(self._ref)
        return {int(p): int(self._ref[p]) for p in live}

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        page = heapq.heappop(self._free)
        self._ref[page] = 1
        self.peak_allocated = max(self.peak_allocated, self.n_allocated)
        return page

    def share(self, page: int) -> None:
        assert page != GARBAGE_PAGE and self._ref[page] >= 1, (
            f"page {page} is not live (ref {self._ref[page]})"
        )
        self._ref[page] += 1

    def release(self, page: int) -> None:
        assert page != GARBAGE_PAGE and self._ref[page] >= 1, (
            f"page {page} double-freed or never allocated"
        )
        self._ref[page] -= 1
        if self._ref[page] == 0:
            heapq.heappush(self._free, page)


def chain_hashes(text_ids: np.ndarray, page_size: int, n_blocks: int) -> List[str]:
    """Per-FULL-block chain hashes of a tokenized prompt.

    Block j of the prefill covers sequence positions [j*ps, (j+1)*ps);
    position 0 is the constant <bos>, so block j's K/V is a function of
    text ids [: (j+1)*ps - 1] exactly (causal attention, fixed rotary
    positions). Hash j therefore digests ids through that boundary —
    incremental, so the whole chain costs one pass over the prompt.
    """
    ids = np.ascontiguousarray(np.asarray(text_ids, np.int32))
    h = hashlib.sha1()
    out = []
    for j in range(n_blocks):
        lo = 0 if j == 0 else j * page_size - 1
        h.update(ids[lo : (j + 1) * page_size - 1].tobytes())
        out.append(h.hexdigest())
    return out


class _PrefixEntry:
    __slots__ = ("key", "chain", "full_pages", "partial_page", "sidecar")

    def __init__(self, key, chain, full_pages, partial_page, sidecar):
        self.key = key
        self.chain = chain  # chain hashes of the full blocks
        self.full_pages = full_pages  # immutable, shareable
        self.partial_page = partial_page  # CoW snapshot (None on boundary)
        self.sidecar = sidecar  # device tree: pending logits + shift rings


class PrefixCache:
    """Content-hash prefix cache over pool pages; LRU eviction."""

    def __init__(
        self,
        pool: BlockPool,
        page_size: int,
        n_full_blocks: int,
        has_partial: bool,
        max_entries: int = 64,
        on_evict: Optional[Callable[[], None]] = None,
    ):
        self.pool = pool
        self.page_size = int(page_size)
        self.n_full_blocks = int(n_full_blocks)
        self.has_partial = bool(has_partial)
        self.max_entries = int(max_entries)
        self.on_evict = on_evict
        self._entries: "OrderedDict[bytes, _PrefixEntry]" = OrderedDict()
        #: chain hash -> [page, n_entries referencing it]
        self._blocks: Dict[str, List[int]] = {}
        #: entry keys pinned against eviction for one admission wave: the
        #: batcher budgets a hit at `pages_per_row - saved` BEFORE the
        #: wave runs, so evicting the entry mid-wave (another row's
        #: allocation cascade) would demote the hit to a full prefill that
        #: consumes `saved` more pages than were charged — breaking the
        #: reservation invariant `_alloc_evicting` asserts on
        self._protected: set = set()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def enabled(self) -> bool:
        return self.max_entries > 0

    def _key(self, text_ids) -> bytes:
        return np.ascontiguousarray(np.asarray(text_ids, np.int32)).tobytes()

    def lookup_full(self, text_ids) -> Optional[_PrefixEntry]:
        """Whole-prompt hit (the zero-prefill-dispatch admission path);
        bumps LRU recency. Does NOT count hit/miss — the engine tallies
        per admission, not per probe."""
        entry = self._entries.get(self._key(text_ids))
        if entry is not None:
            self._entries.move_to_end(self._key(text_ids))
        return entry

    def peek_full(self, text_ids) -> Optional[_PrefixEntry]:
        """`lookup_full` without the LRU bump — for capacity probes.
        `can_admit` runs on every worker wake; a queued-but-unadmittable
        prompt must not pin its entry against eviction by being asked
        about."""
        return self._entries.get(self._key(text_ids))

    def bloom_digest(self, bits: int = 256, hashes: int = 2) -> Dict:
        """Compact Bloom filter over the cached prompt keys, advertised
        on /healthz for the fleet scraper: a router-side placer can test
        "has replica R plausibly seen this prompt?" without shipping the
        key set (the first observable slice of prefix-affine routing).
        False positives shrink with `bits`; never false negatives for
        the snapshot it was built from.

        Reads from probe threads race the worker's inserts/evictions —
        the snapshot is retried a few times and degrades to an empty
        digest rather than raising into the health path."""
        keys: List[bytes] = []
        for _ in range(3):
            try:
                keys = list(self._entries)
                break
            except RuntimeError:  # resized mid-iteration; retry
                continue
        bitmap = bytearray(max(8, bits) // 8)
        nbits = len(bitmap) * 8
        for key in keys:
            digest = hashlib.blake2b(key, digest_size=4 * hashes).digest()
            for i in range(hashes):
                idx = int.from_bytes(
                    digest[4 * i:4 * (i + 1)], "little"
                ) % nbits
                bitmap[idx // 8] |= 1 << (idx % 8)
        return {
            "bits": nbits,
            "hashes": int(hashes),
            "entries": len(keys),
            "b64": base64.b64encode(bytes(bitmap)).decode("ascii"),
        }

    def block_page(self, h: str) -> Optional[int]:
        """Page registered for one chain hash, None when unknown."""
        hit = self._blocks.get(h)
        return hit[0] if hit is not None else None

    def shared_prefix_pages(self, text_ids) -> List[int]:
        """Pages of the longest cached chain of FULL blocks matching this
        prompt's prefix (possibly spliced from multiple entries — chain
        hashes deduplicate identical blocks across prompts)."""
        pages = []
        for h in chain_hashes(text_ids, self.page_size, self.n_full_blocks):
            page = self.block_page(h)
            if page is None:
                break
            pages.append(page)
        return pages

    def cache_only_pages(self) -> int:
        """Pages that would return to the pool if every entry were evicted
        right now (refcount 1 = the cache's own reference): the
        reclaimable headroom `can_admit` may count on."""
        n = 0
        for entry in self._entries.values():
            for page in entry.full_pages:
                if self.pool.refcount(page) == 1:
                    n += 1
            if entry.partial_page is not None and (
                self.pool.refcount(entry.partial_page) == 1
            ):
                n += 1
        return n

    def register(
        self,
        text_ids,
        full_pages: Sequence[int],
        partial_page: Optional[int],
        sidecar,
    ) -> None:
        """Adopt a freshly-prefilled prompt. The caller has already given
        the cache its references (pool.share on each full page; the
        partial snapshot page was allocated cache-owned). Evicts LRU
        entries past `max_entries`."""
        key = self._key(text_ids)
        assert key not in self._entries, "prompt already registered"
        chain = chain_hashes(text_ids, self.page_size, self.n_full_blocks)
        assert len(full_pages) == self.n_full_blocks
        for h, page in zip(chain, full_pages):
            ref = self._blocks.get(h)
            if ref is None:
                self._blocks[h] = [int(page), 1]
            else:
                assert ref[0] == int(page), (
                    "chain hash maps two different pages — caller must map "
                    "the cached page for matched prefix blocks"
                )
                ref[1] += 1
        self._entries[key] = _PrefixEntry(
            key, chain, [int(p) for p in full_pages], partial_page, sidecar
        )
        while len(self._entries) > self.max_entries:
            if not self.evict_lru():
                break  # everything protected: trim on the next wave

    def protect(self, keys) -> set:
        """Pin entries against eviction for the duration of one admission
        wave (the caller unprotects in a finally). Protected entries keep
        their LRU position; eviction simply skips them. Returns only the
        NEWLY protected keys so nested guards (the batcher pins a whole
        multi-split wave, `prefill_slots` pins its own split) unprotect
        exactly what they added — a plain set would let the inner finally
        strip the outer guard's pins."""
        added = set(keys) - self._protected
        self._protected.update(added)
        return added

    def unprotect(self, keys) -> None:
        self._protected.difference_update(keys)

    def evict_lru(self) -> bool:
        """Drop the least-recently-used unprotected entry; returns False
        when none is evictable. Pages shared with live rows stay allocated
        (refcount) — only the cache's own references are released."""
        victim = next(
            (k for k in self._entries if k not in self._protected), None
        )
        if victim is None:
            return False
        entry = self._entries.pop(victim)
        for h, page in zip(entry.chain, entry.full_pages):
            ref = self._blocks[h]
            ref[1] -= 1
            if ref[1] == 0:
                del self._blocks[h]
            self.pool.release(page)
        if entry.partial_page is not None:
            self.pool.release(entry.partial_page)
        self.evictions += 1
        if self.on_evict is not None:
            self.on_evict()
        return True

    def clear(self) -> None:
        self._protected.clear()
        while self.evict_lru():
            pass
        self.evictions = 0
        self.hits = 0
        self.misses = 0


class PagedKVManager:
    """Page tables + reservation accounting for the paged engine.

    One logical row per engine slot; `table` is the [n_rows,
    pages_per_row] int32 array every paged dispatch takes as traced data.
    Rows hold one pool reference per mapped page (shared prefix blocks
    included), released wholesale at `release(slot)`.
    """

    def __init__(
        self,
        n_rows: int,
        page_size: int,
        max_positions: int,
        text_positions: int,
        n_pages: int,
        max_entries: int = 64,
        on_evict: Optional[Callable[[], None]] = None,
    ):
        self.page_size = int(page_size)
        self.max_positions = int(max_positions)  # total_seq_len + 1
        self.pages_per_row = -(-self.max_positions // self.page_size)
        self.text_positions = int(text_positions)  # text_seq_len + 1 (bos)
        self.n_text_pages = -(-self.text_positions // self.page_size)
        self.has_partial = self.text_positions % self.page_size != 0
        self.n_full_blocks = (
            self.n_text_pages - 1 if self.has_partial else self.n_text_pages
        )
        self.pool = BlockPool(n_pages)
        self.cache = PrefixCache(
            self.pool, self.page_size, self.n_full_blocks, self.has_partial,
            max_entries=max_entries, on_evict=on_evict,
        )
        self.n_rows = int(n_rows)
        self.table = np.zeros((self.n_rows, self.pages_per_row), np.int32)
        self._row_pages: List[List[int]] = [[] for _ in range(self.n_rows)]
        self._mapped = np.zeros(self.n_rows, np.int64)  # blocks mapped
        self._debt = np.zeros(self.n_rows, np.int64)  # pages still owed

    # --------------------------------------------------------- allocation

    def _alloc_evicting(self) -> int:
        """Allocate one page, evicting LRU prefix entries as needed. The
        reservation invariant guarantees success for reserved debt."""
        page = self.pool.alloc()
        while page is None:
            assert self.cache.evict_lru(), (
                "page pool exhausted with nothing evictable — reservation "
                "accounting is broken (admission must not have happened)"
            )
            page = self.pool.alloc()
        return page

    def _map(self, slot: int, block: int, page: int) -> None:
        """Record page in the row's table; the row's reference was already
        taken (alloc) or must be (share) by the caller."""
        self.table[slot, block] = page
        self._row_pages[slot].append(page)
        self._mapped[slot] = max(self._mapped[slot], block + 1)

    # ---------------------------------------------------------- admission

    def row_demand(self, text_ids) -> int:
        """Worst-case headroom this prompt consumes over its whole life.

        Only a FULL-entry hit reduces demand, and only by blocks some
        LIVE row already pins (pool refcount >= 2): mapping a cache-only
        page removes it from the reclaimable set `can_admit` counts on,
        which costs the same headroom an allocation would — and a
        partial-prefix match is charged the full worst case because the
        chain mappings it would splice can be deleted by another row's
        eviction cascade between budgeting and `admit_miss` (a full hit's
        entry is wave-protected against exactly that, a loose chain block
        is not). Under-counting either is how a reservation scheme
        deadlocks mid-decode."""
        if not self.cache.enabled:
            return self.pages_per_row
        entry = self.cache.peek_full(text_ids)
        if entry is None:
            return self.pages_per_row
        saved = sum(
            1 for p in entry.full_pages if self.pool.refcount(p) >= 2
        )
        return self.pages_per_row - saved

    def admission_headroom(self) -> int:
        """Pages available for NEW admissions: free + cache-reclaimable
        minus live rows' already-reserved debt. Fixed for the whole of one
        admission loop — pages move only at prefill/release, both on the
        batcher worker thread — so the batcher snapshots it once per wave
        and sums per-head `row_demand` against it (O(W), not O(W^2))."""
        available = self.pool.n_free + self.cache.cache_only_pages()
        return available - int(self._debt.sum())

    def can_admit(self, texts: Sequence[np.ndarray]) -> bool:
        """Free + cache-reclaimable pages cover the already-reserved debt
        of live rows PLUS this wave's worst case."""
        needed = sum(self.row_demand(ids) for ids in texts)
        return self.admission_headroom() >= needed

    def can_ever_admit(self, n_rows: int) -> bool:
        """Could a request of n_rows unique prompts EVER fit an empty
        pool? Submit-time rejection for requests that would queue
        forever."""
        return n_rows * self.pages_per_row <= self.pool.n_pages - 1

    def admit_miss(
        self, slot: int, text_ids, register: bool, pending_blocks=None
    ):
        """Map/allocate the text-block pages for a prefill row. Returns
        (page_row [n_text_pages], partial_snapshot_page or GARBAGE_PAGE,
        shared_block_count, registration token or None).

        `pending_blocks` is a wave-local {chain hash: page} overlay for
        blocks earlier rows of the SAME admission wave mapped: two
        distinct prompts sharing a leading block must land on ONE page
        (the batched dispatch writes every mapped page, and a page's
        content IS its chain hash no matter which row writes it), or
        their registrations would content-address the same hash to two
        different pages and trip `PrefixCache.register`'s invariant."""
        assert not self._row_pages[slot], f"slot {slot} already mapped"
        chain = (
            chain_hashes(text_ids, self.page_size, self.n_full_blocks)
            if self.cache.enabled
            else []
        )
        shared = []
        for h in chain:
            page = self.cache.block_page(h)
            if page is None and pending_blocks is not None:
                page = pending_blocks.get(h)
            if page is None:
                break
            shared.append(page)
        page_row = []
        for j, page in enumerate(shared):
            self.pool.share(page)  # the row's own reference
            self._map(slot, j, page)
            page_row.append(page)
        for j in range(len(shared), self.n_text_pages):
            page = self._alloc_evicting()
            self._map(slot, j, page)
            page_row.append(page)
        if pending_blocks is not None:
            for h, page in zip(chain, page_row):
                pending_blocks[h] = page
        self._debt[slot] = self.pages_per_row - self.n_text_pages
        partial_dst = GARBAGE_PAGE
        token = None
        register = (
            register
            and self.cache.enabled
            and self.cache.lookup_full(text_ids) is None
        )
        if register:
            # cache references on the full blocks now; the partial
            # snapshot page is cache-owned from birth. Registration pages
            # are reclaimable, so they never threaten the debt invariant —
            # but don't force an eviction just to register.
            partial_page = None
            if self.has_partial:
                partial_page = self.pool.alloc()
                if partial_page is None:
                    register = False
            if register:
                full_pages = page_row[: self.n_full_blocks]
                for page in full_pages:
                    self.pool.share(page)
                partial_dst = (
                    partial_page if partial_page is not None else GARBAGE_PAGE
                )
                token = (text_ids, full_pages, partial_page)
        return page_row, partial_dst, len(shared), token

    def admit_resume(self, slot: int, n_positions: int) -> None:
        """Map FRESH pages covering positions [0, n_positions) for a
        mid-decode resume row (decode-state migration). Deliberately NO
        prefix sharing: the resume dispatch rewrites every page it maps
        with the row's OWN prompt+prefix K/V, and overwriting a page the
        prefix cache (or another row) maps would corrupt their view —
        the resume row pays full pages, which is exactly what
        `admission_demand` charged it. Remaining blocks stay on the
        garbage page until `ensure` maps them ahead of decode, covered
        by the reservation like any other row's debt."""
        assert not self._row_pages[slot], f"slot {slot} already mapped"
        n_blocks = min(
            -(-int(n_positions) // self.page_size), self.pages_per_row
        )
        for j in range(n_blocks):
            page = self._alloc_evicting()
            self._map(slot, j, page)
        self._debt[slot] = self.pages_per_row - n_blocks

    def finish_register(self, token, sidecar) -> None:
        """Complete a registration begun in `admit_miss` once the prefill
        dispatch has produced the sidecar."""
        text_ids, full_pages, partial_page = token
        self.cache.register(text_ids, full_pages, partial_page, sidecar)

    def admit_hit(self, slot: int, entry: _PrefixEntry):
        """Map a full-prompt cache hit: share every full block, allocate
        the private copy-on-write page for the divergence block. Returns
        (partial_src, partial_dst) page ids for `admit_cached_prefix`
        (GARBAGE_PAGE when the prefix ends on a page boundary)."""
        assert not self._row_pages[slot], f"slot {slot} already mapped"
        for j, page in enumerate(entry.full_pages):
            self.pool.share(page)
            self._map(slot, j, page)
        partial_src = partial_dst = GARBAGE_PAGE
        if self.has_partial:
            partial_src = entry.partial_page
            partial_dst = self._alloc_evicting()
            self._map(slot, self.n_full_blocks, partial_dst)
        self._debt[slot] = self.pages_per_row - self.n_text_pages
        return partial_src, partial_dst

    # ------------------------------------------------------- decode/release

    def ensure(self, slot: int, n_blocks: int) -> None:
        """Lazily allocate decode pages so the row's table covers its next
        chunk's writes (reserved at admission — cannot fail)."""
        n_blocks = min(int(n_blocks), self.pages_per_row)
        while self._mapped[slot] < n_blocks:
            page = self._alloc_evicting()
            self._map(slot, int(self._mapped[slot]), page)
            self._debt[slot] -= 1
        assert self._debt[slot] >= 0

    def release(self, slot: int) -> None:
        """Return the row's page references; table entries go back to the
        garbage page so the fixed-shape chunk program's stale writes for
        this slot can never touch live pages."""
        for page in self._row_pages[slot]:
            self.pool.release(page)
        self._row_pages[slot] = []
        self.table[slot, :] = GARBAGE_PAGE
        self._mapped[slot] = 0
        self._debt[slot] = 0

    @property
    def blocks_active(self) -> int:
        return self.pool.n_allocated

    @property
    def blocks_free(self) -> int:
        return self.pool.n_free

    def leak_check(self) -> List[str]:
        """Audit the reservation/refcount invariants; returns violation
        strings (empty = consistent). The fault-injection tests run this
        after mid-wave dispatch failures and preempt/resume churn: a
        leaked page or refcount here is exactly the corruption a failed
        donated dispatch could smuggle past the rebuild path."""
        problems: List[str] = []
        expected: Dict[int, int] = {}
        for slot, pages in enumerate(self._row_pages):
            for p in pages:
                expected[p] = expected.get(p, 0) + 1
            mapped, debt = int(self._mapped[slot]), int(self._debt[slot])
            if pages and mapped + debt != self.pages_per_row:
                problems.append(
                    f"slot {slot}: mapped {mapped} + reserved {debt} != "
                    f"pages_per_row {self.pages_per_row}"
                )
            if not pages and (mapped or debt):
                problems.append(
                    f"slot {slot}: no pages but mapped={mapped} debt={debt}"
                )
            live = [int(p) for p in self.table[slot] if p != GARBAGE_PAGE]
            if sorted(live) != sorted(pages):
                problems.append(
                    f"slot {slot}: table pages {sorted(live)} != row pages "
                    f"{sorted(pages)}"
                )
        for entry in self.cache._entries.values():
            for p in entry.full_pages:
                expected[p] = expected.get(p, 0) + 1
            if entry.partial_page is not None:
                expected[entry.partial_page] = (
                    expected.get(entry.partial_page, 0) + 1
                )
        actual = self.pool.refcounts()
        for p in sorted(set(expected) | set(actual)):
            if expected.get(p, 0) != actual.get(p, 0):
                problems.append(
                    f"page {p}: refcount {actual.get(p, 0)} but "
                    f"{expected.get(p, 0)} references held (rows + cache)"
                )
        free = sorted(self.pool._free)
        should_be_free = sorted(
            p for p in range(1, self.pool.n_pages) if p not in actual
        )
        if free != should_be_free:
            problems.append(
                f"free list {free} != unreferenced pages {should_be_free}"
            )
        return problems

    def debug_dump(self) -> Dict:
        """JSON-ready paging state for `/debug/state` and stall reports:
        per-row page tables + debt, live-page refcounts, prefix-cache
        entries. Plain host reads on the worker-owned structures — a
        point-in-time view, consistent enough for postmortems (the one
        writer is the batcher worker, and a stalled worker isn't
        writing)."""
        rows = []
        for slot in range(self.n_rows):
            pages = self._row_pages[slot]
            if not pages and not self._debt[slot]:
                continue
            rows.append({
                "slot": slot,
                "pages": [int(p) for p in pages],
                "blocks_mapped": int(self._mapped[slot]),
                "pages_reserved": int(self._debt[slot]),
            })
        return {
            "page_size": self.page_size,
            "pages_per_row": self.pages_per_row,
            "blocks_total": self.pool.n_pages - 1,
            "blocks_active": self.blocks_active,
            "blocks_free": self.blocks_free,
            "page_refcounts": self.pool.refcounts(),
            "rows": rows,
            "prefix_cache": {
                "entries": len(self.cache),
                "protected": len(self.cache._protected),
                "hits": self.cache.hits,
                "misses": self.cache.misses,
                "evictions": self.cache.evictions,
            },
        }
