"""Host-side decode-sparsity policy: static attention layouts reduced to
per-row KV-tile bitmaps for the block-sparse flash-decode kernel.

The model's own sparse attention patterns (`axial_row`/`axial_col`/
`conv_like`/`sparse` — ops/masks.py) say which KV positions a decode step
can ever read, but until now they bought nothing at decode time: pattern-
masked rows fell back to dense attention over the whole cache. This module
precomputes, per layer and per image position, the BLOCK-level shadow of
each pattern (tile width = the model's `decode_sparse_block`), and the
engine ships the per-slot rows of that table into every chunk dispatch as
traced data (`models/dalle.py:_with_block_bitmap`). Policy semantics:

  * conservative by construction — a tile any pattern row in the chunk
    window touches is read whole (`ops/masks.py:mask_to_block_bitmap`),
    and the chunk's bitmap is the UNION over its `chunk_tokens` query
    positions (the bitmap is constant across the in-program scan; the
    kernel's per-step causal/length mask trims inside live tiles);
  * the text prefix (<bos> + text tokens) is ALWAYS live — every shipped
    pattern lets image rows read all text, and prefill/quality both
    depend on it;
  * "full" layers get all-ones rows (pure length-skip, i.e. exactly the
    non-sparse flash kernel);
  * inactive slots get all-ones rows: they compute as padding whose
    outputs are discarded, and all-ones keeps their math identical to
    the non-sparse program (bit-parity pins stay checkable row-wise).

Everything here is host numpy; nothing traces or compiles. The ONLY
compile-relevant quantity is the tile width baked into the model clone
(`decode_sparse_block`) — the bitmaps themselves are data, so admission,
retirement, and even swapping the whole policy never trigger a compile
(the Vortex lesson, PAPERS.md: programmable sparsity must be data).
"""

from __future__ import annotations

from itertools import cycle, islice

import numpy as np

from dalle_pytorch_tpu.models.attention import DECODE_SPARSE_BLOCK
from dalle_pytorch_tpu.models.transformer import _build_static_mask
from dalle_pytorch_tpu.ops.masks import mask_to_block_bitmap


class DecodeSparsityPolicy:
    """Per-(layer, image-position) KV-tile liveness tables for one model.

    Parameters mirror what the engine knows at boot: the (already cloned)
    model carrying `decode_sparse_block`, and the chunk size its decode
    programs advance by. `max_batch` only sizes the emitted tables.
    """

    def __init__(self, model, chunk_tokens: int, max_batch: int):
        self.max_batch = int(max_batch)
        self.chunk = max(int(chunk_tokens), 1)
        self.text_len = model.text_seq_len + 1  # <bos> + text prefix
        self.image_seq_len = model.image_seq_len
        self.max_len = model.total_seq_len + 1
        block = (
            DECODE_SPARSE_BLOCK
            if getattr(model, "decode_sparse_block", None) is None
            else model.decode_sparse_block
        )
        # mirror the kernel's block_k clamp (tiny test geometries)
        self.block = max(min(int(block), self.max_len), 1)
        self.n_blocks = -(-self.max_len // self.block)
        self.depth = model.depth

        attn_types = (
            tuple(model.attn_types) if model.attn_types else ("full",)
        )
        type_per_layer = list(islice(cycle(attn_types), self.depth))

        # per-layer [image_seq_len, n_blocks] bool: tile liveness for a
        # chunk STARTING at image position p (union over the window).
        # Layers sharing (attn_type, seed-irrelevant) could share tables,
        # but "sparse" layers seed by layer index, so compute per layer
        # and dedup by attn_type only where that is sound ("full"/axial/
        # conv tables are layer-independent).
        self._windows: list[np.ndarray | None] = []  # None = all-ones
        table_cache: dict[str, np.ndarray] = {}
        for ind, t in enumerate(type_per_layer):
            if t == "full":
                self._windows.append(None)
                continue
            key = t if t != "sparse" else f"sparse_{ind}"
            if key not in table_cache:
                mask = np.asarray(
                    _build_static_mask(
                        t, model.total_seq_len, model.image_fmap_size, ind
                    )
                )
                # size to the cache geometry exactly like the dense
                # path's mask_rows_at: True-pad up to max_len, then crop
                if mask.shape[0] < self.max_len:
                    pad = self.max_len - mask.shape[0]
                    mask = np.pad(mask, ((0, pad), (0, pad)),
                                  constant_values=True)
                mask = mask[: self.max_len, : self.max_len]
                rows = mask_to_block_bitmap(
                    mask, self.block, n_blocks=self.n_blocks,
                    always_live=self.text_len,
                )
                # union over each chunk window [p, p + chunk)
                img_rows = rows[self.text_len :][: self.image_seq_len]
                win = np.zeros(
                    (self.image_seq_len, self.n_blocks), dtype=bool
                )
                for off in range(self.chunk):
                    hi = self.image_seq_len - off
                    if hi <= 0:
                        break
                    # win[p] |= rows[p + off]; positions whose window runs
                    # past the last image row simply union fewer rows
                    win[:hi] |= img_rows[off : off + hi]
                table_cache[key] = win
            self._windows.append(table_cache[key])

    # ------------------------------------------------------------ tables

    def chunk_bitmaps(self, img_pos, active) -> np.ndarray:
        """[depth, max_batch, n_blocks] int32 for one chunk dispatch.

        `img_pos`/`active` are the engine's host mirrors of each slot's
        image position and liveness. Inactive slots (and "full" layers)
        get all-ones rows — identical math to the non-sparse program."""
        pos = np.clip(
            np.asarray(img_pos, np.int64)[: self.max_batch],
            0, self.image_seq_len - 1,
        )
        act = np.asarray(active, bool)[: self.max_batch]
        out = np.ones(
            (self.depth, self.max_batch, self.n_blocks), dtype=np.int32
        )
        for li, win in enumerate(self._windows):
            if win is None:
                continue
            rows = win[pos]  # [B, n_blocks] bool
            out[li, : len(pos)] = np.where(act[:, None], rows, True)
        return out

    def prefill_bitmaps(self, prefill_batch: int) -> np.ndarray:
        """[depth, R, n_blocks] all-ones: text rows under every shipped
        pattern read (at most) the causal text prefix, and tiles above the
        prefill length are dead via the kernel's length AND — so all-ones
        is exact, and keeps prefill numerics identical to the non-sparse
        flash path."""
        return np.ones(
            (self.depth, int(prefill_batch), self.n_blocks), dtype=np.int32
        )

    # -------------------------------------------------------- accounting

    def count_tiles(self, img_pos, active) -> tuple[int, int]:
        """(read, skipped) KV tiles for one chunk dispatch, summed over
        active rows and layers (per head the counts are identical, so
        heads are left out of the unit). `skipped` counts only tiles the
        LENGTH skip would have read — i.e. the policy's own savings on
        top of PR 4's length skip — which is the number the bench and the
        fleet counters compare against dense-causal flash."""
        pos = np.clip(
            np.asarray(img_pos, np.int64)[: self.max_batch],
            0, self.image_seq_len - 1,
        )
        act = np.asarray(active, bool)[: self.max_batch]
        if not act.any():
            return 0, 0
        lengths = np.minimum(
            pos[act] + self.text_len + self.chunk, self.max_len
        )
        llb = np.maximum(lengths - 1, 0) // self.block  # last live tile
        in_range = (
            np.arange(self.n_blocks)[None, :] <= llb[:, None]
        )  # [A, nb]
        read = skipped = 0
        for win in self._windows:
            if win is None:
                read += int(in_range.sum())
                continue
            live = win[pos[act]] & in_range
            read += int(live.sum())
            skipped += int((in_range & ~live).sum())
        return read, skipped

    def detail(self) -> dict:
        """Static policy summary for /healthz."""
        dead_frac = 0.0
        patterned = [w for w in self._windows if w is not None]
        if patterned:
            dead_frac = float(
                np.mean([1.0 - w.mean() for w in patterned])
            )
        return {
            "block": self.block,
            "n_blocks": self.n_blocks,
            "patterned_layers": len(patterned),
            "depth": self.depth,
            "static_dead_tile_frac": round(dead_frac, 4),
        }
