"""Stdlib-only JSON HTTP front end for the generation engine.

Endpoints:
  POST /generate   {"prompt": str, "num_images": int=1, "seed": int?,
                    "temperature": float=1.0, "top_k": float=0.9,
                    "rerank": bool=false, "timeout_s": float?}
                -> {"tokens": [[int]], "shape": [n, H, W, 3]?,
                    "images_png_b64": [str]?, "clip_scores": [float]?,
                    "latency_ms": float}
  GET  /healthz -> {"status": "ok", ...} (503 once draining or after an
                   engine failure — fail fast, don't wedge clients)
  GET  /metrics -> Prometheus text exposition from the shared registry
                   (`training/metrics.py:MetricsRegistry`): queue depth,
                   batch-occupancy histogram, request latency p50/p95,
                   compile-cache hits, images/requests/batches totals.

`ThreadingHTTPServer` gives one thread per in-flight request; they all
funnel into the `MicroBatcher`, which is where concurrent requests
coalesce into one padded sampler batch. Backpressure maps to HTTP:
queue full -> 503 + Retry-After, per-request timeout -> 504 (the queued
request is cancelled so it never costs a batch row), engine error ->
500. Client disconnects are NOT detected mid-wait (stdlib handler
limitation); an abandoned request still completes and is discarded.
"""

from __future__ import annotations

import base64
import io
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from dalle_pytorch_tpu.serving.batcher import (
    ContinuousBatcher,
    MicroBatcher,
    QueueFullError,
    RequestTimeout,
    ShuttingDownError,
)
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    GenerationEngine,
    SampleSpec,
)

MAX_BODY_BYTES = 1 << 20  # prompts are tiny; reject anything bigger


def _png_b64(img: np.ndarray) -> str:
    from PIL import Image

    from dalle_pytorch_tpu.utils.images import to_uint8

    buf = io.BytesIO()
    Image.fromarray(to_uint8(img)).save(buf, format="PNG")
    return base64.b64encode(buf.getvalue()).decode("ascii")


class _Handler(BaseHTTPRequestHandler):
    # the served object is reachable as self.server (ServingHTTPServer)
    protocol_version = "HTTP/1.1"
    # per-connection socket timeout: bounds idle keep-alive connections and
    # slow/partial request bodies (slowloris) so they can't pin handler
    # threads forever — ThreadingHTTPServer spawns one thread per connection
    timeout = 120

    def log_message(self, fmt, *args):  # route access logs through the owner
        if self.server.owner.verbose:
            super().log_message(fmt, *args)

    # ------------------------------------------------------------ helpers

    def _reply(self, code: int, payload: dict, extra_headers=()) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code >= 400:
            # error paths may not have drained the request body; under
            # HTTP/1.1 keep-alive the leftover bytes would be parsed as the
            # next request line, so close instead of corrupting the stream
            self.send_header("Connection", "close")
            self.close_connection = True
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -------------------------------------------------------------- GETs

    def do_GET(self):
        owner = self.server.owner
        if self.path == "/healthz":
            healthy, detail = owner.health()
            self._reply(200 if healthy else 503, detail)
        elif self.path == "/metrics":
            text = owner.registry.render().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            try:
                self.wfile.write(text)
            except (BrokenPipeError, ConnectionResetError):
                pass  # scraper gave up mid-scrape; not traceback-worthy
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    # -------------------------------------------------------------- POSTs

    def do_POST(self):
        owner = self.server.owner
        if self.path != "/generate":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if not 0 < length <= MAX_BODY_BYTES:
                raise ValueError(f"bad Content-Length {length}")
            body = json.loads(self.rfile.read(length))
            prompt = body["prompt"]
            assert isinstance(prompt, str) and prompt.strip(), "empty prompt"
            num_images = int(body.get("num_images", 1))
            assert 1 <= num_images <= owner.engine.max_batch, (
                f"num_images must be in [1, {owner.engine.max_batch}]"
            )
            temperature = float(body.get("temperature", 1.0))
            # NaN fails every comparison, so this also rejects it (Python's
            # json parser accepts the bare NaN literal)
            assert 0.0 <= temperature <= 100.0, (
                "temperature must be a finite value in [0, 100]"
            )
            top_k = float(body.get("top_k", 0.9))
            assert 0.0 <= top_k <= 1.0, "top_k is a fraction in [0, 1]"
            seed = body.get("seed")
            if seed is not None:
                assert not isinstance(seed, (list, dict, bool)), "seed must be an int"
                seed = int(seed)
            timeout_s = float(body.get("timeout_s", owner.request_timeout_s))
            # NaN fails the comparison; cap at the server's own policy so a
            # client can't pin handler threads/queue rows past it
            assert 0.0 < timeout_s <= owner.request_timeout_s, (
                f"timeout_s must be in (0, {owner.request_timeout_s}]"
            )
            do_rerank = bool(body.get("rerank", False))
            assert not do_rerank or owner.engine.clip is not None, (
                "rerank requested but no CLIP checkpoint is loaded "
                "(start the server with --clip_path)"
            )
        except Exception as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return

        if seed is None:
            seed = owner.next_seed(num_images)
        t0 = time.monotonic()
        try:
            try:
                text_ids = owner.engine.tokenize(prompt)
            except Exception as exc:  # tokenizer failure is a server error
                self._reply(500, {"error": f"tokenization failed: {exc}"})
                return
            specs = [
                SampleSpec(
                    text_ids=text_ids,
                    seed=int(seed) + i,
                    temperature=temperature,
                    top_k=top_k,
                )
                for i in range(num_images)
            ]
            req = owner.batcher.submit(specs, timeout_s=timeout_s)
        except QueueFullError as exc:
            self._reply(503, {"error": str(exc)}, [("Retry-After", "1")])
            return
        except ShuttingDownError as exc:
            self._reply(503, {"error": str(exc)})
            return

        try:
            tokens, pixels = req.future.result(timeout=timeout_s + 5.0)
        except RequestTimeout as exc:
            req.cancel()
            self._reply(504, {"error": str(exc)})
            return
        except Exception as exc:
            self._reply(500, {"error": f"generation failed: {exc}"})
            return

        try:
            tokens = np.asarray(tokens)
            payload = {
                "prompt": prompt,
                "num_images": num_images,
                "seed": int(seed),
                "latency_ms": round((time.monotonic() - t0) * 1000.0, 2),
            }
            if pixels is not None:
                clip_scores = None
                if do_rerank:
                    pixels, scores, order = owner.engine.rerank(prompt, pixels)
                    tokens = tokens[order]  # keep tokens[i] paired with image i
                    if owner.engine.clip is not None:
                        clip_scores = np.asarray(scores).tolist()
                payload["shape"] = list(np.asarray(pixels).shape)
                payload["images_png_b64"] = [_png_b64(img) for img in pixels]
                if clip_scores is not None:
                    payload["clip_scores"] = clip_scores
            payload["tokens"] = tokens.tolist()
        except Exception as exc:  # rerank/PNG-encode failure: 500, not EOF
            self._reply(500, {"error": f"response encoding failed: {exc}"})
            return
        self._reply(200, payload)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, owner: "ServingServer"):
        self.owner = owner
        super().__init__(addr, _Handler)


class ServingServer:
    """Engine + batcher + HTTP listener with graceful lifecycle.

    `start()` binds and serves on a background thread (port 0 picks a free
    port; read it back from `.port`). `shutdown()` stops intake, drains the
    batcher queue, then closes the listener — in-flight clients get their
    results, new ones get 503.
    """

    def __init__(
        self,
        engine: GenerationEngine,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_delay_ms: float = 25.0,
        max_queue_rows: int = 64,
        request_timeout_s: float = 120.0,
        verbose: bool = False,
    ):
        self.engine = engine
        self.registry = engine.registry
        self.request_timeout_s = float(request_timeout_s)
        self.verbose = verbose
        if isinstance(engine, ContinuousEngine):
            # token-boundary admission: max_delay_ms does not apply (there
            # is no flush deadline; admission happens at chunk boundaries)
            self.batcher = ContinuousBatcher(
                engine,
                max_queue_rows=max_queue_rows,
                registry=self.registry,
            )
        else:
            self.batcher = MicroBatcher(
                engine,
                max_delay_ms=max_delay_ms,
                max_queue_rows=max_queue_rows,
                registry=self.registry,
            )
        try:
            self._httpd = _Server((host, port), self)
        except OSError:
            # bind failure (port in use, bad host): don't leak the batcher
            # worker thread the line above just started
            self.batcher.shutdown(drain=False)
            raise
        self._thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self._serving = False
        self._closed = False
        self._draining = False
        self._started_at = time.time()
        self._seed_lock = threading.Lock()
        self._seed_counter = int(time.time()) & 0x7FFFFFFF

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def next_seed(self, n: int) -> int:
        """Allocate n consecutive seeds for a request that didn't pin one."""
        with self._seed_lock:
            s = self._seed_counter
            self._seed_counter = (self._seed_counter + n) & 0x7FFFFFFF
            return s

    # how long a failed flush keeps /healthz at 503. Time-decayed rather
    # than cleared-on-success only: a health-gated router pulls traffic on
    # 503, which would starve the server of the successful batch it needs
    # to clear the error — latching it unhealthy forever.
    error_window_s: float = 60.0

    def health(self):
        # snapshot once: the batcher worker can set/clear the error fields
        # concurrently with this probe
        err = self.batcher.last_error
        err_age = self.batcher.error_age_s()
        erroring = err_age is not None and err_age < self.error_window_s
        healthy = not self._draining and not erroring
        detail = {
            "status": "ok" if healthy else "unhealthy",
            "uptime_s": round(time.time() - self._started_at, 1),
            "queue_depth_rows": self.batcher.queue_depth_rows,
            "compiled_shapes": list(self.engine.stats.compiled_shapes),
            "batch_shapes": list(self.engine.batch_shapes),
        }
        if isinstance(self.batcher, ContinuousBatcher):
            detail["engine"] = "continuous"
            detail["slots_active"] = self.batcher.allocator.n_active
            detail["chunk_tokens"] = self.engine.chunk_tokens
        if err is not None:
            detail["last_error"] = repr(err)
            if err_age is not None:
                detail["last_error_age_s"] = round(err_age, 1)
        if self._draining:
            detail["draining"] = True
        return healthy, detail

    def start(self) -> "ServingServer":
        assert self._thread is None, "already started"
        with self._state_lock:
            assert not self._closed, "server already shut down"
            self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="dalle-serving-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Foreground variant for the CLI: blocks until shutdown().

        Returns immediately if shutdown() already ran (e.g. a SIGTERM
        delivered during startup) instead of serving a closed socket.
        """
        assert self._thread is None, "already started in background"
        with self._state_lock:
            if self._closed:
                return
            self._serving = True
        self._httpd.serve_forever(poll_interval=0.05)

    def shutdown(self, drain: bool = True) -> None:
        self._draining = True
        self.batcher.shutdown(drain=drain)
        with self._state_lock:
            first_close = not self._closed
            self._closed = True
            serving = self._serving
        if serving:
            # socketserver's shutdown() waits on an event only serve_forever
            # sets; calling it on a never-served listener blocks forever.
            # (A serve loop that committed under _state_lock but hasn't
            # entered yet still exits promptly: its shutdown-request flag is
            # already set when the loop starts.)
            self._httpd.shutdown()
            self._serving = False
        if first_close:
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
