"""Stdlib-only JSON HTTP front end for the generation engine.

Endpoints:
  POST /generate   {"prompt": str, "num_images": int=1, "seed": int?,
                    "temperature": float=1.0, "top_k": float=0.9,
                    "rerank": bool=false, "timeout_s": float?}
                -> {"tokens": [[int]], "shape": [n, H, W, 3]?,
                    "images_png_b64": [str]?, "clip_scores": [float]?,
                    "latency_ms": float}
  GET  /healthz -> {"status": "ok", ...} (503 once draining or after an
                   engine failure — fail fast, don't wedge clients)
  GET  /metrics -> Prometheus text exposition from the shared registry
                   (`training/metrics.py:MetricsRegistry`): queue depth,
                   batch-occupancy histogram, request latency p50/p95,
                   per-stage wall time, compile-cache hits,
                   images/requests/batches totals. `?exemplars=1` adds
                   OpenMetrics exemplar annotations (most recent trace ID
                   per histogram).
  GET  /debug/traces -> Chrome/Perfetto `trace_event` JSON of the most
                   recent request traces (`obs/tracing.py` ring buffer);
                   load the body in ui.perfetto.dev. `?n=` bounds the
                   export; `?trace_id=` exact-looks-up one retained
                   trace (404 once evicted from the ring).
  GET  /debug/vitals -> vitals time-series ring (`obs/vitals.py`
                   sampler): queue depth, slots/blocks active,
                   dispatch-in-flight age, device memory stats, recent
                   watchdog stalls, SLO burn status. `?n=` tails it.
  GET  /debug/programs -> per-program XLA cost/memory table captured at
                   warmup (FLOPs, bytes accessed, HBM footprint) plus
                   live MFU / achieved bandwidth where measured.
                   `?per_shard=1` adds per-mesh-device rows where jax
                   exposed per-shard cost analysis at capture (sharded
                   engine; falls back to the global row otherwise).
  GET  /debug/state -> full engine-state dump for postmortems: slot
                   table with in-flight trace IDs, page tables +
                   refcounts (paged engine), queue summary, recent
                   compile events, worker-thread stacks. The same dump
                   rides every watchdog `stall` log event.
  POST /debug/profile?seconds=N -> on-demand `jax.profiler` capture of N
                   seconds of live traffic (root-gated -> 403,
                   single-flight -> 409); returns the TensorBoard trace
                   dir.
  POST /admin/drain -> reversibly pause intake on THIS replica: new
                   /generate requests get 503 + Retry-After, /healthz
                   goes 503 `"draining"` (so a health-gated router pulls
                   it), in-flight requests run to completion. Returns
                   the drain status (inflight/queued rows, quiesced).
                   The fleet router's `/admin/drain?replica=&propagate=1`
                   calls this so direct clients are refused during a
                   rolling restart too. `?migrate=1` makes it a
                   ZERO-LOST-WORK drain: every queued + in-flight
                   request is exported as a decode-state checkpoint at
                   the next chunk boundary (each waiting client gets a
                   409 carrying its checkpoint — the router re-dispatches
                   it as a resume; the full bundle rides this response).
  POST /admin/undrain -> resume intake.
  GET  /admin/checkpoints -> non-destructive chunk-boundary snapshot of
                   every in-flight request's decode state (pull-based
                   drain: collect, then kill, then re-dispatch); serves
                   the last crash-beacon bundle when the engine is
                   wedged.

Every /generate request gets a trace ID at ingress — ADOPTED from a valid
`x-dalle-trace` header (fleet context propagation, obs/aggregate.py:
the caller's span becomes the remote parent of this server's root span),
minted fresh otherwise. It rides the `GenRequest` through the batcher
(queue/prefill/chunk/harvest spans), comes back in the response payload
as `trace_id`, is logged as one structured JSON line per completed
request when a `StructuredLog` is attached, and — when a `TraceExporter`
is attached (`serve.py --trace_export URL`) — ships to the fleet trace
collector at finish.

`ThreadingHTTPServer` gives one thread per in-flight request; they all
funnel into the `MicroBatcher`, which is where concurrent requests
coalesce into one padded sampler batch. Backpressure maps to HTTP:
queue full -> 503 + Retry-After, per-request timeout -> 504 (the queued
request is cancelled so it never costs a batch row), engine error ->
500. Client disconnects are NOT detected mid-wait (stdlib handler
limitation); an abandoned request still completes and is discarded.

Streaming mode (`"stream": true` in the /generate body, continuous
engine only) switches the response to Server-Sent Events
(serving/streaming.py): a `progress` event at every decode chunk
boundary, a `preview` event (base64 PNGs of the partial token grid run
through the engine's warmed fill+decode program) every
`--preview_every` chunks, keep-alive comments on idle, and ONE terminal
event — `result` (the exact non-streamed payload), `migrated` (the 409
checkpoint as an event, so the fleet router can splice a resumed
replica's stream onto the client's), or `error`. Unlike the buffered
path, a streamed client disconnect IS detected (the next event write
fails) and cancels the request at the next chunk boundary via the
batcher's reap path; a re-dispatched request with the same
`x-dalle-request-key` re-attaches to the live stream instead of
double-submitting.
"""

from __future__ import annotations

import base64
import io
import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

import numpy as np

from dalle_pytorch_tpu.obs.aggregate import (
    TRACE_HEADER,
    default_site,
    parse_trace_header,
    sanitize_site,
)
from dalle_pytorch_tpu.serving.router import (
    REQUEST_KEY_HEADER,
    ROUTE_HEADER,
    parse_request_key,
    parse_route_header,
)
from dalle_pytorch_tpu.serving.migrate import (
    CheckpointCorrupt,
    CheckpointMismatch,
    CheckpointSpool,
    MigratedError,
    decode_checkpoint,
    encode_checkpoint,
    from_wire,
    to_wire,
)
from dalle_pytorch_tpu.obs.logging import StructuredLog
from dalle_pytorch_tpu.obs.profiler import ProfilerBusy, ProfilerCapture
from dalle_pytorch_tpu.obs.tracing import Tracer
from dalle_pytorch_tpu.obs.vitals import EngineVitals, thread_stacks
from dalle_pytorch_tpu.utils import compile_guard
from dalle_pytorch_tpu.serving.batcher import (
    ContinuousBatcher,
    MicroBatcher,
    QueueFullError,
    RequestTimeout,
    ShuttingDownError,
)
from dalle_pytorch_tpu.serving.qos import (
    PRIORITY_CLASSES,
    ShedError,
    TenantQuotaError,
)
from dalle_pytorch_tpu.serving.streaming import (
    KEEPALIVE,
    RequestStream,
    StreamRegistry,
    encode_sse,
)
from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    GenerationEngine,
    SampleSpec,
)

MAX_BODY_BYTES = 1 << 20  # prompts are tiny; reject anything bigger


def _usage_block(engine, req, num_images: int) -> dict:
    """Per-request token accounting for the response payload: the fleet
    router's usage ledger attributes chip-seconds and decode work per
    tenant off this block, so it must distinguish tokens this replica
    actually decoded from tokens restored verbatim out of a resume
    checkpoint (migrated/resumed requests re-pay nothing for those)."""
    seq = int(getattr(engine, "image_seq_len", 0) or 0)
    resumed = sum(
        len(t) for t in (getattr(req, "resume_tokens", None) or {}).values()
    )
    return {
        "rows": int(num_images),
        "decoded_tokens": max(0, int(num_images) * seq - resumed),
        "resumed_tokens": int(resumed),
    }


def _png_b64(img: np.ndarray) -> str:
    from PIL import Image

    from dalle_pytorch_tpu.utils.images import to_uint8

    buf = io.BytesIO()
    Image.fromarray(to_uint8(img)).save(buf, format="PNG")
    return base64.b64encode(buf.getvalue()).decode("ascii")


class _Handler(BaseHTTPRequestHandler):
    # the served object is reachable as self.server (ServingHTTPServer)
    protocol_version = "HTTP/1.1"
    # per-connection socket timeout: bounds idle keep-alive connections and
    # slow/partial request bodies (slowloris) so they can't pin handler
    # threads forever — ThreadingHTTPServer spawns one thread per connection
    timeout = 120

    def log_message(self, fmt, *args):  # route access logs through the owner
        if self.server.owner.verbose:
            super().log_message(fmt, *args)

    # ------------------------------------------------------------ helpers

    def _reply(self, code: int, payload: dict, extra_headers=()) -> None:
        # default=str: debug dumps carry numpy scalars and Paths; a
        # diagnostics endpoint must degrade to strings, not 500
        body = json.dumps(payload, default=str).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code >= 400:
            # error paths may not have drained the request body; under
            # HTTP/1.1 keep-alive the leftover bytes would be parsed as the
            # next request line, so close instead of corrupting the stream
            self.send_header("Connection", "close")
            self.close_connection = True
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _parse_n(self, params) -> Optional[int]:
        """Shared `?n=` tail bound of the debug ring exports; raises
        ValueError on anything but a positive integer."""
        n_param = params.get("n", [None])[0]
        n = None if n_param is None else int(n_param)
        if n is not None and n <= 0:
            raise ValueError(n)
        return n

    # -------------------------------------------------------------- GETs

    def do_GET(self):
        owner = self.server.owner
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            healthy, detail = owner.health()
            self._reply(200 if healthy else 503, detail)
        elif path == "/metrics":
            # exemplars are OpenMetrics syntax; classic Prometheus text
            # parsers reject them, so they're strictly opt-in per scrape
            # and served with the OpenMetrics content type (+ # EOF)
            exemplars = parse_qs(query).get("exemplars", ["0"])[0] in (
                "1", "true",
            )
            text = owner.registry.render(exemplars=exemplars).encode("utf-8")
            content_type = (
                "application/openmetrics-text; version=1.0.0; charset=utf-8"
                if exemplars
                else "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_response(200)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            try:
                self.wfile.write(text)
            except (BrokenPipeError, ConnectionResetError):
                pass  # scraper gave up mid-scrape; not traceback-worthy
        elif path == "/debug/traces":
            # ?n= bounds the payload: a small-chunk continuous config
            # holds one chunk span per decode chunk, so the full ring
            # can serialize to megabytes. ?trace_id= is the exact lookup
            # of ONE retained trace (the request log / response payload
            # hands clients the ID); 404 once the ring evicted it.
            params = parse_qs(query)
            trace_id = params.get("trace_id", [None])[0]
            if trace_id is not None:
                trace = owner.tracer.find(trace_id)
                if trace is None:
                    self._reply(404, {
                        "error": f"trace {trace_id} not retained "
                        "(evicted from the ring or never minted)"
                    })
                    return
                self._reply(200, owner.tracer.trace_events(traces=[trace]))
                return
            try:
                n = self._parse_n(params)
            except ValueError:
                self._reply(400, {"error": "n must be a positive integer"})
                return
            self._reply(200, owner.tracer.trace_events(n))
        elif path == "/debug/vitals":
            try:
                n = self._parse_n(parse_qs(query))
            except ValueError:
                self._reply(400, {"error": "n must be a positive integer"})
                return
            self._reply(200, owner.vitals.detail(n))
        elif path == "/debug/programs":
            table = getattr(owner.engine, "cost_table", None)
            if table is None:
                self._reply(200, {
                    "programs": [],
                    "note": "no ProgramCostTable attached "
                    "(set engine.cost_table before warmup)",
                })
            else:
                # ?per_shard=1 adds per-mesh-device cost rows where jax
                # exposed per-shard analysis at capture (global-only
                # programs just render without the block)
                per_shard = parse_qs(query).get("per_shard", ["0"])[0] in (
                    "1", "true",
                )
                self._reply(200, table.detail(per_shard=per_shard))
        elif path == "/debug/state":
            self._reply(200, owner.state_dump())
        elif path == "/admin/checkpoints":
            # pull-based drain: a chunk-boundary snapshot of every
            # in-flight request's decode state WITHOUT disturbing it —
            # an orchestrator can collect, then kill, then re-dispatch
            self._reply(200, owner.checkpoints_snapshot())
        else:
            self._reply(404, {"error": f"unknown path {self.path}"})

    # -------------------------------------------------------------- POSTs

    def _profile(self, owner, query: str) -> None:
        """POST /debug/profile?seconds=N — blocking on-demand capture."""
        try:
            length = int(self.headers.get("Content-Length", "0") or 0)
            # the endpoint takes no body; an oversized or malformed one is
            # a client error (and _reply's >=400 path closes the
            # connection, so undrained bytes can't corrupt keep-alive).
            # Explicit raise, not assert: the bound must survive python -O.
            if not 0 <= length <= MAX_BODY_BYTES:
                raise ValueError(f"bad Content-Length {length}")
        except ValueError as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return
        if length:
            self.rfile.read(length)  # drain before replying 200 keep-alive
        try:
            seconds = float(parse_qs(query).get("seconds", ["2"])[0])
        except (TypeError, ValueError):
            self._reply(400, {"error": "seconds must be a number"})
            return
        # report what was actually captured: capture() clamps oversized
        # requests to max_seconds, and the response/log must not claim an
        # hour-long trace when the dir holds 60s
        if seconds > 0:
            seconds = min(seconds, owner.profiler.max_seconds)
        try:
            trace_dir = owner.profiler.capture(seconds)
        except ProfilerBusy as exc:
            self._reply(409, {"error": str(exc)})
            return
        except PermissionError as exc:
            self._reply(403, {"error": str(exc)})
            return
        except ValueError as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return
        except Exception as exc:
            self._reply(500, {"error": f"profiler capture failed: {exc}"})
            return
        if owner.log is not None:
            owner.log.event(
                "profile_capture", trace_dir=str(trace_dir), seconds=seconds
            )
        self._reply(200, {"trace_dir": str(trace_dir), "seconds": seconds})

    def _drain_body(self) -> bool:
        """Read and discard a bounded request body (admin POSTs take
        none, but keep-alive requires draining whatever came). False +
        a 400 reply on an oversized/malformed length."""
        try:
            length = int(self.headers.get("Content-Length", "0") or 0)
            if not 0 <= length <= MAX_BODY_BYTES:
                raise ValueError(f"bad Content-Length {length}")
        except ValueError as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return False
        if length:
            self.rfile.read(length)
        return True

    def do_POST(self):
        owner = self.server.owner
        path, _, query = self.path.partition("?")
        if path == "/debug/profile":
            self._profile(owner, query)
            return
        if path == "/admin/drain":
            if self._drain_body():
                migrate = parse_qs(query).get("migrate", ["0"])[0] in (
                    "1", "true",
                )
                self._reply(200, owner.drain_intake(migrate=migrate))
            return
        if path == "/admin/undrain":
            if self._drain_body():
                self._reply(200, owner.undrain_intake())
            return
        if path != "/generate":
            self._reply(404, {"error": f"unknown path {self.path}"})
            return
        if owner.intake_paused:
            # draining for a rolling restart: refuse BEFORE reading the
            # body/minting a trace — the router stopped sending already,
            # this is the direct-client path
            self._reply(
                503, {"error": "replica draining (admin)"},
                [("Retry-After", "5")],
            )
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if not 0 < length <= MAX_BODY_BYTES:
                raise ValueError(f"bad Content-Length {length}")
            body = json.loads(self.rfile.read(length))
            prompt = body["prompt"]
            assert isinstance(prompt, str) and prompt.strip(), "empty prompt"
            num_images = int(body.get("num_images", 1))
            assert 1 <= num_images <= owner.engine.max_batch, (
                f"num_images must be in [1, {owner.engine.max_batch}]"
            )
            temperature = float(body.get("temperature", 1.0))
            # NaN fails every comparison, so this also rejects it (Python's
            # json parser accepts the bare NaN literal)
            assert 0.0 <= temperature <= 100.0, (
                "temperature must be a finite value in [0, 100]"
            )
            top_k = float(body.get("top_k", 0.9))
            assert 0.0 <= top_k <= 1.0, "top_k is a fraction in [0, 1]"
            seed = body.get("seed")
            if seed is not None:
                assert not isinstance(seed, (list, dict, bool)), "seed must be an int"
                seed = int(seed)
            timeout_s = float(body.get("timeout_s", owner.request_timeout_s))
            # NaN fails the comparison; cap at the server's own policy so a
            # client can't pin handler threads/queue rows past it
            assert 0.0 < timeout_s <= owner.request_timeout_s, (
                f"timeout_s must be in (0, {owner.request_timeout_s}]"
            )
            do_rerank = bool(body.get("rerank", False))
            assert not do_rerank or owner.engine.clip is not None, (
                "rerank requested but no CLIP checkpoint is loaded "
                "(start the server with --clip_path)"
            )
            priority = body.get("priority", "normal")
            assert priority in PRIORITY_CLASSES, (
                f"priority must be one of {list(PRIORITY_CLASSES)}"
            )
            tenant = body.get("tenant", "")
            assert isinstance(tenant, str) and len(tenant) <= 128, (
                "tenant must be a string of at most 128 characters"
            )
            resume_wire = body.get("resume")
            assert resume_wire is None or isinstance(resume_wire, str), (
                "resume must be a wire-encoded checkpoint string"
            )
            stream_mode = bool(body.get("stream", False))
            assert not stream_mode or isinstance(
                owner.batcher, ContinuousBatcher
            ), (
                "stream=true requires the continuous engine "
                "(start the server with --continuous)"
            )
        except Exception as exc:
            self._reply(400, {"error": f"bad request: {exc}"})
            return

        if seed is None:
            seed = owner.next_seed(num_images)
        t0 = time.monotonic()
        # trace context at ingress: a valid x-dalle-trace header ADOPTS
        # the caller's trace ID (and records the caller's span as the
        # remote parent) so a bench client's or replica router's spans
        # and this server's land in ONE fleet-collector tree; absent or
        # malformed, the ID is minted here exactly as before. finish()
        # runs on EVERY exit path so error traces reach the ring buffer,
        # the exporter, and the request log too.
        ctx = parse_trace_header(self.headers.get(TRACE_HEADER))
        trace = owner.tracer.start_trace(
            "request",
            trace_id=ctx[0] if ctx else None,
            parent_uid=ctx[1] if ctx else None,
            rows=num_images, seed=int(seed),
            prompt_chars=len(prompt),
        )

        # submit-time load context (queue depth, slots, free blocks):
        # stamped just before the submit call so the log line records the
        # admission conditions this request actually faced. Seeded with
        # the fleet router's routing decision (x-dalle-route:
        # replica/attempt/hedged) so a fleet log join can attribute
        # every retry to the attempt that produced it.
        admission: dict = dict(
            parse_route_header(self.headers.get(ROUTE_HEADER)) or {}
        )

        def closed_out(outcome: str, status: int, **fields):
            trace.finish(outcome=outcome)
            owner.log_request(
                trace, outcome=outcome, status=status,
                latency_ms=(time.monotonic() - t0) * 1000.0,
                rows=num_images, **admission, **fields,
            )

        try:
            try:
                text_ids = owner.engine.tokenize(prompt)
            except Exception as exc:  # tokenizer failure is a server error
                closed_out("error", 500, error=repr(exc))
                self._reply(500, {"error": f"tokenization failed: {exc}"})
                return
            specs = [
                SampleSpec(
                    text_ids=text_ids,
                    seed=int(seed) + i,
                    temperature=temperature,
                    top_k=top_k,
                )
                for i in range(num_images)
            ]
            # decode-state resume (serving/migrate.py): a checkpoint that
            # fails fingerprint/integrity/consistency validation degrades
            # to a clean position-0 restart — counted and logged, NEVER a
            # client-visible error or a cross-build resume
            resume_cp = resume_bytes = None
            if resume_wire is not None:
                resume_cp, resume_bytes = owner.validate_resume(
                    resume_wire, specs
                )
                if resume_cp is not None:
                    admission["migrated_from"] = resume_cp.site
                    admission["resumed_at_chunk"] = int(
                        resume_cp.chunk_index
                    )
                    admission["checkpoint_bytes"] = resume_bytes
                else:
                    admission["resume_rejected"] = True
            request_key = parse_request_key(
                self.headers.get(REQUEST_KEY_HEADER)
            )
            admission.update(owner.admission_context())
            admission["priority"] = priority
            if tenant:
                admission["tenant"] = tenant
            stream = None
            if stream_mode:
                existing = owner.streams.reattach(request_key)
                if existing is not None and existing.request is not None:
                    # this replica is ALREADY decoding this request key (a
                    # router failover retry or a network blip between
                    # router and replica re-dispatched it): steal the
                    # reader generation and continue the live stream
                    # instead of double-submitting the decode
                    admission["stream_reattach"] = True
                    self._stream_serve(
                        existing, existing.attach(), existing.request,
                        prompt=prompt, do_rerank=do_rerank,
                        timeout_s=timeout_s, t0=t0, trace=trace,
                        closed_out=closed_out, reattach=True,
                    )
                    return
                stream = RequestStream(
                    key=request_key, trace_id=trace.trace_id or None
                )
                if not owner.streams.register(stream):
                    # registry full of LIVE attached streams: shed rather
                    # than run an untracked stream past the bound
                    closed_out(
                        "rejected", 503, streamed=True,
                        error="stream registry full",
                    )
                    self._reply(
                        503, {"error": "stream registry full"},
                        [("Retry-After", "1")],
                    )
                    return
            req = owner.batcher.submit(
                specs, timeout_s=timeout_s, trace=trace,
                priority=priority, tenant=tenant,
                request_key=request_key,
                resume=resume_cp, resume_bytes=resume_bytes,
                stream=stream,
            )
        except QueueFullError as exc:
            if stream is not None:
                owner.streams.discard(stream)
            closed_out("rejected", 503, error=str(exc))
            # Retry-After from the batcher's chunk-wall-EMA drain
            # estimate where it has one; the pre-first-measurement
            # fallback is the old constant 1s
            retry = getattr(exc, "retry_after_s", None) or 1.0
            self._reply(
                503, {"error": str(exc)},
                [("Retry-After", str(int(round(retry))))],
            )
            return
        except ShedError as exc:
            # deadline-aware admission shed: the cost model says this
            # request's own timeout is unmeetable — 503 now beats a 504
            # after timeout_s of queueing
            if stream is not None:
                owner.streams.discard(stream)
            closed_out("shed", 503, error=str(exc))
            self._reply(
                503, {"error": str(exc)},
                [("Retry-After", str(int(round(exc.retry_after_s))))],
            )
            return
        except TenantQuotaError as exc:
            if stream is not None:
                owner.streams.discard(stream)
            closed_out("quota", 429, error=str(exc))
            self._reply(
                429, {"error": str(exc)},
                [("Retry-After", str(int(round(exc.retry_after_s))))],
            )
            return
        except ShuttingDownError as exc:
            if stream is not None:
                owner.streams.discard(stream)
            closed_out("shutdown", 503)
            self._reply(503, {"error": str(exc)})
            return

        if stream is not None:
            # first attachment of a fresh stream (not a re-attach)
            self._stream_serve(
                stream, stream.attach(mark_reattach=False), req,
                prompt=prompt, do_rerank=do_rerank, timeout_s=timeout_s,
                t0=t0, trace=trace, closed_out=closed_out, reattach=False,
            )
            return

        try:
            tokens, pixels = req.future.result(timeout=timeout_s + 5.0)
        except RequestTimeout as exc:
            req.cancel()
            closed_out("timeout", 504)
            self._reply(504, {"error": str(exc)})
            return
        except MigratedError as exc:
            # drain?migrate=1 exported this request's decode state at the
            # chunk boundary: 409 carries the checkpoint so the fleet
            # router re-dispatches THE SAME request as a resume on a
            # healthy replica (a direct client may re-POST with
            # {"resume": checkpoint} itself)
            blob = exc.checkpoint.encoded or encode_checkpoint(
                exc.checkpoint, owner.resume_fingerprint
            )
            closed_out(
                "migrated", 409,
                resumed_at_chunk=int(exc.checkpoint.chunk_index),
                checkpoint_bytes=len(blob),
            )
            self._reply(409, {
                "error": "request migrated out (replica draining); "
                "re-dispatch with the attached resume checkpoint",
                "migrated": True,
                "checkpoint": to_wire(blob),
                "resumed_at_chunk": int(exc.checkpoint.chunk_index),
                "migrated_from": exc.checkpoint.site,
            })
            return
        except Exception as exc:
            incidents = list(getattr(req, "incidents", ()) or ())
            if (
                owner.quarantine_after
                and len(incidents) >= owner.quarantine_after
            ):
                # poison-request quarantine (batcher half): this request
                # was in flight for `quarantine_after`+ consecutive
                # failed engine dispatches — it plausibly CAUSES them.
                # A terminal 4xx (with the incident ids) tells the
                # client and the fleet router not to redispatch it; a
                # 500 would read as replica failure and invite failover.
                owner.count_quarantined()
                closed_out(
                    "quarantined", 422, error=repr(exc),
                    incidents=incidents,
                )
                self._reply(422, {
                    "error": "request quarantined after "
                    f"{len(incidents)} failed engine dispatches: {exc}",
                    "incidents": incidents,
                })
                return
            closed_out("error", 500, error=repr(exc), incidents=incidents)
            self._reply(500, {"error": f"generation failed: {exc}"})
            return

        tr0 = time.monotonic()  # stage timing works with tracing off too
        respond_span = trace.begin("respond")
        try:
            tokens = np.asarray(tokens)
            payload = {
                "prompt": prompt,
                "num_images": num_images,
                "seed": int(seed),
                "latency_ms": round((time.monotonic() - t0) * 1000.0, 2),
                # per-request work accounting for the fleet router's
                # usage ledger: tokens this replica decoded for THIS
                # request vs tokens restored from a resume checkpoint
                "usage": _usage_block(owner.engine, req, num_images),
            }
            if trace:
                payload["trace_id"] = trace.trace_id
            if pixels is not None:
                clip_scores = None
                if do_rerank:
                    pixels, scores, order = owner.engine.rerank(prompt, pixels)
                    tokens = tokens[order]  # keep tokens[i] paired with image i
                    if owner.engine.clip is not None:
                        clip_scores = np.asarray(scores).tolist()
                payload["shape"] = list(np.asarray(pixels).shape)
                payload["images_png_b64"] = [_png_b64(img) for img in pixels]
                if clip_scores is not None:
                    payload["clip_scores"] = clip_scores
            payload["tokens"] = tokens.tolist()
        except Exception as exc:  # rerank/PNG-encode failure: 500, not EOF
            trace.end(respond_span, error=repr(exc))
            # observe the stage on error too, so /metrics and the traces
            # keep agreeing (same contract as the batcher's harvest path)
            owner.batcher.stage_seconds.labels("respond").observe(
                time.monotonic() - tr0, exemplar=trace.trace_id or None
            )
            closed_out("error", 500, error=repr(exc))
            self._reply(500, {"error": f"response encoding failed: {exc}"})
            return
        trace.end(respond_span)
        owner.batcher.stage_seconds.labels("respond").observe(
            time.monotonic() - tr0, exemplar=trace.trace_id or None
        )
        # paged engine: whether this request admitted via the prefix cache
        # — the request-log field that explains cheap vs full prefills
        extra = {} if req.prefix_hit is None else {"prefix_hit": req.prefix_hit}
        if req.preemptions:
            # QoS lifecycle made visible per request: how often this one
            # was suspended for a higher class / retried after a failure
            extra["preemptions"] = req.preemptions
        if req.dispatch_retries:
            extra["dispatch_retries"] = req.dispatch_retries
        closed_out("ok", 200, **extra)
        self._reply(200, payload)

    # ------------------------------------------------------ SSE streaming

    #: idle keep-alive cadence on an event stream — an SSE comment line
    #: every this-many seconds of silence keeps proxies and clients from
    #: mistaking a slow decode for a dead connection
    KEEPALIVE_S = 10.0

    @staticmethod
    def _stream_payload(data: dict) -> dict:
        """Event data -> JSON-safe dict. Preview events carry raw pixel
        arrays off the worker; the PNG/base64 encode happens HERE, on the
        handler thread that owns the socket — the decode hotloop never
        pays image encoding."""
        pixels = data.get("pixels")
        if pixels is None:
            return data
        out = {k: v for k, v in data.items() if k != "pixels"}
        try:
            out["previews_png_b64"] = [
                _png_b64(img) for img in np.asarray(pixels)
            ]
        except Exception as exc:  # PIL hiccup: degrade, don't kill the stream
            out["preview_error"] = repr(exc)
        return out

    def _stream_serve(self, stream, gen, req, *, prompt, do_rerank,
                      timeout_s, t0, trace, closed_out, reattach) -> None:
        """Serve one streaming /generate response: SSE frames off the
        request's `RequestStream` until its terminal event.

        The batcher worker writes progress/preview events at chunk
        boundaries; this handler thread drains them to the socket,
        emitting keep-alive comments on idle. When the request future
        resolves, the CURRENT reader converts it into the stream's one
        terminal event (`result`/`migrated`/`error` — same status
        mapping as the buffered path). A write failure means the client
        went away: the request is cancelled at the next chunk boundary
        via the batcher's reap path — unless a re-dispatched copy of the
        request already re-attached and stole the reader generation, in
        which case this handler exits WITHOUT cancelling the stream its
        successor is serving."""
        owner = self.server.owner
        try:
            cursor = int(self.headers.get("Last-Event-ID", "0"))
        except (TypeError, ValueError):
            cursor = 0
        # backstop only: the worker's reaper expires the request (and
        # resolves the future) on its own; this guards a wedged worker
        deadline = t0 + timeout_s + 30.0
        logged = False  # exactly one request-log line per handler
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            # SSE frames are self-delimiting and the stream ends with the
            # connection — no Content-Length, no keep-alive reuse
            self.send_header("Connection", "close")
            self.close_connection = True
            self.end_headers()
            self.wfile.write(encode_sse("open", {
                "request_key": stream.key,
                "trace_id": stream.trace_id,
                "site": owner.identity.get("site"),
                "reattach": bool(reattach),
                "cursor": int(cursor),
            }))
            self.wfile.flush()
            owner.count_stream_event("open")
            while True:
                if not stream.current(gen):
                    # superseded: a re-dispatch of this request key
                    # re-attached — the successor owns the stream now
                    if not logged:
                        closed_out(
                            "superseded", 200, streamed=True,
                            previews_sent=stream.previews_sent,
                            stream_reattaches=stream.reattaches,
                        )
                    return
                events, drained = stream.next_events(
                    cursor, timeout=self.KEEPALIVE_S
                )
                for seq, etype, data in events:
                    self.wfile.write(
                        encode_sse(etype, self._stream_payload(data),
                                   seq=seq)
                    )
                    cursor = seq + 1
                self.wfile.flush()
                if drained:
                    break
                if stream.finished:
                    continue  # terminal queued above the cursor: drain it
                if req.future.done():
                    logged = self._stream_finish(
                        stream, req, prompt=prompt, do_rerank=do_rerank,
                        t0=t0, trace=trace, closed_out=closed_out,
                    ) or logged
                    continue
                if not events:
                    if time.monotonic() > deadline:
                        # wedged-worker backstop: the reaper never expired
                        # the request, so the handler ends the stream
                        req.cancel()
                        if stream.finish(
                            "error", status=504,
                            error="stream deadline exceeded",
                        ):
                            owner.count_stream_event("error")
                            closed_out(
                                "timeout", 504, streamed=True,
                                previews_sent=stream.previews_sent,
                                stream_reattaches=stream.reattaches,
                            )
                            logged = True
                        continue
                    self.wfile.write(KEEPALIVE)
                    self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError) as exc:
            # client went away mid-stream: cancel at the next chunk
            # boundary (reap path frees the slots) — but only while this
            # handler is still the CURRENT reader; a superseded reader
            # must never cancel the request its successor is streaming
            if stream.orphan(gen) and not req.future.done():
                req.cancel()
            if not logged:
                closed_out(
                    "disconnected", 200, streamed=True, error=repr(exc),
                    previews_sent=stream.previews_sent,
                    stream_reattaches=stream.reattaches,
                )
            return
        # terminal written and acknowledged by the socket: the stream is
        # complete — drop it from the registry (a late re-dispatch of the
        # same key starts a fresh request/stream, as on the buffered path)
        owner.streams.discard(stream)
        if not logged:
            # this handler replayed a terminal another handler resolved
            # (re-attach racing completion); the winner logged the
            # authoritative outcome line already
            closed_out(
                "streamed", 200, streamed=True,
                previews_sent=stream.previews_sent,
                stream_reattaches=stream.reattaches,
            )

    def _stream_finish(self, stream, req, *, prompt, do_rerank, t0, trace,
                       closed_out) -> bool:
        """Resolve the request future into the stream's ONE terminal
        event, with the same outcome/status mapping as the buffered
        path. Returns True when THIS caller won the terminal (and wrote
        the request-log line); False when another handler already
        finished the stream."""
        owner = self.server.owner
        num_images = len(req.specs)
        seed = int(req.specs[0].seed)

        def fields(**extra):
            out = dict(
                streamed=True,
                previews_sent=stream.previews_sent,
                stream_reattaches=stream.reattaches,
            )
            out.update(extra)
            return out

        try:
            tokens, pixels = req.future.result(timeout=0)
        except RequestTimeout as exc:
            req.cancel()
            if not stream.finish("error", status=504, error=str(exc)):
                return False
            owner.count_stream_event("error")
            closed_out("timeout", 504, **fields())
            return True
        except MigratedError as exc:
            # drain?migrate=1 exported this request at the chunk
            # boundary: the checkpoint rides the TERMINAL EVENT (the SSE
            # analogue of the buffered path's 409 body) so the fleet
            # router re-dispatches the same request as a resume and
            # splices the new replica's stream onto the client's
            blob = exc.checkpoint.encoded or encode_checkpoint(
                exc.checkpoint, owner.resume_fingerprint
            )
            if not stream.finish(
                "migrated",
                checkpoint=to_wire(blob),
                resumed_at_chunk=int(exc.checkpoint.chunk_index),
                migrated_from=exc.checkpoint.site,
            ):
                return False
            owner.count_stream_event("migrated")
            closed_out(
                "migrated", 409, **fields(
                    resumed_at_chunk=int(exc.checkpoint.chunk_index),
                    checkpoint_bytes=len(blob),
                ),
            )
            return True
        except Exception as exc:
            incidents = list(getattr(req, "incidents", ()) or ())
            status, outcome = 500, "error"
            data = {"error": f"generation failed: {exc}"}
            if (
                owner.quarantine_after
                and len(incidents) >= owner.quarantine_after
            ):
                owner.count_quarantined()
                status, outcome = 422, "quarantined"
                data = {
                    "error": "request quarantined after "
                    f"{len(incidents)} failed engine dispatches: {exc}",
                    "incidents": incidents,
                }
            if not stream.finish("error", status=status, **data):
                return False
            owner.count_stream_event("error")
            extra = fields(error=repr(exc))
            if incidents:
                extra["incidents"] = incidents
            closed_out(outcome, status, **extra)
            return True

        # success terminal: the SAME payload shape the buffered path
        # replies with, carried in the `result` event
        tr0 = time.monotonic()
        respond_span = trace.begin("respond")
        try:
            tokens = np.asarray(tokens)
            payload = {
                "prompt": prompt,
                "num_images": num_images,
                "seed": seed,
                "latency_ms": round((time.monotonic() - t0) * 1000.0, 2),
                "usage": _usage_block(owner.engine, req, num_images),
            }
            if trace:
                payload["trace_id"] = trace.trace_id
            if pixels is not None:
                clip_scores = None
                if do_rerank:
                    pixels, scores, order = owner.engine.rerank(
                        prompt, pixels
                    )
                    tokens = tokens[order]
                    if owner.engine.clip is not None:
                        clip_scores = np.asarray(scores).tolist()
                payload["shape"] = list(np.asarray(pixels).shape)
                payload["images_png_b64"] = [_png_b64(img) for img in pixels]
                if clip_scores is not None:
                    payload["clip_scores"] = clip_scores
            payload["tokens"] = tokens.tolist()
        except Exception as exc:  # rerank/PNG-encode failure
            trace.end(respond_span, error=repr(exc))
            owner.batcher.stage_seconds.labels("respond").observe(
                time.monotonic() - tr0, exemplar=trace.trace_id or None
            )
            if not stream.finish(
                "error", status=500,
                error=f"response encoding failed: {exc}",
            ):
                return False
            owner.count_stream_event("error")
            closed_out("error", 500, **fields(error=repr(exc)))
            return True
        trace.end(respond_span)
        owner.batcher.stage_seconds.labels("respond").observe(
            time.monotonic() - tr0, exemplar=trace.trace_id or None
        )
        if not stream.finish("result", **payload):
            return False
        owner.count_stream_event("result")
        extra = fields()
        if req.prefix_hit is not None:
            extra["prefix_hit"] = req.prefix_hit
        if req.preemptions:
            extra["preemptions"] = req.preemptions
        if req.dispatch_retries:
            extra["dispatch_retries"] = req.dispatch_retries
        closed_out("ok", 200, **extra)
        return True


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr, owner: "ServingServer"):
        self.owner = owner
        super().__init__(addr, _Handler)


class ServingServer:
    """Engine + batcher + HTTP listener with graceful lifecycle.

    `start()` binds and serves on a background thread (port 0 picks a free
    port; read it back from `.port`). `shutdown()` stops intake, drains the
    batcher queue, then closes the listener — in-flight clients get their
    results, new ones get 503.
    """

    def __init__(
        self,
        engine: GenerationEngine,
        host: str = "127.0.0.1",
        port: int = 8000,
        max_delay_ms: float = 25.0,
        max_queue_rows: int = 64,
        request_timeout_s: float = 120.0,
        verbose: bool = False,
        tracer: Optional[Tracer] = None,
        log: Optional[StructuredLog] = None,
        log_requests: bool = True,
        profiler: Optional[ProfilerCapture] = None,
        trace_dump_path: Optional[str] = None,
        vitals: Optional[EngineVitals] = None,
        exporter=None,
        tenant_quota_rows: Optional[int] = None,
        tenant_weights: Optional[dict] = None,
        preempt: bool = True,
        deadline_shed: bool = True,
        reserve_slots: int = 0,
        quarantine_after: int = 2,
        checkpoint_spool=None,
        spool_every: int = 8,
        preview_every: int = 4,
        max_streams: int = 256,
    ):
        self.engine = engine
        self.registry = engine.registry
        self.request_timeout_s = float(request_timeout_s)
        self.verbose = verbose
        # batcher-half poison quarantine: a request that died carrying
        # this many dispatch-failure incident ids gets a terminal 422
        # (with the ids) instead of a failover-inviting 500. The default
        # of 2 pairs with the batcher's one bounded retry: first failure
        # retries, a request whose retry ALSO failed is the common
        # factor of two consecutive incidents. 0 disables.
        self.quarantine_after = int(quarantine_after)
        self._m_quarantined = self.registry.counter(
            "dalle_serving_quarantined_total",
            "requests failed as poison: in flight for quarantine_after+ "
            "consecutive failed engine dispatches (terminal 422)",
        )
        # vitals default OFF (the inert, counter-gated zero-allocation
        # object) — serve.py passes an enabled sampler; tests opt in
        self.vitals = (
            vitals if vitals is not None else EngineVitals(enabled=False)
        )
        # tracing defaults ON: the ring buffer is bounded and span
        # bookkeeping is host-side clock reads — pass
        # Tracer(enabled=False) to get the pinned zero-allocation path
        self.tracer = tracer if tracer is not None else Tracer(max_traces=128)
        # fleet export (obs/aggregate.py TraceExporter, `serve.py
        # --trace_export URL`): attached here so the server owns its
        # lifecycle — shutdown stops the shipper thread after the last
        # handler finished its trace. None leaves NULL_EXPORTER in place.
        self.exporter = exporter
        if exporter is not None:
            exporter.attach(self.tracer)
        self.log = log  # None: no structured logging at all (tests stay quiet)
        # log_requests=False keeps lifecycle events (warmup, trace_dump,
        # shutdown) flowing but drops the per-request lines — the
        # `serve.py --no_request_log` contract
        self.log_requests = bool(log_requests)
        self.profiler = (
            profiler if profiler is not None else ProfilerCapture()
        )
        self.trace_dump_path = trace_dump_path
        self._trace_dumped = False
        # build identity for decode-state checkpoints (serving/migrate.py):
        # a checkpoint resumes here ONLY when the exporting replica's
        # fingerprint matches; engines without the hook (test fakes) fall
        # back to a shared sentinel so same-process fakes interoperate
        fp_fn = getattr(engine, "resume_fingerprint", None)
        try:
            self.resume_fingerprint = (
                fp_fn() if fp_fn is not None else "unfingerprinted"
            )
        except Exception:
            self.resume_fingerprint = "unfingerprinted"
        self._m_resume_rejects = self.registry.counter_family(
            "dalle_serving_resume_rejects_total",
            "resume checkpoints refused and degraded to a clean "
            "position-0 restart, by reason (mismatch: different build "
            "fingerprint; corrupt: failed integrity validation; "
            "inconsistent: checkpoint disagrees with the request body)",
            label_name="reason",
        )
        self.spool = (
            checkpoint_spool
            if checkpoint_spool is None
            or isinstance(checkpoint_spool, CheckpointSpool)
            else CheckpointSpool(checkpoint_spool)
        )
        # streaming /generate (serving/streaming.py): request-key -> live
        # SSE stream. Built for every batcher flavor (the gauge reads 0
        # on a micro engine, where stream=true is a 400) so /healthz and
        # tests see one shape.
        self._m_streams_active = self.registry.gauge(
            "dalle_serving_streams_active",
            "live SSE event streams currently registered "
            "(streaming /generate requests incl. re-attachable orphans)",
        )
        self.streams = StreamRegistry(
            max_streams=max_streams, gauge=self._m_streams_active.set
        )
        if isinstance(engine, ContinuousEngine):
            # token-boundary admission: max_delay_ms does not apply (there
            # is no flush deadline; admission happens at chunk boundaries)
            self.batcher = ContinuousBatcher(
                engine,
                max_queue_rows=max_queue_rows,
                registry=self.registry,
                tenant_quota_rows=tenant_quota_rows,
                tenant_weights=tenant_weights,
                log=log,
                preempt=preempt,
                deadline_shed=deadline_shed,
                reserve_slots=reserve_slots,
                spool=self.spool,
                spool_every=spool_every,
                preview_every=preview_every,
            )
            self.batcher.checkpoint_fingerprint = self.resume_fingerprint
        else:
            self.batcher = MicroBatcher(
                engine,
                max_delay_ms=max_delay_ms,
                max_queue_rows=max_queue_rows,
                registry=self.registry,
                tenant_quota_rows=tenant_quota_rows,
                tenant_weights=tenant_weights,
                log=log,
            )
        # wire the sampler's host-state sources and launch it (no-op when
        # disabled); binding also hands the engine its dispatch clock
        self.vitals.bind(
            engine=engine, batcher=self.batcher, log=log,
            state_dump_fn=self.state_dump,
        ).start()
        # preemption-aware SLO burn (ROADMAP §5 follow-on): the batcher's
        # deadline shed and preemption victim policy consult the
        # SLOTracker's burn rate — a replica already burning its error
        # budget sheds earlier and evicts the cheapest-to-redo victim
        if self.vitals.slo is not None and hasattr(self.batcher, "slo_burn"):
            self.batcher.slo_burn = self.vitals.slo.max_burn
        # stable process identity (the PR 9 site/pid/host clamp, shared
        # with StructuredLog so log lines, traces, and /debug/state all
        # carry ONE identity a fleet join can key on)
        self.identity = (
            dict(log._identity) if log is not None else {
                "site": default_site(),
                "pid": os.getpid(),
                "host": sanitize_site(socket.gethostname() or "localhost"),
            }
        )
        if hasattr(self.batcher, "checkpoint_site"):
            # exported checkpoints carry this replica's identity — the
            # `migrated_from` the resuming replica logs
            self.batcher.checkpoint_site = self.identity["site"]
        try:
            self._httpd = _Server((host, port), self)
        except OSError:
            # bind failure (port in use, bad host): don't leak the batcher
            # worker thread, the vitals sampler, or the exporter shipper
            # just started above
            self.vitals.stop()
            self.batcher.shutdown(drain=False)
            if self.exporter is not None:
                self.exporter.stop(final_flush=False)
            raise
        self._thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self._serving = False
        self._closed = False
        self._draining = False
        # reversible admin drain (POST /admin/drain): intake refused,
        # /healthz 503, in-flight work completes — distinct from the
        # terminal shutdown drain above
        self._intake_paused = False
        self._started_at = time.time()
        self._seed_lock = threading.Lock()
        self._seed_counter = int(time.time()) & 0x7FFFFFFF

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def next_seed(self, n: int) -> int:
        """Allocate n consecutive seeds for a request that didn't pin one."""
        with self._seed_lock:
            s = self._seed_counter
            self._seed_counter = (self._seed_counter + n) & 0x7FFFFFFF
            return s

    def count_quarantined(self) -> None:
        self._m_quarantined.inc()

    def count_stream_event(self, etype: str) -> None:
        """Terminal/open events are minted by handler threads; the
        chunk-boundary progress/preview counts come from the batcher
        worker at emit time — one `stream_events_total` family covers
        both sides (absent on the micro batcher, where streaming is a
        400 and this is a no-op)."""
        fam = getattr(self.batcher, "_m_stream_events", None)
        if fam is not None:
            fam.labels(etype).inc()

    def log_request(self, trace, outcome: str, status: int,
                    latency_ms: float, **fields) -> None:
        """One structured JSON line per completed request (no-op without
        an attached StructuredLog, or with log_requests=False). The stage
        breakdown comes from the request's finished trace; empty when
        tracing is off."""
        if self.log is None or not self.log_requests:
            return
        self.log.request(
            trace_id=trace.trace_id,
            outcome=outcome,
            status=status,
            latency_ms=latency_ms,
            stages=trace.stage_seconds(),
            **fields,
        )

    # how long a failed flush keeps /healthz at 503. Time-decayed rather
    # than cleared-on-success only: a health-gated router pulls traffic on
    # 503, which would starve the server of the successful batch it needs
    # to clear the error — latching it unhealthy forever.
    error_window_s: float = 60.0

    @property
    def intake_paused(self) -> bool:
        return self._intake_paused

    def drain_status(self) -> dict:
        """Drain progress off the batcher's drain hooks — what a rolling
        restart polls while waiting for this replica to quiesce."""
        return {
            "draining": self._intake_paused or self._draining,
            "inflight_rows": self.batcher.inflight_rows,
            "queue_depth_rows": self.batcher.queue_depth_rows,
            "quiesced": self.batcher.quiesced,
        }

    def drain_intake(self, migrate: bool = False) -> dict:
        """POST /admin/drain: reversibly stop admissions (503 to new
        /generate, 503 `"draining"` on /healthz) while in-flight rows run
        to completion. The process stays up — `shutdown()` remains the
        terminal path.

        `?migrate=1` additionally exports every queued + in-flight
        request as a decode-state checkpoint at the next chunk boundary:
        each request's waiting client gets a 409 carrying its checkpoint
        (the router re-dispatches it as a resume), and the full bundle
        rides this response too for pull-based orchestration — the drain
        finishes in one chunk instead of one full decode."""
        self._intake_paused = True
        out = self.drain_status()
        if migrate:
            export = getattr(self.batcher, "migrate_out", None)
            if export is None:
                out["migrate"] = {
                    "supported": False,
                    "note": "micro engine holds no resumable decode "
                    "state; drain waits out the in-flight batch",
                }
            else:
                cps = export(timeout_s=30.0)
                if cps is None:
                    out["migrate"] = {
                        "supported": True, "timeout": True,
                        "note": "worker never reached a chunk boundary; "
                        "nothing was exported",
                    }
                else:
                    bundle = {}
                    for cp in cps:
                        key = cp.request_key or f"anon-{len(bundle)}"
                        bundle[key] = to_wire(
                            cp.encoded or encode_checkpoint(
                                cp, self.resume_fingerprint
                            )
                        )
                    out["migrate"] = {
                        "supported": True,
                        "migrated": len(cps),
                        "fingerprint": self.resume_fingerprint,
                        "checkpoints": bundle,
                    }
            out.update(self.drain_status())
        if self.log is not None:
            self.log.event(
                "drain_intake", migrate=migrate,
                migrated=(out.get("migrate") or {}).get("migrated"),
                **self.drain_status(),
            )
        return out

    def undrain_intake(self) -> dict:
        """POST /admin/undrain: resume admissions after a drain."""
        self._intake_paused = False
        if self.log is not None:
            self.log.event("undrain_intake")
        return self.drain_status()

    def checkpoints_snapshot(self) -> dict:
        """GET /admin/checkpoints: non-destructive chunk-boundary export
        of every in-flight request's decode state (requests keep
        decoding here). Falls back to the last crash-beacon bundle when
        the worker cannot reach a boundary (wedged engine) — stale
        progress beats none for a pull-based drain."""
        peek = getattr(self.batcher, "peek_checkpoints", None)
        if peek is None:
            return {
                "checkpoints": {},
                "note": "micro engine holds no resumable decode state",
            }
        cps = peek(timeout_s=10.0)
        if cps is None:
            beacon = getattr(self.batcher, "last_beacon", None) or {}
            return {
                "stale": True,
                "note": "worker never reached a chunk boundary; "
                "serving the last beacon bundle",
                "checkpoints": beacon.get("checkpoints", {}),
                "beacon_ts": beacon.get("ts"),
                "fingerprint": self.resume_fingerprint,
            }
        bundle = {}
        for cp in cps:
            key = cp.request_key or f"anon-{len(bundle)}"
            bundle[key] = to_wire(
                encode_checkpoint(cp, self.resume_fingerprint)
            )
        return {
            "checkpoints": bundle,
            "count": len(bundle),
            "fingerprint": self.resume_fingerprint,
        }

    def validate_resume(self, wire: str, specs):
        """Decode + validate one wire checkpoint against this build and
        THIS request. Returns (RequestCheckpoint, bytes) on acceptance,
        (None, None) on any rejection — every reject is counted by
        reason and logged, and the caller serves the request from a
        clean position-0 start (never an error, never a corrupt
        resume)."""
        def reject(reason: str, detail: str):
            self._m_resume_rejects.labels(reason).inc()
            if self.log is not None:
                self.log.event(
                    "resume_rejected", reason=reason, detail=detail
                )
            return None, None

        try:
            blob = from_wire(wire)
            cp = decode_checkpoint(blob, self.resume_fingerprint)
        except CheckpointMismatch as exc:
            return reject("mismatch", str(exc))
        except CheckpointCorrupt as exc:
            return reject("corrupt", str(exc))
        if len(cp.rows) != len(specs):
            return reject(
                "inconsistent",
                f"{len(cp.rows)} checkpoint rows != {len(specs)} "
                "request rows",
            )
        image_seq_len = getattr(self.engine, "image_seq_len", None)
        seen = set()
        for row in cp.rows:
            i = int(row.row_index)
            if not 0 <= i < len(specs) or i in seen:
                return reject("inconsistent", f"bad row index {i}")
            seen.add(i)
            spec = specs[i]
            if not np.array_equal(
                np.asarray(row.prompt_ids, np.int32),
                np.asarray(spec.text_ids, np.int32),
            ):
                return reject(
                    "inconsistent", f"row {i} prompt differs from request"
                )
            if int(row.seed) != int(spec.seed) or (
                float(row.temperature) != float(spec.temperature)
                or float(row.top_k) != float(spec.top_k)
            ):
                # different sampling identity would NOT regenerate the
                # checkpointed prefix — resuming it would splice two
                # decodes together
                return reject(
                    "inconsistent",
                    f"row {i} sampling params differ from request",
                )
            if image_seq_len is not None:
                n = len(row.tokens)
                if row.done and n != int(image_seq_len):
                    return reject(
                        "inconsistent",
                        f"done row {i} has {n} tokens, expected "
                        f"{image_seq_len}",
                    )
                if not row.done and n >= int(image_seq_len):
                    return reject(
                        "inconsistent",
                        f"partial row {i} claims {n} tokens",
                    )
        return cp, len(blob)

    def health(self):
        # snapshot once: the batcher worker can set/clear the error fields
        # concurrently with this probe
        err = self.batcher.last_error
        err_age = self.batcher.error_age_s()
        erroring = err_age is not None and err_age < self.error_window_s
        draining = self._draining or self._intake_paused
        healthy = not draining and not erroring
        # the degraded tier sits BETWEEN ok and 503: the replica still
        # serves (200 — a health-gated router must not pull it), but a
        # recent watchdog stall or a burning SLO budget says "shed load /
        # investigate". Hard failures (draining, engine errors) stay 503.
        status = "ok" if healthy else "unhealthy"
        degraded_reasons = []
        if healthy:
            degraded_reasons = self.vitals.degraded_reasons()
            if degraded_reasons:
                status = "degraded"
        detail = {
            "status": status,
            "uptime_s": round(time.time() - self._started_at, 1),
            "queue_depth_rows": self.batcher.queue_depth_rows,
            "compiled_shapes": list(self.engine.stats.compiled_shapes),
            "batch_shapes": list(self.engine.batch_shapes),
        }
        # machine-readable work accounting for the fleet scraper's
        # capacity/goodput model: warmup work done, the token geometry
        # that converts batches to tokens, and the lifetime decode
        # counters (also on /metrics — repeated here so one /healthz
        # poll carries the whole capacity input)
        work = {
            "warmup_batches": int(
                getattr(self.engine.stats, "warmup_batches", 0) or 0
            ),
            "image_seq_len": int(
                getattr(self.engine, "image_seq_len", 0) or 0
            ),
            "max_batch": int(getattr(self.engine, "max_batch", 0) or 0),
        }
        for key, name in (
            ("decoded_tokens", "dalle_serving_decoded_tokens_total"),
            ("resumed_tokens", "dalle_serving_resumed_tokens_total"),
        ):
            counter = self.registry.get(name)
            if counter is not None and hasattr(counter, "value"):
                work[key] = int(counter.value)
        detail["work"] = work
        if degraded_reasons:
            detail["degraded_reasons"] = degraded_reasons
        if self.vitals.slo is not None:
            detail["slo"] = self.vitals.slo.status()
        if isinstance(self.batcher, ContinuousBatcher):
            detail["engine"] = "continuous"
            detail["slots_active"] = self.batcher.allocator.n_active
            detail["chunk_tokens"] = self.engine.chunk_tokens
            detail["qos"] = self.qos_detail()
            # streaming block: live SSE streams + lifetime open/re-attach
            # counts, with the oldest few streams' snapshots
            detail["streaming"] = dict(
                self.streams.detail(),
                preview_every=self.batcher.preview_every,
            )
            kv_detail = getattr(self.engine, "kv_detail", None)
            if kv_detail is not None:
                # paged engine: block-pool occupancy + prefix-cache size,
                # the new resource axis a capacity dashboard needs
                detail["kv"] = kv_detail()
            mesh_detail = getattr(self.engine, "mesh_detail", None)
            if mesh_detail is not None:
                # sharded engine: axis names/sizes + per-device buffer
                # bytes, so a probe (and the watchdog's stall dump, which
                # rides engine.state_dump) names the sick shard
                detail["mesh"] = mesh_detail()
            sparsity_detail = getattr(self.engine, "sparsity_detail", None)
            if sparsity_detail is not None:
                # block-sparse decode (--decode_sparsity policy): tile
                # width/static dead fraction + the lifetime read/skipped
                # tile counters (None on causal boots — block omitted)
                sp = sparsity_detail()
                if sp is not None:
                    detail["sparsity"] = sp
        if err is not None:
            detail["last_error"] = repr(err)
            if err_age is not None:
                detail["last_error_age_s"] = round(err_age, 1)
        if draining:
            detail["draining"] = True
            detail["drain"] = self.drain_status()
        return healthy, detail

    def state_dump(self) -> dict:
        """Full engine-state dump for `GET /debug/state` and the
        watchdog's `stall` events: engine internals (slot/page tables,
        refcounts), queue summary with in-flight trace IDs, recent
        compile events, and the worker threads' Python stacks. Host-side
        reads only — safe to call while the engine is wedged, which is
        precisely when it matters."""
        dump = {
            "ts": round(time.time(), 3),
            "uptime_s": round(time.time() - self._started_at, 1),
            "draining": self._draining or self._intake_paused,
            # stable replica identity (site/pid/host, the PR 9 clamp):
            # a fleet postmortem joins this dump against log lines and
            # collector traces without guessing which process wrote it
            "identity": self.identity,
        }
        engine_dump = getattr(self.engine, "state_dump", None)
        dump["engine"] = (
            engine_dump() if engine_dump is not None
            else {"engine": type(self.engine).__name__}
        )
        summary = getattr(self.batcher, "state_summary", None)
        dump["batcher"] = summary() if summary is not None else {}
        dump["recent_compiles"] = compile_guard.recent_events()
        dump["worker_stacks"] = thread_stacks("batcher")
        if self.exporter is not None:
            # fleet-export health rides the postmortem dump: "did this
            # replica's traces actually reach the collector" is the first
            # question a cross-host stall investigation asks
            dump["trace_export"] = self.exporter.detail()
        if self.spool is not None:
            dump["checkpoint_spool"] = self.spool.detail()
        return dump

    def qos_detail(self) -> dict:
        """Overload-behavior snapshot for /healthz: per-class queue
        depth plus the preempt/resume/shed lifetime tallies — the first
        numbers an overload investigation asks for."""
        out: dict = {
            "queue_by_class": self.batcher.class_depths(),
            "preempt_enabled": getattr(self.batcher, "preempt", False),
            "deadline_shed": getattr(self.batcher, "deadline_shed", False),
        }
        for key, metric in (
            ("preemptions", "dalle_serving_preemptions_total"),
            ("resumptions", "dalle_serving_resumptions_total"),
            ("shed", "dalle_serving_shed_total"),
        ):
            fam = self.registry.get(metric)
            if fam is not None:
                out[key] = {
                    label: int(child.value) for label, child in fam.items()
                }
        retries = self.registry.get("dalle_serving_dispatch_retries_total")
        if retries is not None:
            out["dispatch_retries"] = int(retries.value)
        return out

    def admission_context(self) -> dict:
        """Submit-time load context stamped onto every request log line
        (`queue_depth_rows`, `slots_active`, `blocks_free` where the
        engine has them) so an overload postmortem reads off the log
        instead of correlating against the vitals ring."""
        ctx = {"queue_depth_rows": self.batcher.queue_depth_rows}
        alloc = getattr(self.batcher, "allocator", None)
        if alloc is not None:
            ctx["slots_active"] = alloc.n_active
        kv = getattr(self.engine, "kv", None)
        if kv is not None:
            ctx["blocks_free"] = kv.blocks_free
        return ctx

    def start(self) -> "ServingServer":
        assert self._thread is None, "already started"
        with self._state_lock:
            assert not self._closed, "server already shut down"
            self._serving = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="dalle-serving-http",
            daemon=True,
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        """Foreground variant for the CLI: blocks until shutdown().

        Returns immediately if shutdown() already ran (e.g. a SIGTERM
        delivered during startup) instead of serving a closed socket.
        """
        assert self._thread is None, "already started in background"
        with self._state_lock:
            if self._closed:
                return
            self._serving = True
        self._httpd.serve_forever(poll_interval=0.05)

    def _dump_traces(self) -> None:
        if not self.trace_dump_path or self._trace_dumped:
            return
        self._trace_dumped = True
        try:
            out = self.tracer.dump(self.trace_dump_path)
            if self.log is not None:
                self.log.event(
                    "trace_dump", path=str(out),
                    traces=len(self.tracer.recent()),
                )
        except OSError as exc:  # a bad path must not block shutdown
            if self.log is not None:
                self.log.event("trace_dump_failed", error=repr(exc))

    def shutdown(self, drain: bool = True) -> None:
        self._draining = True
        self.vitals.stop()
        self.batcher.shutdown(drain=drain)
        with self._state_lock:
            first_close = not self._closed
            self._closed = True
            serving = self._serving
        if serving:
            # socketserver's shutdown() waits on an event only serve_forever
            # sets; calling it on a never-served listener blocks forever.
            # (A serve loop that committed under _state_lock but hasn't
            # entered yet still exits promptly: its shutdown-request flag is
            # already set when the loop starts.)
            self._httpd.shutdown()
            self._serving = False
        if first_close:
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        # dump LAST — after the queue drained, the listener stopped, and
        # the serve thread joined — so requests that were mid-respond when
        # shutdown began have had their handler finish() the trace into
        # the ring. (A handler thread still encoding a huge payload at
        # this instant is best-effort: the dump won't wait for it.)
        self._dump_traces()
        if self.exporter is not None and first_close:
            # same ordering logic as the dump: every finished trace is in
            # the buffer by now; stop() makes one final best-effort flush
            # (bounded by the POST timeout, so a dead collector cannot
            # wedge shutdown)
            self.exporter.stop()
            if self.log is not None:
                self.log.event("trace_export_stopped", **self.exporter.detail())
        if first_close and self.log is not None:
            self.log.event("shutdown", drain=drain)
