"""Dynamic micro-batching over a bounded request queue.

The throughput/latency knob of the serving layer: requests from many
concurrent clients accumulate in a bounded queue; a single worker thread
flushes a micro-batch to the engine when EITHER `max_batch` rows are
waiting OR the oldest request has waited `max_delay_ms` — the classic
deadline-or-capacity policy (cf. Vortex/TF-Serving style batchers,
PAPERS.md). One worker means one in-flight sampler dispatch, which is the
right shape for a single accelerator: overlapping dispatches would just
queue inside the backend anyway.

Overload handling is explicit, never implicit:
  * queue full  -> `submit` raises `QueueFullError` immediately
    (backpressure; the HTTP layer maps it to 503 + Retry-After);
  * too old     -> requests that waited past their timeout are failed
    with `RequestTimeout` when they reach the head of the queue, not
    silently dropped;
  * cancelled   -> client-abandoned requests are skipped without costing
    a batch row;
  * engine error-> every request in the failed batch gets the exception
    (fail fast; no wedged clients), and the error is surfaced through
    `last_error` for /healthz;
  * shutdown    -> `shutdown(drain=True)` stops intake, flushes what is
    queued, then joins the worker; `drain=False` fails the queue.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence

from dalle_pytorch_tpu.serving.engine import SampleSpec


class QueueFullError(RuntimeError):
    """Bounded queue is at capacity — reject, don't buffer unboundedly."""


class RequestTimeout(RuntimeError):
    """Request spent longer than its timeout queued or in flight."""


class RequestCancelled(RuntimeError):
    """Request was cancelled by the client before execution."""


class ShuttingDownError(RuntimeError):
    """Batcher no longer accepts work."""


class _Future:
    """Minimal thread-safe one-shot result slot.

    Deliberately NOT concurrent.futures.Future: our cancellation is
    queue-level (`GenRequest.cancel` sets a flag; the WORKER later resolves
    the future with `RequestCancelled` when it pops the request), and a
    stdlib Future that has been `.cancel()`ed raises InvalidStateError on
    that late `set_exception` — exactly our flow.
    """

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exception: Optional[BaseException] = None

    def set_result(self, result) -> None:
        self._result = result
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exception = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise RequestTimeout("timed out waiting for generation result")
        if self._exception is not None:
            raise self._exception
        return self._result


class GenRequest:
    """One client request: `rows` batch rows that must stay together
    (e.g. num_images samples of one prompt), flushed in a single batch so
    the result arrives whole."""

    def __init__(self, specs: Sequence[SampleSpec], timeout_s: float = 120.0):
        assert specs, "request needs at least one sample row"
        self.specs: List[SampleSpec] = list(specs)
        self.timeout_s = float(timeout_s)
        self.enqueued_at = time.monotonic()
        self.future = _Future()
        self._cancelled = threading.Event()

    @property
    def rows(self) -> int:
        return len(self.specs)

    def cancel(self) -> None:
        """Best-effort: a request already handed to the engine completes."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def expired(self, now: float) -> bool:
        return now - self.enqueued_at > self.timeout_s


class MicroBatcher:
    def __init__(
        self,
        engine,
        max_batch: Optional[int] = None,
        max_delay_ms: float = 25.0,
        max_queue_rows: int = 64,
        registry=None,
        name: str = "dalle_serving",
    ):
        """`engine` needs `.generate(list[SampleSpec]) -> (tokens, pixels)`
        and (unless `max_batch` is given) a `.max_batch` attribute — the
        tests drive a fake with exactly that surface."""
        self.engine = engine
        self.max_batch = int(max_batch or engine.max_batch)
        assert self.max_batch >= 1
        engine_cap = getattr(engine, "max_batch", None)
        assert engine_cap is None or self.max_batch <= engine_cap, (
            f"max_batch={self.max_batch} exceeds the engine's largest "
            f"compiled shape {engine_cap}; every flush would fail"
        )
        assert int(max_queue_rows) >= self.max_batch, (
            f"max_queue_rows={max_queue_rows} < max_batch={self.max_batch}: "
            "a full-size request could never even enqueue"
        )
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows)
        self._cond = threading.Condition()
        self._pending: deque = deque()
        self._pending_rows = 0
        self._closed = False
        self._drain = True
        self.last_error: Optional[BaseException] = None
        self._last_error_at: Optional[float] = None

        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        p = name
        self._m_depth = registry.gauge(
            f"{p}_queue_depth_rows", "request rows waiting in the batcher queue"
        )
        self._m_rejected = registry.counter(
            f"{p}_rejected_total", "requests rejected because the queue was full"
        )
        self._m_timeouts = registry.counter(
            f"{p}_timeouts_total", "requests failed by per-request timeout"
        )
        self._m_cancelled = registry.counter(
            f"{p}_cancelled_total", "requests cancelled before execution"
        )
        self._m_errors = registry.counter(
            f"{p}_engine_errors_total", "batches failed by an engine exception"
        )
        self._m_requests = registry.counter(
            f"{p}_requests_total", "requests accepted into the queue"
        )
        self._m_images = registry.counter(
            f"{p}_images_total", "images generated (batch rows completed)"
        )
        self._m_batches = registry.counter(
            f"{p}_batches_total", "micro-batches flushed to the engine"
        )
        # one bucket per occupancy up to a render-size cap; bigger batches
        # land in +Inf (the _sum/_count ratio still shows mean occupancy)
        self._m_occupancy = registry.histogram(
            f"{p}_batch_occupancy_rows",
            "real (unpadded) rows per flushed micro-batch",
            buckets=tuple(float(b) for b in range(1, min(self.max_batch, 32) + 1)),
        )
        self._m_latency = registry.histogram(
            f"{p}_request_latency_seconds",
            "enqueue-to-result latency per request",
        )
        self._m_batch_seconds = registry.histogram(
            f"{p}_batch_seconds", "engine wall time per flushed micro-batch"
        )

        self._worker = threading.Thread(
            target=self._run, name=f"{name}-batcher", daemon=True
        )
        self._worker.start()

    # -------------------------------------------------------------- intake

    def submit(
        self, specs: Sequence[SampleSpec], timeout_s: float = 120.0
    ) -> GenRequest:
        """Enqueue one request; returns it (result via `req.future.result()`).

        Raises `QueueFullError` (backpressure) or `ShuttingDownError`
        immediately instead of blocking the caller.
        """
        req = GenRequest(specs, timeout_s=timeout_s)
        with self._cond:
            if self._closed:
                raise ShuttingDownError("batcher is shutting down")
            if req.rows > self.max_batch:
                self._m_rejected.inc()
                raise QueueFullError(
                    f"request of {req.rows} rows exceeds max batch "
                    f"{self.max_batch}"
                )
            if self._pending_rows + req.rows > self.max_queue_rows:
                self._m_rejected.inc()
                raise QueueFullError(
                    f"queue full ({self._pending_rows}/{self.max_queue_rows} rows)"
                )
            self._pending.append(req)
            self._pending_rows += req.rows
            self._m_requests.inc()
            self._m_depth.set(self._pending_rows)
            self._cond.notify_all()
        return req

    @property
    def queue_depth_rows(self) -> int:
        return self._pending_rows

    def error_age_s(self) -> Optional[float]:
        """Seconds since the most recent failed flush; None if the last
        flush succeeded (or none has failed yet). Lets health checks decay
        a transient error instead of latching unhealthy — a health-gated
        router that pulls traffic on 503 would otherwise starve the server
        of the successful batch it needs to clear `last_error`."""
        if self.last_error is None or self._last_error_at is None:
            return None
        return time.monotonic() - self._last_error_at

    # -------------------------------------------------------------- worker

    def _pop_ready(self, batch: List[GenRequest]) -> None:
        """Move queued requests into `batch` (capacity permitting), failing
        expired ones and skipping cancelled ones. Caller holds the lock."""
        now = time.monotonic()
        rows = sum(r.rows for r in batch)
        while self._pending:
            head = self._pending[0]
            if head.cancelled:
                self._pending.popleft()
                self._pending_rows -= head.rows
                self._m_cancelled.inc()
                head.future.set_exception(RequestCancelled("cancelled"))
                continue
            if head.expired(now):
                self._pending.popleft()
                self._pending_rows -= head.rows
                self._m_timeouts.inc()
                head.future.set_exception(
                    RequestTimeout(
                        f"spent >{head.timeout_s:.1f}s queued; overloaded?"
                    )
                )
                continue
            if rows + head.rows > self.max_batch:
                break
            self._pending.popleft()
            self._pending_rows -= head.rows
            rows += head.rows
            batch.append(head)
        self._m_depth.set(self._pending_rows)

    def _assemble(self) -> Optional[List[GenRequest]]:
        """Block until a batch is ready (deadline-or-capacity), or None at
        shutdown with nothing left to drain."""
        with self._cond:
            while not self._pending:
                if self._closed:
                    return None
                self._cond.wait(timeout=0.05)
            batch: List[GenRequest] = []
            self._pop_ready(batch)
            if not batch:  # everything queued was expired/cancelled
                return []
            # deadline anchored at the OLDEST accepted request's arrival
            deadline = batch[0].enqueued_at + self.max_delay_s
            while sum(r.rows for r in batch) < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(remaining, 0.05))
                self._pop_ready(batch)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._assemble()
            if batch is None:
                return
            if not batch:
                continue
            self._flush(batch)

    def _flush(self, batch: List[GenRequest]) -> None:
        specs: List[SampleSpec] = []
        for req in batch:
            specs.extend(req.specs)
        t0 = time.monotonic()
        try:
            tokens, pixels = self.engine.generate(specs)
        except Exception as exc:  # fail fast: every waiter gets the error
            # timestamp first: readers check last_error then error_age_s
            self._last_error_at = time.monotonic()
            self.last_error = exc
            self._m_errors.inc()
            for req in batch:
                req.future.set_exception(exc)
            return
        self.last_error = None  # engine recovered: let /healthz go green again
        # counted on success only, so batches/occupancy/images/batch_seconds
        # stay mutually consistent (failures are engine_errors_total)
        self._m_batches.inc()
        self._m_occupancy.observe(len(specs))
        self._m_batch_seconds.observe(time.monotonic() - t0)
        offset = 0
        now = time.monotonic()
        for req in batch:
            toks = tokens[offset : offset + req.rows]
            pix = None if pixels is None else pixels[offset : offset + req.rows]
            offset += req.rows
            self._m_images.inc(req.rows)
            self._m_latency.observe(now - req.enqueued_at)
            req.future.set_result((toks, pix))

    # ------------------------------------------------------------ shutdown

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake; `drain=True` flushes queued requests first,
        `drain=False` fails them with `ShuttingDownError`."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._pending:
                    req = self._pending.popleft()
                    req.future.set_exception(
                        ShuttingDownError("server shutting down")
                    )
                self._pending_rows = 0
                self._m_depth.set(0)
            self._cond.notify_all()
        self._worker.join(timeout=timeout)
