"""Dynamic micro-batching over a bounded request queue.

The throughput/latency knob of the serving layer: requests from many
concurrent clients accumulate in a bounded queue; a single worker thread
flushes a micro-batch to the engine when EITHER `max_batch` rows are
waiting OR the oldest request has waited `max_delay_ms` — the classic
deadline-or-capacity policy (cf. Vortex/TF-Serving style batchers,
PAPERS.md). One worker means one in-flight sampler dispatch, which is the
right shape for a single accelerator: overlapping dispatches would just
queue inside the backend anyway.

Overload handling is explicit, never implicit:
  * queue full  -> `submit` raises `QueueFullError` immediately
    (backpressure; the HTTP layer maps it to 503 + Retry-After derived
    from the measured chunk-wall EMA and queue depth);
  * over quota  -> a tenant past `tenant_quota_rows` queued rows gets
    `TenantQuotaError` (429 at the HTTP layer);
  * unmeetable  -> with `deadline_shed`, a request whose estimated
    completion time already exceeds its own timeout is rejected at
    admission with `ShedError` (503 + Retry-After) instead of being
    queued to a certain 504;
  * too old     -> requests that waited past their timeout are failed
    with `RequestTimeout` when they reach the head of the queue, not
    silently dropped — and (continuous engine) a request whose deadline
    passes or that is cancelled MID-DECODE is retired at the next chunk
    boundary, releasing its slot instead of squatting to completion;
  * cancelled   -> client-abandoned requests are skipped without costing
    a batch row;
  * engine error-> (continuous) the inflight set gets ONE bounded retry:
    the donated-state rebuild left a clean engine, so every live request
    is suspended and re-admitted from scratch (bit-identical tokens —
    decode RNG is (seed, position)-keyed); a request whose retry budget
    is spent gets the exception. Micro-batches keep fail-fast: every
    request in the failed batch gets the exception. Either way the error
    surfaces through `last_error` for /healthz;
  * overloaded  -> (continuous) PRIORITY PREEMPTION: when the scheduler's
    chosen head is blocked on slots/pages and a strictly-lower-class
    request is decoding, the youngest such victim is released at the
    chunk boundary and re-queued at the front of its own class — the
    paged engine's prefix cache makes its eventual re-prefill near-free,
    and restarting decode at position 0 regenerates the SAME tokens, so
    preemption costs latency, never correctness;
  * shutdown    -> `shutdown(drain=True)` stops intake, flushes what is
    queued, then joins the worker; `drain=False` fails the queue.

Intake order is not FIFO but weighted-fair over priority classes with
per-tenant accounting (`serving/qos.py:WeightedFairQueue`): a tenant
flooding the low class cannot starve other tenants or classes, and the
low class's admission share is bounded below (no outright starvation).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from dalle_pytorch_tpu.obs.tracing import NULL_SPAN, NULL_TRACE
from dalle_pytorch_tpu.serving.engine import SampleSpec
from dalle_pytorch_tpu.serving.qos import (
    ShedError,
    TenantQuotaError,
    WeightedFairQueue,
    priority_class,
)


class QueueFullError(RuntimeError):
    """Bounded queue is at capacity — reject, don't buffer unboundedly.
    `retry_after_s` carries the batcher's drain estimate for the HTTP
    Retry-After header (None when the estimate has no basis yet)."""

    retry_after_s: Optional[float] = None


class RequestTimeout(RuntimeError):
    """Request spent longer than its timeout queued or in flight."""


class RequestCancelled(RuntimeError):
    """Request was cancelled by the client before execution."""


class ShuttingDownError(RuntimeError):
    """Batcher no longer accepts work."""


class _Future:
    """Minimal thread-safe one-shot result slot.

    Deliberately NOT concurrent.futures.Future: our cancellation is
    queue-level (`GenRequest.cancel` sets a flag; the WORKER later resolves
    the future with `RequestCancelled` when it pops the request), and a
    stdlib Future that has been `.cancel()`ed raises InvalidStateError on
    that late `set_exception` — exactly our flow.
    """

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exception: Optional[BaseException] = None
        self._callbacks: List = []

    def add_done_callback(self, fn) -> None:
        """Run `fn()` once the future resolves (immediately if it
        already has). The SSE layer uses this to nudge a stream reader
        blocked on its event condition, whatever path resolved the
        future (retire, reap, recover, migrate). Callbacks must be
        idempotent and non-blocking; a late concurrent add may fire
        twice."""
        self._callbacks.append(fn)
        if self._event.is_set():
            fn()

    def _notify(self) -> None:
        for fn in list(self._callbacks):
            try:
                fn()
            except Exception:
                pass

    def set_result(self, result) -> None:
        self._result = result
        self._event.set()
        self._notify()

    def set_exception(self, exc: BaseException) -> None:
        self._exception = exc
        self._event.set()
        self._notify()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise RequestTimeout("timed out waiting for generation result")
        if self._exception is not None:
            raise self._exception
        return self._result


class GenRequest:
    """One client request: `rows` batch rows that must stay together
    (e.g. num_images samples of one prompt), flushed in a single batch so
    the result arrives whole."""

    def __init__(
        self,
        specs: Sequence[SampleSpec],
        timeout_s: float = 120.0,
        trace=NULL_TRACE,
        priority: str = "normal",
        tenant: str = "",
    ):
        assert specs, "request needs at least one sample row"
        self.specs: List[SampleSpec] = list(specs)
        self.timeout_s = float(timeout_s)
        self.enqueued_at = time.monotonic()
        self.future = _Future()
        self._cancelled = threading.Event()
        # QoS identity: priority class drives the weighted-fair scheduler,
        # tenant drives per-tenant fairness/quotas (qos.py). Validation
        # raises ValueError here, which the HTTP layer maps to 400.
        self.priority = str(priority)
        self.klass = priority_class(self.priority)
        self.tenant = str(tenant or "")
        # ------------- suspension state (preemption / dispatch retry) ---
        # rows harvested COMPLETE before a suspension, kept host-side so a
        # resumed request only re-decodes its unfinished rows
        self.resume_tokens: Dict[int, np.ndarray] = {}
        # generated-so-far tokens per row at the last preemption, clipped
        # to the row's decode position — observability, and the material
        # for the resume bit-identity pin (a resumed row's final tokens
        # must start with exactly this prefix)
        self.preempt_snapshots: Dict[int, np.ndarray] = {}
        self.preemptions = 0
        self.dispatch_retries = 0
        #: incident ids of the CONSECUTIVE failed engine dispatches this
        #: request was in flight for (a successful chunk dispatch clears
        #: the streak) — the batcher-side half of poison-request
        #: attribution (the HTTP layer maps a request that died with
        #: `quarantine_after`+ incidents to a terminal 422 carrying them)
        self.incidents: List[str] = []
        #: admission order stamp (continuous batcher) — the preemption
        #: victim policy releases the YOUNGEST lower-class request
        self.admitted_seq: Optional[int] = None
        self._preempt_span = NULL_SPAN
        self._suspend_reason: Optional[str] = None
        # ------------------------- migration identity (serving/migrate.py)
        #: router content key (x-dalle-request-key) — the join key for
        #: crash-spool recovery and fleet log attribution
        self.request_key: Optional[str] = None
        #: True when this request arrived with a decode-state resume
        #: checkpoint (the exporting replica's site, when known, rides
        #: `migrated_from`)
        self.migrated = False
        self.migrated_from: Optional[str] = None
        self.resumed_at_chunk: Optional[int] = None
        self.checkpoint_bytes: Optional[int] = None
        self._migrate_counted = False
        # request-scoped trace (obs/tracing.py), minted at HTTP ingress and
        # carried through the worker so stage spans land on one tree; the
        # default NULL_TRACE makes every span call a no-op for callers
        # (benches, tests) that don't trace
        self.trace = trace
        self._queue_span = trace.begin("queue", rows=len(self.specs))
        self._stage_span = NULL_SPAN  # current worker-side stage span
        # when the request's FIRST token existed on the host: the chunk
        # boundary after admission (continuous engine) or batch completion
        # (micro-batch engine — its tokens only materialize at scan end).
        # Benches read it for time-to-first-token percentiles.
        self.first_token_at: Optional[float] = None
        # True when EVERY row of this request admitted via the prefix
        # cache (paged engine, zero prefill dispatches); None when the
        # engine doesn't report admission stats. Rides into the structured
        # request log so per-request traces explain cheap vs full prefills.
        self.prefix_hit: Optional[bool] = None
        #: SSE event channel (serving/streaming.py RequestStream) when
        #: the client asked for a streamed response — the worker emits
        #: chunk-boundary progress/preview events onto it; None for
        #: ordinary request/response traffic
        self.stream = None

    @property
    def rows(self) -> int:
        return len(self.specs)

    @property
    def pending_rows(self) -> int:
        """Rows still to serve: total minus rows harvested complete before
        a suspension (the scheduler's and allocator's accounting unit)."""
        return len(self.specs) - len(self.resume_tokens)

    def pending_row_specs(self) -> List:
        """(row index, spec) for every row still to decode."""
        return [
            (i, s) for i, s in enumerate(self.specs)
            if i not in self.resume_tokens
        ]

    def apply_resume(self, checkpoint, nbytes: Optional[int] = None) -> None:
        """Install a decode-state checkpoint (serving/migrate.py
        `RequestCheckpoint`) as this request's resume state: completed
        rows restore verbatim into `resume_tokens` (never re-decoded),
        partial rows' snapshots land in `preempt_snapshots` (the
        bit-identity oracle — the row itself restarts at position 0,
        which regenerates the same tokens via the (seed, position)-keyed
        RNG). Caller has already validated the checkpoint against this
        request's specs/fingerprint."""
        for row in checkpoint.rows:
            i = int(row.row_index)
            if not 0 <= i < len(self.specs):
                continue
            toks = np.asarray(row.tokens, np.int32)
            if row.done:
                self.resume_tokens[i] = toks
            elif len(toks):
                self.preempt_snapshots[i] = toks
                # engines with resume support continue THIS row from its
                # checkpointed position (one teacher-forced re-prefill);
                # others ignore the fields and restart at 0
                self.specs[i].resume_tokens = toks
                self.specs[i].resume_pos = len(toks)
        self.migrated = True
        self.migrated_from = checkpoint.site
        self.resumed_at_chunk = int(checkpoint.chunk_index)
        self.checkpoint_bytes = nbytes

    def cancel(self) -> None:
        """Best-effort: a request already handed to the engine completes."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def expired(self, now: float) -> bool:
        return now - self.enqueued_at > self.timeout_s


def _unique_requests(reqs) -> List[GenRequest]:
    """First-seen-order dedup by identity (GenRequest hashes by identity;
    a multi-row request owns several slots but is one trace)."""
    return list(dict.fromkeys(reqs))


def _first_trace_id(reqs) -> Optional[str]:
    """Exemplar for a shared-dispatch observation: the first traced
    request's ID, or None when nothing in the group is traced."""
    for req in reqs:
        if req.trace:
            return req.trace.trace_id
    return None


class MicroBatcher:
    def __init__(
        self,
        engine,
        max_batch: Optional[int] = None,
        max_delay_ms: float = 25.0,
        max_queue_rows: int = 64,
        registry=None,
        name: str = "dalle_serving",
        tenant_quota_rows: Optional[int] = None,
        class_weights: Optional[dict] = None,
        tenant_weights: Optional[dict] = None,
        log=None,
    ):
        """`engine` needs `.generate(list[SampleSpec]) -> (tokens, pixels)`
        and (unless `max_batch` is given) a `.max_batch` attribute — the
        tests drive a fake with exactly that surface. `tenant_quota_rows`
        caps any one tenant's queued rows (429 past it; None = no quota);
        `class_weights` overrides qos.py's priority-class admission
        shares and `tenant_weights` sets proportional per-tenant shares
        within each class; `log` (a StructuredLog) receives
        dispatch-retry and preemption lifecycle events."""
        self.engine = engine
        # explicit None check: a caller passing a misconfigured 0 should
        # hit the assert below, not silently get the engine's cap
        self.max_batch = int(
            engine.max_batch if max_batch is None else max_batch
        )
        assert self.max_batch >= 1
        engine_cap = getattr(engine, "max_batch", None)
        assert engine_cap is None or self.max_batch <= engine_cap, (
            f"max_batch={self.max_batch} exceeds the engine's largest "
            f"compiled shape {engine_cap}; every flush would fail"
        )
        assert int(max_queue_rows) >= self.max_batch, (
            f"max_queue_rows={max_queue_rows} < max_batch={self.max_batch}: "
            "a full-size request could never even enqueue"
        )
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows)
        self.tenant_quota_rows = (
            None if tenant_quota_rows is None else int(tenant_quota_rows)
        )
        self.log = log
        self._cond = threading.Condition()
        # weighted-fair priority intake (qos.py) — with one class and one
        # tenant (the defaults) it degrades to exactly the old FIFO
        self._queue = WeightedFairQueue(class_weights, tenant_weights)
        #: rows currently inside an engine dispatch — the drain hook the
        #: replica's /admin/drain status and the fleet router's
        #: zero-error rolling restart read
        self._inflight_rows = 0
        self._closed = False
        self._drain = True
        self.last_error: Optional[BaseException] = None
        self._last_error_at: Optional[float] = None
        #: monotonically-numbered engine dispatch failures; every request
        #: in flight at failure time carries the incident id (poison
        #: attribution — `GenRequest.incidents`)
        self._incident_seq = 0

        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._name = name
        p = name
        self._m_depth = registry.gauge(
            f"{p}_queue_depth_rows", "request rows waiting in the batcher queue"
        )
        self._m_rejected = registry.counter(
            f"{p}_rejected_total", "requests rejected because the queue was full"
        )
        self._m_timeouts = registry.counter(
            f"{p}_timeouts_total", "requests failed by per-request timeout"
        )
        self._m_cancelled = registry.counter(
            f"{p}_cancelled_total", "requests cancelled before execution"
        )
        self._m_errors = registry.counter(
            f"{p}_engine_errors_total",
            "generation dispatches (flushed batches / slot chunks) failed "
            "by an engine exception",
        )
        self._m_requests = registry.counter(
            f"{p}_requests_total", "requests accepted into the queue"
        )
        self._m_images = registry.counter(
            f"{p}_images_total", "images generated (batch rows completed)"
        )
        self._m_latency = registry.histogram(
            f"{p}_request_latency_seconds",
            "enqueue-to-result latency per request",
        )
        self._m_depth_by_class = registry.gauge_family(
            f"{p}_queue_depth_rows_by_class",
            "request rows waiting in the batcher queue, by priority class",
            label_name="class",
        )
        self._m_shed = registry.counter_family(
            f"{p}_shed_total",
            "requests rejected at admission by the QoS layer, by reason "
            "(deadline: the cost model said the SLO was unmeetable; "
            "quota: the tenant was over its queued-rows quota)",
            label_name="reason",
        )
        self._m_retries = registry.counter(
            f"{p}_dispatch_retries_total",
            "inflight requests re-admitted after a failed continuous "
            "dispatch rebuilt the engine state (one bounded retry each)",
        )
        # per-stage wall time, labeled by stage — the aggregate view of the
        # span tracer's per-request breakdown, so /metrics and
        # /debug/traces agree on where the time went. Observed whether or
        # not tracing is on; exemplars carry the most recent trace ID when
        # it is (render(exemplars=True)).
        self.stage_seconds = registry.histogram_family(
            f"{p}_stage_seconds",
            "wall time per request stage (queue/prefill/chunk/harvest for "
            "the continuous engine; queue/generate for micro-batches; "
            "respond is observed by the HTTP layer)",
            label_name="stage",
        )

        self._post_init()  # batching-mode instruments + subclass state must
        self._worker = threading.Thread(  # exist before the worker runs
            target=self._run, name=f"{name}-batcher", daemon=True
        )
        self._worker.start()

    def _post_init(self) -> None:
        """Register the flush-path instruments. `ContinuousBatcher`
        overrides this with its slot-path instruments instead, so a
        continuous server's /metrics never exposes permanently-empty
        micro-batch series (an occupancy dashboard reading them would see
        'no batches ever flushed' against a busy server)."""
        registry, p = self.registry, self._name
        self._m_batches = registry.counter(
            f"{p}_batches_total", "micro-batches flushed to the engine"
        )
        # one bucket per occupancy up to a render-size cap; bigger batches
        # land in +Inf (the _sum/_count ratio still shows mean occupancy)
        self._m_occupancy = registry.histogram(
            f"{p}_batch_occupancy_rows",
            "real (unpadded) rows per flushed micro-batch",
            buckets=tuple(float(b) for b in range(1, min(self.max_batch, 32) + 1)),
        )
        self._m_batch_seconds = registry.histogram(
            f"{p}_batch_seconds", "engine wall time per flushed micro-batch"
        )
        # per-compiled-shape series: which rung served the batch and how
        # long it took there — occupancy-vs-shape is the padding-waste
        # dashboard (ROADMAP "/metrics per-shape occupancy labels")
        self._m_occupancy_by_shape = registry.histogram_family(
            f"{p}_batch_occupancy_rows_by_shape",
            "real rows per flushed micro-batch, by compiled batch shape",
            label_name="shape",
            buckets=tuple(float(b) for b in range(1, min(self.max_batch, 32) + 1)),
        )
        self._m_batch_seconds_by_shape = registry.histogram_family(
            f"{p}_batch_seconds_by_shape",
            "engine wall time per flushed micro-batch, by compiled batch shape",
            label_name="shape",
        )

    # -------------------------------------------------------------- intake

    def submit(
        self,
        specs: Sequence[SampleSpec],
        timeout_s: float = 120.0,
        trace=NULL_TRACE,
        priority: str = "normal",
        tenant: str = "",
        request_key: Optional[str] = None,
        resume=None,
        resume_bytes: Optional[int] = None,
        stream=None,
    ) -> GenRequest:
        """Enqueue one request; returns it (result via `req.future.result()`).

        Raises `QueueFullError` (backpressure), `TenantQuotaError` (the
        tenant is over its queued-rows quota), `ShedError` (deadline-
        aware admission shed) or `ShuttingDownError` immediately instead
        of blocking the caller. `trace` (a `Trace` from `obs/tracing.py`)
        rides on the request; the worker records stage spans onto it.
        `priority` ("high"/"normal"/"low") and `tenant` feed the
        weighted-fair scheduler. `resume` (a validated
        `migrate.RequestCheckpoint`) installs a migrated request's
        decode-state resume — it enters like a preempt-resume, at the
        FRONT of its own (class, tenant) queue, and every admission
        bound below charges only its PENDING rows (rows the checkpoint
        already completed occupy nothing). `stream` (a
        `streaming.RequestStream`) opts the request into chunk-boundary
        SSE events from the continuous worker.
        """
        req = GenRequest(
            specs, timeout_s=timeout_s, trace=trace,
            priority=priority, tenant=tenant,
        )
        req.request_key = request_key
        if stream is not None:
            req.stream = stream
            stream.request = req
            # whatever path resolves the future (retire/reap/recover/
            # migrate), the blocked SSE reader wakes to write the
            # terminal event instead of sleeping out its poll timeout
            req.future.add_done_callback(stream.wake)
        if resume is not None:
            req.apply_resume(resume, nbytes=resume_bytes)
        with self._cond:
            if self._closed:
                raise ShuttingDownError("batcher is shutting down")
            cap = self._admission_cap(req)
            if req.pending_rows > cap:
                # permanent: this request could NEVER admit (its class's
                # usable slots are max_batch minus any high-class
                # reserve), and all-or-nothing admission means queueing
                # it would head-of-line-block its class forever
                self._m_rejected.inc()
                raise QueueFullError(
                    f"request of {req.pending_rows} rows exceeds max batch "
                    f"{cap} admissible at priority {req.priority!r}"
                )
            can_ever = getattr(self.engine, "can_ever_admit", None)
            if can_ever is not None and not can_ever(
                [s for _, s in req.pending_row_specs()]
            ):
                # paged engine: the request's worst case exceeds the WHOLE
                # block pool — it would queue forever, so reject now
                self._m_rejected.inc()
                raise QueueFullError(
                    f"request of {req.pending_rows} rows exceeds the "
                    "engine's KV block pool capacity"
                )
            # class-horizon queue bound: a request competes only against
            # rows its class must wait behind (its own class and better),
            # so a low-class flood 503s ITSELF while high-class arrivals
            # still see room — overload rejections land on the class
            # causing them
            ahead = self._queue.rows_at_or_better(req.klass)
            if ahead + req.pending_rows > self.max_queue_rows:
                self._m_rejected.inc()
                exc = QueueFullError(
                    f"queue full ({ahead}/{self.max_queue_rows} rows at "
                    f"priority {req.priority!r} or better)"
                )
                exc.retry_after_s = self.retry_after_s()
                raise exc
            if self.tenant_quota_rows is not None and (
                self._queue.tenant_rows(req.tenant) + req.pending_rows
                > self.tenant_quota_rows
            ):
                self._m_shed.labels("quota").inc()
                raise TenantQuotaError(
                    f"tenant {req.tenant!r} already has "
                    f"{self._queue.tenant_rows(req.tenant)} rows queued "
                    f"(quota {self.tenant_quota_rows})",
                    retry_after_s=self.retry_after_s(),
                )
            shed = self._shed_check(req)
            if shed is not None:
                self._m_shed.labels(shed.reason).inc()
                raise shed
            if resume is not None:
                # migrated resume enters like a preempt-resume: next in
                # line WITHIN its own (class, tenant) queue — it already
                # waited (and decoded) once on the exporting replica
                self._queue.push_front(req)
            else:
                self._queue.push(req)
            self._m_requests.inc()
            self._set_depth_gauges()
            self._cond.notify_all()
        return req

    def retry_after_s(self) -> float:
        """Seconds a rejected client should wait before retrying. The
        base batcher has no service-time model, so 1s; the continuous
        batcher overrides with a chunk-wall-EMA drain estimate."""
        return 1.0

    def _mint_incident(self, reqs, exc: BaseException) -> str:
        """Attribute one failed engine dispatch to every request in
        flight for it. Worker-thread only."""
        self._incident_seq += 1
        inc_id = f"disp-{self._incident_seq:06d}"
        for req in _unique_requests(reqs):
            req.incidents.append(inc_id)
        if self.log is not None:
            self.log.event(
                "dispatch_incident", incident=inc_id, error=repr(exc),
                implicated=len(_unique_requests(reqs)),
            )
        return inc_id

    def _admission_cap(self, req) -> int:
        """Largest row count this request could EVER admit with — the
        submit-time reject bound (the continuous batcher subtracts the
        high-class slot reserve for non-high requests)."""
        return self.max_batch

    def _shed_check(self, req) -> Optional[ShedError]:
        """Admission-time deadline shed (None = admit). Base batcher: no
        cost model, never sheds; the continuous batcher overrides."""
        return None

    def _set_depth_gauges(self) -> None:
        """Caller holds the lock."""
        self._m_depth.set(self._queue.rows)
        for name, rows in self._queue.class_depths().items():
            self._m_depth_by_class.labels(name).set(rows)

    @property
    def queue_depth_rows(self) -> int:
        with self._cond:  # the worker mutates the queue under this lock
            return self._queue.rows

    @property
    def inflight_rows(self) -> int:
        """Rows currently being served by the engine (drain hook: a
        micro-batch in flight; the continuous batcher overrides with its
        live slot count)."""
        with self._cond:  # the worker counts rows in under this lock
            return self._inflight_rows

    @property
    def quiesced(self) -> bool:
        """True when nothing is queued and nothing is in flight — the
        'safe to restart this replica' predicate behind graceful drain.
        Taken under the lock so drain can't observe 'idle' between a
        queue pop and the matching in-flight count (the RLock-backed
        condition makes the nested inflight_rows read reentrant-safe)."""
        with self._cond:
            return not len(self._queue) and self.inflight_rows == 0

    def class_depths(self) -> Dict[str, int]:
        """{priority class: queued rows} — vitals/healthz snapshot."""
        with self._cond:
            return self._queue.class_depths()

    def head_age_s(self) -> Optional[float]:
        """Age of the oldest queued request (None when empty) — the
        vitals sampler's queue-staleness signal. Taking the queue lock at
        sampler cadence (~1 Hz) is noise next to the worker's own
        per-wave acquisitions."""
        with self._cond:
            oldest = self._queue.oldest_enqueued_at()
            if oldest is None:
                return None
            return time.monotonic() - oldest

    def state_summary(self) -> dict:
        """Queue-side state for `/debug/state` and stall reports."""
        with self._cond:
            reqs = self._queue.requests()
            rows = self._queue.rows
            by_class = self._queue.class_depths()
            oldest = self._queue.oldest_enqueued_at()
            head_age = (
                time.monotonic() - oldest if oldest is not None else None
            )
            queued_traces = [
                req.trace.trace_id for req in reqs if req.trace
            ][:16]
        out = {
            "queue_requests": len(reqs),
            "queue_depth_rows": rows,
            "queue_depth_by_class": by_class,
            "max_queue_rows": self.max_queue_rows,
            "queue_head_age_s": (
                round(head_age, 3) if head_age is not None else None
            ),
            "queued_trace_ids": queued_traces,
            "closed": self._closed,
        }
        if self.last_error is not None:
            out["last_error"] = repr(self.last_error)
        return out

    def error_age_s(self) -> Optional[float]:
        """Seconds since the most recent failed flush; None if the last
        flush succeeded (or none has failed yet). Lets health checks decay
        a transient error instead of latching unhealthy — a health-gated
        router that pulls traffic on 503 would otherwise starve the server
        of the successful batch it needs to clear `last_error`."""
        if self.last_error is None or self._last_error_at is None:
            return None
        return time.monotonic() - self._last_error_at

    # -------------------------------------------------------------- worker

    def _close_preempt_span(self, req, **kw) -> None:
        """End a suspended request's open `preempted` span (no-op when it
        has none) — on resume, or on any terminal outcome while queued."""
        if req._preempt_span is not NULL_SPAN:
            req.trace.end(req._preempt_span, **kw)
            req._preempt_span = NULL_SPAN

    def _viable_head(self, now: float) -> Optional[GenRequest]:
        """The scheduler's next admissible request, WITHOUT popping it —
        failing expired and skipping cancelled picks on the way (those
        pops are uncharged: a dead request consumed no capacity, so it
        must not cost its class its fair share). Caller holds the lock.
        Shared by the micro-batch assembler and the continuous admission
        loop so timeout/cancel bookkeeping cannot drift between the two
        batchers."""
        while True:
            head = self._queue.peek()
            if head is None:
                return None
            if head.cancelled:
                self._queue.pop(charge=False)
                self._m_cancelled.inc()
                self._close_preempt_span(head, outcome="cancelled")
                head.trace.end(head._queue_span, outcome="cancelled")
                # requests that die queued still observe the queue stage
                # so /metrics and the traces keep agreeing under overload
                # — except suspended ones, whose queue stage was already
                # observed at FIRST admission (a second observation would
                # cover decode time too and skew the histogram)
                self._observe_queue_stage(head, now)
                head.future.set_exception(RequestCancelled("cancelled"))
                continue
            if head.expired(now):
                self._queue.pop(charge=False)
                self._m_timeouts.inc()
                self._close_preempt_span(head, outcome="timeout")
                head.trace.end(head._queue_span, outcome="timeout")
                self._observe_queue_stage(head, now)
                head.future.set_exception(
                    RequestTimeout(
                        f"spent >{head.timeout_s:.1f}s queued; overloaded?"
                    )
                )
                continue
            return head

    def _observe_queue_stage(self, req, now: float) -> None:
        """Observe the queue stage for a request dying in the queue —
        unless it already observed it at a prior admission (suspended
        requests re-queue; their wait shows as the `preempted` span)."""
        if req._suspend_reason is not None:
            return
        self.stage_seconds.labels("queue").observe(
            now - req.enqueued_at, exemplar=req.trace.trace_id or None
        )

    def _pop_head(self, head: GenRequest) -> None:
        """Pop the request `_viable_head` just returned. Caller holds the
        lock; nothing may have touched the queue in between (the stride
        scheduler is deterministic, so the pick cannot have moved)."""
        popped = self._queue.pop()
        assert popped is head, "queue mutated between peek and pop"

    def _pop_ready(self, batch: List[GenRequest]) -> None:
        """Move queued requests into `batch` (capacity permitting), failing
        expired ones and skipping cancelled ones. Caller holds the lock."""
        now = time.monotonic()
        rows = sum(r.rows for r in batch)
        while True:
            head = self._viable_head(now)
            if head is None or rows + head.rows > self.max_batch:
                break
            self._pop_head(head)
            rows += head.rows
            batch.append(head)
            # counted from the POP, not the flush: between assembly and
            # dispatch these rows are in the worker's hands, and the
            # drain predicate (`quiesced`) must not report an idle
            # batcher while they are — an operator restarting on it
            # would drop them
            self._inflight_rows += head.rows
        self._set_depth_gauges()

    def _assemble(self) -> Optional[List[GenRequest]]:
        """Block until a batch is ready (deadline-or-capacity), or None at
        shutdown with nothing left to drain."""
        with self._cond:
            while not len(self._queue):
                if self._closed:
                    return None
                # empty queue: park until submit/shutdown notifies — an
                # idle server burns no CPU. The timed 0.05s waits below
                # apply only while a flush deadline is pending.
                self._cond.wait()
            batch: List[GenRequest] = []
            self._pop_ready(batch)
            if not batch:  # everything queued was expired/cancelled
                return []
            # deadline anchored at the OLDEST accepted request's arrival
            deadline = batch[0].enqueued_at + self.max_delay_s
            while sum(r.rows for r in batch) < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(remaining, 0.05))
                self._pop_ready(batch)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._assemble()
            if batch is None:
                return
            if not batch:
                continue
            self._flush(batch)

    def _flush(self, batch: List[GenRequest]) -> None:  # tracelint: hotloop
        specs: List[SampleSpec] = []
        for req in batch:
            specs.extend(req.specs)
        try:  # rows were counted into _inflight_rows at pop time
            self._flush_inner(batch, specs)
        finally:
            self._inflight_rows = 0

    def _flush_inner(self, batch: List[GenRequest],
                     specs: List[SampleSpec]) -> None:
        t0 = time.monotonic()
        for req in batch:
            req.trace.end(req._queue_span)
            self.stage_seconds.labels("queue").observe(
                t0 - req.enqueued_at, exemplar=req.trace.trace_id or None
            )
            req._stage_span = req.trace.begin(
                "generate", rows=req.rows, batch_rows=len(specs)
            )
        try:
            tokens, pixels = self.engine.generate(specs)
        except Exception as exc:  # fail fast: every waiter gets the error
            failed_at = time.monotonic()
            # timestamp first: readers check last_error then error_age_s
            self._last_error_at = failed_at
            self.last_error = exc
            self._m_errors.inc()
            self._mint_incident(batch, exc)
            # errored batches still observe the stage so /metrics and the
            # traces keep agreeing (same contract as the harvest path)
            self.stage_seconds.labels("generate").observe(
                failed_at - t0, exemplar=_first_trace_id(batch)
            )
            for req in batch:
                req.trace.end(req._stage_span, error=repr(exc))
                req.future.set_exception(exc)
            return
        self.last_error = None  # engine recovered: let /healthz go green again
        # counted on success only, so batches/occupancy/images/batch_seconds
        # stay mutually consistent (failures are engine_errors_total)
        self._m_batches.inc()
        self._m_occupancy.observe(len(specs))
        batch_s = time.monotonic() - t0
        self._m_batch_seconds.observe(batch_s)
        pick = getattr(self.engine, "pick_shape", None)
        shape = pick(len(specs)) if pick is not None else len(specs)
        ex = _first_trace_id(batch)
        self._m_occupancy_by_shape.labels(shape).observe(
            len(specs), exemplar=ex
        )
        self._m_batch_seconds_by_shape.labels(shape).observe(
            batch_s, exemplar=ex
        )
        self.stage_seconds.labels("generate").observe(batch_s, exemplar=ex)
        offset = 0
        now = time.monotonic()
        for req in batch:
            toks = tokens[offset : offset + req.rows]
            pix = None if pixels is None else pixels[offset : offset + req.rows]
            offset += req.rows
            self._m_images.inc(req.rows)
            self._m_latency.observe(now - req.enqueued_at)
            req.trace.end(req._stage_span, shape=shape)
            req.first_token_at = now
            req.future.set_result((toks, pix))

    # ------------------------------------------------------------ shutdown

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake; `drain=True` flushes queued requests first,
        `drain=False` fails them with `ShuttingDownError`."""
        with self._cond:
            self._closed = True
            if not drain:
                for req in self._queue.drain():
                    self._close_preempt_span(req, outcome="shutdown")
                    req.trace.end(req._queue_span, outcome="shutdown")
                    self._observe_queue_stage(req, time.monotonic())
                    req.future.set_exception(
                        ShuttingDownError("server shutting down")
                    )
                self._set_depth_gauges()
            self._cond.notify_all()
        self._worker.join(timeout=timeout)


class ContinuousBatcher(MicroBatcher):
    """Token-boundary admission over a `ContinuousEngine`'s cache slots.

    Same queue/backpressure surface as `MicroBatcher` (submit / timeout /
    cancel / drain semantics, same instrument names), but the worker never
    assembles flush batches: it runs a persistent loop of

        admit   — pop queued requests into free cache slots, batched: the
                  admission wave prefills in groups of the engine's fixed
                  `prefill_batch`, so R pending rows cost
                  ceil(R / prefill_batch) dispatches, not R (a request's
                  rows admit all-or-nothing so its images stay one
                  retirement unit),
        chunk   — advance every live slot by `engine.chunk_tokens` tokens
                  in one fixed-shape dispatch,
        retire  — at the chunk boundary, harvest rows that completed
                  `image_seq_len` tokens, resolve their requests, and free
                  the slots for the next admission

    so a request arriving mid-decode waits at most one chunk for admission
    instead of a whole `image_seq_len` scan, and batch occupancy backfills
    while other rows are still decoding. Extra observability: per-request
    time-to-first-token histogram, chunk wall-time histogram, and the
    engine's `dalle_serving_slots_active` gauge.
    """

    def __init__(
        self,
        engine,
        max_queue_rows: int = 64,
        registry=None,
        name: str = "dalle_serving",
        tenant_quota_rows: Optional[int] = None,
        class_weights: Optional[dict] = None,
        tenant_weights: Optional[dict] = None,
        log=None,
        preempt: bool = True,
        deadline_shed: bool = True,
        reserve_slots: int = 0,
        spool=None,
        spool_every: int = 8,
        preview_every: int = 4,
    ):
        """`engine` needs the slot surface of `ContinuousEngine`
        (`prefill_slot` / `step_chunk` / `harvest` / `release` /
        `decode_pixels` / `image_seq_len` / `max_batch`; batched admission
        additionally uses `prefill_slots` + `prefill_batch` when present)
        — the tests drive a fake with exactly that surface. `preempt`
        enables decode-time priority preemption; `deadline_shed` enables
        the admission-time SLO-unmeetable shed (both on by default).
        `reserve_slots` keeps that many cache slots usable ONLY by the
        high class, so a high arrival usually admits at the next chunk
        boundary without waiting for a preemption cycle — the latency/
        utilization trade (reserved slots idle when no high traffic;
        default 0 = fully work-conserving, preemption alone reclaims
        capacity). `spool` (a `migrate.CheckpointSpool`) arms the crash
        progress beacon: every `spool_every` chunks the worker journals
        in-flight decode-state checkpoints to it at the chunk boundary,
        so a hard kill loses at most that many chunks of bookkeeping.
        `preview_every` sets the progressive-preview cadence for
        streaming requests (one shared fill+decode dispatch per due
        chunk boundary; 0 disables previews — progress events still
        flow)."""
        self.preview_every = max(0, int(preview_every))
        self.preempt = bool(preempt)
        self.deadline_shed = bool(deadline_shed)
        self.reserve_slots = int(reserve_slots)
        self.spool = spool
        self.spool_every = max(1, int(spool_every))
        assert 0 <= self.reserve_slots < int(
            engine.max_batch if hasattr(engine, "max_batch") else 1 << 30
        ), "reserve_slots must leave at least one slot for other classes"
        super().__init__(
            engine,
            max_queue_rows=max_queue_rows,
            registry=registry,
            name=name,
            tenant_quota_rows=tenant_quota_rows,
            class_weights=class_weights,
            tenant_weights=tenant_weights,
            log=log,
        )

    def _post_init(self) -> None:
        from dalle_pytorch_tpu.serving.engine import SlotAllocator

        self.allocator = SlotAllocator(self.max_batch)
        p = self._name
        self._m_ttft = self.registry.histogram(
            f"{p}_ttft_seconds",
            "enqueue-to-first-token latency per request (chunk-boundary "
            "granularity)",
        )
        self._m_chunk_seconds = self.registry.histogram(
            f"{p}_chunk_seconds", "engine wall time per decode chunk"
        )
        self._m_admitted = self.registry.counter(
            f"{p}_admitted_total", "rows admitted into cache slots"
        )
        self._m_preempt = self.registry.counter_family(
            f"{p}_preemptions_total",
            "decoding requests suspended at a chunk boundary, by reason "
            "(priority: slot reclaimed for a higher class; "
            "dispatch_retry: suspended by the bounded retry after a "
            "failed dispatch rebuilt the engine state)",
            label_name="reason",
        )
        self._m_resume = self.registry.counter_family(
            f"{p}_resumptions_total",
            "suspended requests re-admitted into slots, by the reason "
            "they were suspended",
            label_name="reason",
        )
        # fallback chunk index for span metadata when the engine doesn't
        # keep its own (`ContinuousEngine.chunk_index`; test fakes don't)
        self._chunks_dispatched = 0
        #: admission-order stamp source for the youngest-victim policy
        self._admit_seq = 0
        #: EMA of chunk-dispatch wall seconds — the cost model behind
        #: deadline shedding and Retry-After estimates (None until the
        #: first measured chunk)
        self._chunk_ema: Optional[float] = None
        # instance-visible so /debug/state can render the in-flight table;
        # mutated only by the worker thread (readers snapshot, see
        # state_summary)
        self._inflight: dict = {}
        self._partial: dict = {}
        #: preemption-aware SLO burn (ROADMAP §5 follow-on): a callable
        #: returning the SLOTracker's max burn rate. Above 1.0 the
        #: deadline shed tightens (a fleet already burning error budget
        #: sheds earlier) and the preemption victim policy switches to
        #: least-progress (cheapest redo). None = burn-blind, exactly
        #: the pre-wiring behavior. ServingServer wires vitals.slo in.
        self.slo_burn = None
        # ------------------------------- migration (serving/migrate.py)
        #: build identity stamped into exported checkpoints; the server
        #: sets the engine's real boot fingerprint after construction
        self.checkpoint_fingerprint = "unfingerprinted"
        #: exporting replica identity for `migrated_from` attribution
        self.checkpoint_site: Optional[str] = None
        #: pending drain?migrate=1 export ({"event", "out"}) the worker
        #: serves at the next chunk boundary
        self._migrate_request: Optional[dict] = None
        #: most recent beacon bundle ({"ts", "chunk_index",
        #: "checkpoints": {key: wire}}) — GET /admin/checkpoints reads it
        self.last_beacon: Optional[dict] = None
        #: per-slot decode position at the last boundary — drives the
        #: decoded-token counter and the migration snapshot clip
        self._slot_pos: dict = {}
        self._last_img_pos = None
        self._m_resumed_tokens = self.registry.counter(
            f"{p}_resumed_tokens_total",
            "image tokens restored verbatim from migrated decode-state "
            "checkpoints (work NOT re-decoded after a drain/crash)",
        )
        self._m_decoded_tokens = self.registry.counter(
            f"{p}_decoded_tokens_total",
            "image tokens decoded by chunk dispatches (re-decoded work "
            "after a failover counts again; the drain bench reads the "
            "difference)",
        )
        self._m_migrated = self.registry.counter(
            f"{p}_migrated_out_total",
            "requests exported as decode-state checkpoints at a chunk "
            "boundary by drain?migrate=1",
        )
        # ------------------------------- streaming (serving/streaming.py)
        self._m_ttfp = self.registry.histogram(
            f"{p}_ttfp_seconds",
            "enqueue-to-first-preview-pixels latency per streaming "
            "request (chunk-boundary granularity) — the user-facing "
            "first-paint metric, vs ttft's first-token",
        )
        self._m_stream_events = self.registry.counter_family(
            f"{p}_stream_events_total",
            "SSE stream events emitted, by type (progress/preview from "
            "the worker at chunk boundaries; open/result/error/migrated "
            "from the HTTP layer)",
            label_name="type",
        )

    def state_summary(self) -> dict:
        """Queue summary plus the slot → in-flight request table. The
        worker mutates `_inflight` without a lock (it is the only
        writer), so the snapshot copy retries around concurrent resize —
        a point-in-time debug view, not a linearizable read."""
        out = super().state_summary()
        now = time.monotonic()
        snap = {}
        for _ in range(4):
            try:
                snap = dict(self._inflight)
                break
            except RuntimeError:  # resized mid-iteration; retry
                continue
        out["slots_inflight"] = {
            int(slot): {
                "trace_id": req.trace.trace_id if req.trace else None,
                "rows": req.rows,
                "row_index": idx,
                "age_s": round(now - req.enqueued_at, 3),
            }
            for slot, (req, idx) in snap.items()
        }
        out["slots_active"] = self.allocator.n_active
        out["slots_free"] = self.allocator.n_free
        return out

    @property
    def inflight_rows(self) -> int:
        """Rows decoding in cache slots right now (the drain hook)."""
        return self.allocator.n_active

    # ------------------------------------------------------------- worker

    def _run(self) -> None:  # tracelint: hotloop
        inflight = self._inflight  # slot -> (request, row index)
        partial = self._partial  # request -> {"tokens": [rows], "remaining"}
        while True:
            if self._migrate_request is not None:
                # the previous iteration's chunk dispatch has returned,
                # so this IS a chunk boundary — the only place decode
                # state may leave the device (TL012 contract)
                self._serve_migration(inflight, partial)
                continue
            admitted: List = []  # (slot, spec) prefills owed this iteration
            restored: List = []  # fully-checkpoint-restored requests
            migrate_pending = False
            with self._cond:
                while True:
                    head = self._viable_head(time.monotonic())
                    self._set_depth_gauges()
                    if self._migrate_request is not None:
                        # wake for an export requested while parked (or
                        # between boundaries): serve it at the loop top
                        migrate_pending = True
                        break
                    if head is not None or inflight:
                        break
                    if self._closed:
                        return
                    # idle: no queued work, no live slots — park until
                    # submit/shutdown notifies (no busy-poll)
                    self._cond.wait()
                if migrate_pending:
                    head = None
                # all-or-nothing admission in weighted-fair scheduler
                # order (no starvation: the stride scheduler bounds every
                # class's wait, and a wide request blocks later narrow
                # ones only until slots free). Paged engines gate on free
                # KV blocks too: block exhaustion keeps the request
                # queued (backpressure) until releases return pages,
                # exactly like slot exhaustion. The check covers the
                # WHOLE wave popped so far, not each request in
                # isolation — pages are only reserved at prefill, so two
                # requests that fit alone could jointly overrun the pool
                # and break the allocator's reservation invariant
                # mid-decode.
                can_admit = getattr(self.engine, "can_admit", None)
                demand_fn = getattr(self.engine, "admission_demand", None)
                headroom_fn = getattr(
                    self.engine, "admission_headroom", None
                )
                incremental = (
                    demand_fn is not None and headroom_fn is not None
                )
                # headroom is fixed while this worker holds the queue
                # (pages move only at prefill/release, on this thread),
                # so each head's demand is summed ONCE against a per-wave
                # snapshot instead of re-deriving the whole wave's demand
                # on every pop; engines exposing only `can_admit` get the
                # equivalent union check
                budget = headroom_fn() if incremental else 0
                wave_demand = 0
                wave_specs: List = []
                while (
                    head is not None
                    and self.allocator.n_free
                    >= head.pending_rows + self._reserve_for(head)
                ):
                    pend = head.pending_row_specs()
                    if incremental:
                        head_demand = demand_fn([s for _, s in pend])
                        if wave_demand + head_demand > budget:
                            break
                        wave_demand += head_demand
                    elif can_admit is not None and not can_admit(
                        wave_specs + [s for _, s in pend]
                    ):
                        break
                    self._pop_head(head)
                    if head.migrated and not head._migrate_counted:
                        # first admission of a migrated resume: count the
                        # resumption and the checkpoint-restored tokens
                        # (work this replica does NOT re-decode): whole
                        # done rows, plus mid-decode prefixes when the
                        # engine resumes rows at their own position
                        head._migrate_counted = True
                        self._m_resume.labels("migrate").inc()
                        saved = sum(
                            len(t) for t in head.resume_tokens.values()
                        )
                        if getattr(self.engine, "supports_resume", False):
                            saved += sum(
                                int(getattr(s, "resume_pos", 0) or 0)
                                for s in head.specs
                            )
                        self._m_resumed_tokens.inc(int(saved))
                    if not pend:
                        # every row restored verbatim from the
                        # checkpoint: nothing to decode — complete after
                        # the lock with one pixel-decode dispatch
                        head.trace.end(head._queue_span)
                        self.stage_seconds.labels("queue").observe(
                            time.monotonic() - head.enqueued_at,
                            exemplar=head.trace.trace_id or None,
                        )
                        restored.append(head)
                        head = self._viable_head(time.monotonic())
                        continue
                    wave_specs.extend(s for _, s in pend)
                    # rows harvested before a suspension resume as done
                    partial[head] = {
                        "tokens": [
                            head.resume_tokens.get(i)
                            for i in range(head.rows)
                        ],
                        "remaining": len(pend),
                    }
                    for i, spec in pend:
                        slot = self.allocator.alloc()
                        inflight[slot] = (head, i)
                        # decoded-token accounting starts at the row's
                        # RESUME position when the engine restores the
                        # prefix (those tokens are restored, not decoded)
                        self._slot_pos[slot] = (
                            int(getattr(spec, "resume_pos", 0) or 0)
                            if getattr(
                                self.engine, "supports_resume", False
                            ) else 0
                        )
                        admitted.append((slot, spec))
                    head.admitted_seq = self._admit_seq
                    self._admit_seq += 1
                    self._m_admitted.inc(len(pend))
                    t_admit = time.monotonic()
                    if head._suspend_reason is not None:
                        # resumption: close the preempted span (its whole
                        # duration is the suspension) — the queue stage
                        # was already observed at FIRST admission
                        self._m_resume.labels(head._suspend_reason).inc()
                        self._close_preempt_span(head, outcome="resumed")
                        head._suspend_reason = None
                    else:
                        head.trace.end(head._queue_span)
                        self.stage_seconds.labels("queue").observe(
                            t_admit - head.enqueued_at,
                            exemplar=head.trace.trace_id or None,
                        )
                    head._stage_span = head.trace.begin("prefill")
                    head = self._viable_head(time.monotonic())
                self._set_depth_gauges()

            if migrate_pending:
                continue  # export at the loop top, then resume admitting
            if restored:
                self._complete_restored(restored)
            if not admitted and not inflight:
                continue  # only fully-restored work: no chunk to dispatch

            # which engine dispatch is in flight, so a failure still
            # observes the stage's wall time into stage_seconds — /metrics
            # and the (abandoned) trace spans must agree on error paths too
            stage_name = None
            stage_t0 = 0.0
            try:
                if admitted:
                    # batched admission: the whole wave goes in groups of
                    # the engine's fixed prefill batch — ceil(R /
                    # prefill_batch) dispatches instead of R (engines
                    # without the batched surface, e.g. test fakes, fall
                    # back to per-row prefill)
                    tp0 = time.monotonic()
                    stage_name, stage_t0 = "prefill", tp0
                    dispatches = 0
                    # paged-engine admission stats (prefix-cache hits admit
                    # with zero prefill dispatches): aggregated over the
                    # wave's splits for span metadata + per-request flags
                    hit_slots: set = set()
                    blocks_reused = suffix_tokens = 0
                    have_stats = False
                    resumed_rows = 0
                    prefill_slots = getattr(self.engine, "prefill_slots", None)
                    if prefill_slots is not None:
                        pb = max(
                            1, int(getattr(self.engine, "prefill_batch", 1))
                        )
                        # mid-decode resume rows (migration / preemption
                        # on a resume-capable engine) dispatch through
                        # the teacher-forced resume program; everything
                        # else takes the ordinary prefill path
                        if getattr(self.engine, "supports_resume", False):
                            resume_wave = [
                                (s, sp) for s, sp in admitted
                                if getattr(sp, "resume_pos", 0)
                            ]
                            fresh_wave = [
                                (s, sp) for s, sp in admitted
                                if not getattr(sp, "resume_pos", 0)
                            ]
                        else:
                            resume_wave, fresh_wave = [], admitted
                        # The wave was budgeted against ONE headroom
                        # snapshot but dispatches in prefill_batch splits;
                        # pin its prefix-cache hit entries across ALL
                        # splits so an earlier split's eviction cascade
                        # can't demote a later split's budgeted hit and
                        # overdraw the block-pool reservation
                        wave_guard = getattr(
                            self.engine, "protect_admission_wave", None
                        )
                        wave_keys = (
                            wave_guard(fresh_wave)
                            if wave_guard is not None and fresh_wave
                            else None
                        )
                        try:
                            for i in range(0, len(fresh_wave), pb):
                                prefill_slots(fresh_wave[i : i + pb])
                                st = getattr(
                                    self.engine, "last_admission_stats", None
                                )
                                if st is not None:
                                    have_stats = True
                                    dispatches += st.get("dispatches", 1)
                                    hit_slots.update(st.get("hit_slots", ()))
                                    blocks_reused += st.get(
                                        "prefix_blocks_reused", 0
                                    )
                                    suffix_tokens += st.get(
                                        "suffix_tokens_computed", 0
                                    )
                                else:
                                    dispatches += 1
                        finally:
                            if wave_keys:
                                self.engine.unprotect_admission_wave(
                                    wave_keys
                                )
                        for i in range(0, len(resume_wave), pb):
                            self.engine.resume_slots(resume_wave[i : i + pb])
                            dispatches += 1
                            resumed_rows += len(resume_wave[i : i + pb])
                    else:
                        for slot, spec in admitted:
                            self.engine.prefill_slot(slot, spec)
                            dispatches += 1
                    prefill_s = time.monotonic() - tp0
                    stage_name = None
                    wave_reqs = _unique_requests(
                        inflight[slot][0] for slot, _ in admitted
                    )
                    for req in wave_reqs:
                        extra = {}
                        if have_stats:
                            req_slots = [
                                s for s, _ in admitted
                                if inflight[s][0] is req
                            ]
                            req.prefix_hit = all(
                                s in hit_slots for s in req_slots
                            )
                            extra = dict(
                                prefix_blocks_reused=blocks_reused,
                                suffix_tokens_computed=suffix_tokens,
                                prefix_hit=req.prefix_hit,
                            )
                        if resumed_rows:
                            extra["resumed_rows"] = resumed_rows
                        req.trace.end(
                            req._stage_span,
                            wave_rows=len(admitted),
                            dispatches=dispatches,
                            **extra,
                        )
                    self.stage_seconds.labels("prefill").observe(
                        prefill_s, exemplar=_first_trace_id(wave_reqs)
                    )
                chunk_reqs = _unique_requests(
                    req for req, _ in inflight.values()
                )
                self._chunks_dispatched += 1
                spans = [
                    (
                        req,
                        req.trace.begin(
                            "chunk", slots_active=len(inflight)
                        ),
                    )
                    for req in chunk_reqs
                ]
                t0 = time.monotonic()
                stage_name, stage_t0 = "chunk", t0
                img_pos, _active = self.engine.step_chunk()
                chunk_s = time.monotonic() - t0
                stage_name = None
                for req in chunk_reqs:
                    if req.incidents:
                        # streaks end on DECODE PROGRESS (a successful
                        # chunk), so a bystander of one old incident
                        # that keeps decoding and later dies in an
                        # unrelated one isn't mislabeled poison (422).
                        # Deliberately NOT cleared by a successful
                        # prefill: re-admission after a retry always
                        # prefills, so a chunk-poison request would
                        # reset its own streak every cycle and never be
                        # caught — at the cost that an innocent doomed
                        # by two back-to-back incidents with no chunk
                        # between them reads as poison (its 422 is
                        # terminal for THAT attempt only; the replica
                        # tracks no fingerprint, so a resubmission on a
                        # healthy engine serves normally)
                        req.incidents.clear()
                chunk_index = getattr(
                    self.engine, "chunk_index", self._chunks_dispatched
                )
                for req, sp in spans:
                    req.trace.end(sp, chunk_index=chunk_index)
                self._m_chunk_seconds.observe(chunk_s)
                # chunk-wall EMA: the service-time basis of deadline
                # shedding and Retry-After estimates (α=0.2 — reactive to
                # load shifts, stable against single-chunk noise)
                self._chunk_ema = (
                    chunk_s if self._chunk_ema is None
                    else 0.2 * chunk_s + 0.8 * self._chunk_ema
                )
                self.stage_seconds.labels("chunk").observe(
                    chunk_s, exemplar=_first_trace_id(chunk_reqs)
                )

                now = time.monotonic()
                finished = []
                for slot, (req, _idx) in inflight.items():
                    if req.first_token_at is None and img_pos[slot] > 0:
                        req.first_token_at = now
                        self._m_ttft.observe(now - req.enqueued_at)
                    # decoded-token accounting: per-slot position deltas
                    # over THIS chunk (re-decoded work after a failover
                    # counts again — the drain bench reads the total)
                    cur = int(img_pos[slot])
                    if cur > self._slot_pos.get(slot, 0):
                        self._m_decoded_tokens.inc(
                            cur - self._slot_pos.get(slot, 0)
                        )
                        self._slot_pos[slot] = cur
                    if img_pos[slot] >= self.engine.image_seq_len:
                        finished.append(slot)
                self._last_img_pos = img_pos
                # streaming: progress events for every live streamed
                # request, plus (cadence-gated, see _emit_stream_events)
                # one shared preview snapshot + fill+decode dispatch —
                # BEFORE _retire so the final boundary's progress event
                # still sees the finished rows' slots
                self._emit_stream_events(inflight, img_pos, now)
                if finished:
                    # harvest/release are engine dispatches too — a failure
                    # here must fail fast like the chunk path, not kill the
                    # worker thread (which would leave the server accepting
                    # requests nobody will ever serve)
                    self._retire(finished, inflight, partial)
                # chunk-boundary housekeeping, in order: retire cancelled/
                # expired rows (their slots must not squat to completion),
                # then reclaim a slot for a blocked higher-class head
                self._reap(inflight, partial)
                self._maybe_preempt(inflight, partial, img_pos)
                if (
                    self.spool is not None
                    and self._chunks_dispatched % self.spool_every == 0
                ):
                    # crash progress beacon, cadence-guarded (TL012): the
                    # snapshot transfer runs at most once per spool_every
                    # chunk boundaries, never mid-chunk
                    self._maybe_beacon(inflight)
            except Exception as exc:
                if stage_name is not None:
                    self.stage_seconds.labels(stage_name).observe(
                        time.monotonic() - stage_t0,
                        exemplar=_first_trace_id(
                            _unique_requests(
                                req for req, _ in inflight.values()
                            )
                        ),
                    )
                # one bounded retry per request off the rebuilt engine
                # state; requests past their budget fail fast as before
                self._recover(exc, inflight, partial)
                continue
            self._set_slots_gauge()

    # ----------------------------------------------- streaming (boundary)

    def _emit_stream_events(self, inflight, img_pos, now) -> None:  # tracelint: hotloop
        """Chunk-boundary streaming emission (worker thread). Every live
        streamed request gets a progress event keyed by its REQUEST-level
        chunk index — min decode position across its in-flight rows, in
        chunks — which the stream's monotonic high water deduplicates
        (a restarted non-resume re-decode replays below it silently, so
        readers never see a duplicated or regressing chunk). Requests
        whose index crossed a `preview_every` multiple share ONE
        `snapshot_rows` transfer and ONE fill+decode dispatch
        (`engine.preview_pixels`, the warmed `preview` program) for the
        whole boundary; pixels ride the event raw — the SSE reader
        thread pays the PNG encode, never this loop. Preview cadence is
        the TL012 guard: the snapshot runs at most once per
        `preview_every` request-chunks, only at a boundary, and a
        preview failure drops this boundary's previews without touching
        decode."""
        per_req: dict = {}
        for slot, (req, idx) in inflight.items():
            if req.stream is not None:
                per_req.setdefault(req, []).append((slot, idx))
        if not per_req:
            return
        chunk_tokens = max(
            1,
            int(getattr(
                self.engine, "chunk_tokens", getattr(self.engine, "chunk", 1)
            )),
        )
        seq_len = int(self.engine.image_seq_len)
        due: List = []  # (req, stream, req_chunk, {slot: pos}, {idx: slot})
        for req, rows in per_req.items():
            stream = req.stream
            info = self._partial.get(req)
            done_rows = sum(
                1 for t in (info["tokens"] if info else ()) if t is not None
            )
            positions = {slot: int(img_pos[slot]) for slot, _ in rows}
            req_chunk = min(positions.values()) // chunk_tokens
            if stream.progress(
                req_chunk,
                tokens=sum(positions.values()) + done_rows * seq_len,
                total_tokens=req.rows * seq_len,
                rows=req.rows,
                slots=sorted(positions),
                trace_id=req.trace.trace_id or None,
            ):
                self._m_stream_events.labels("progress").inc()
            if stream.preview_due(req_chunk, self.preview_every):
                due.append((
                    req, stream, req_chunk, positions,
                    {idx: slot for slot, idx in rows},
                ))
        if not due:
            return
        previewer = getattr(self.engine, "preview_pixels", None)
        snap_fn = getattr(self.engine, "snapshot_rows", None)
        if (
            previewer is None or snap_fn is None
            or not getattr(self.engine, "preview_enabled", True)
        ):
            # engine can't preview (no fill+decode program warmed):
            # progress streams still flow, previews just never fire
            return
        t0 = time.monotonic()
        spans = [
            (req, req.trace.begin("preview", chunk=c))
            for req, _st, c, _pos, _rows in due
        ]
        try:
            all_slots = sorted(
                s for _r, _st, _c, positions, _rows in due for s in positions
            )
            snap = dict(zip(all_slots, snap_fn(all_slots)))
            batch_toks: List = []
            batch_pos: List = []
            layout: List = []  # (req, stream, req_chunk, ordered row idxs)
            for req, stream, req_chunk, positions, slot_of in due:
                info = self._partial.get(req)
                order = []
                for i in range(req.rows):
                    slot = slot_of.get(i)
                    if slot is not None:
                        batch_toks.append(np.asarray(snap[slot], np.int32))  # tracelint: disable=TL002 -- snapshot_rows already ran its fused device_get; this slices the host copy at the chunk boundary
                        batch_pos.append(positions[slot])
                        order.append(i)
                    elif info is not None and info["tokens"][i] is not None:
                        # row finished earlier: preview it complete
                        batch_toks.append(
                            np.asarray(info["tokens"][i], np.int32)  # tracelint: disable=TL002 -- harvested rows are host arrays already; no device sync here
                        )
                        batch_pos.append(seq_len)
                        order.append(i)
                layout.append((req, stream, req_chunk, order))
            pixels = previewer(
                np.stack(batch_toks), np.asarray(batch_pos, np.int32)
            )
        except Exception as exc:
            # previews are best-effort: a failed fill+decode loses this
            # boundary's previews, never the requests (unlike the chunk
            # path, no decode state was donated into it)
            for req, sp in spans:
                req.trace.end(sp, error=repr(exc))
            if self.log is not None:
                self.log.event("preview_failed", error=repr(exc))
            return
        if pixels is None:
            for req, sp in spans:
                req.trace.end(sp, rows=0)
            return
        preview_s = time.monotonic() - t0
        span_of = dict((id(req), sp) for req, sp in spans)
        offset = 0
        for req, stream, req_chunk, order in layout:
            pix = pixels[offset : offset + len(order)]
            offset += len(order)
            first = stream.previews_sent == 0
            if stream.preview(
                req_chunk,
                rows=list(order),
                pixels=np.asarray(pix),  # tracelint: disable=TL002 -- preview_pixels returns after its own designed sync; this is a host-side slice
                trace_id=req.trace.trace_id or None,
            ):
                self._m_stream_events.labels("preview").inc()
                if first:
                    # time-to-first-pixels: the streaming analogue of
                    # TTFT — enqueue to the first preview a client could
                    # have painted
                    self._m_ttfp.observe(
                        now - req.enqueued_at,
                        exemplar=req.trace.trace_id or None,
                    )
            req.trace.end(
                span_of[id(req)], rows=len(order), previews=stream.previews_sent
            )
        self.stage_seconds.labels("preview").observe(
            preview_s,
            exemplar=_first_trace_id([req for req, _st, _c, _o in layout]),
        )

    # --------------------------------------------------- QoS / preemption

    def _image_time_s(self) -> Optional[float]:
        """Estimated wall seconds to decode one full image, from the
        chunk EMA (None before the first measured chunk)."""
        if self._chunk_ema is None:
            return None
        chunk_tokens = max(
            1,
            int(getattr(
                self.engine, "chunk_tokens", getattr(self.engine, "chunk", 1)
            )),
        )
        chunks = -(-int(self.engine.image_seq_len) // chunk_tokens)
        return chunks * self._chunk_ema

    def _est_wait_s(self) -> Optional[float]:
        """Rough time a NEW row waits for a slot: rows in the system
        (queued + decoding) drain at ~`max_batch` rows per image-time in
        steady state. Deliberately coarse — it gates SHEDDING, where a 2x
        error means rejecting slightly early or late, not corruption."""
        image_time = self._image_time_s()
        if image_time is None:
            return None
        backlog = self._queue.rows + self.allocator.n_active
        return (backlog / self.max_batch) * image_time

    def retry_after_s(self) -> float:
        """Queue-drain estimate for Retry-After headers (503/429): how
        long until today's backlog has drained at the measured service
        rate. Clamped to [1, 60] — a precise huge value just tells the
        client 'much later', and 0 invites an instant re-reject."""
        wait = self._est_wait_s()
        if wait is None:
            return 1.0
        return min(max(1.0, wait), 60.0)

    def _burn_factor(self) -> float:
        """SLO-burn pessimism multiplier from the wired `slo_burn` hook:
        1.0 at or under budget (or unwired), the burn rate itself above
        it, capped at 4x so a pathological burn spike cannot shed every
        request outright."""
        fn = self.slo_burn
        if fn is None:
            return 1.0
        try:
            burn = float(fn())
        except Exception:
            return 1.0  # a broken burn source must not break admission
        return max(1.0, min(burn, 4.0))

    def _shed_check(self, req) -> Optional[ShedError]:
        """Deadline-aware admission shed: if the backlog estimate says
        this request cannot finish inside ITS OWN timeout, reject it now
        (503 + Retry-After) instead of queueing it to a certain 504 —
        the queued-to-die request would also steal service time from
        requests that still can meet their deadlines. When the SLO
        error budget is burning (burn rate > 1 from the PR 7
        SLOTracker), the margin tightens by the burn factor: a fleet
        already missing its objective sheds EARLIER, trading marginal
        admissions for budget recovery (reason `slo_burn`)."""
        if not self.deadline_shed:
            return None
        wait = self._est_wait_s()
        image_time = self._image_time_s()
        if wait is None or image_time is None:
            return None  # no measured basis yet: admit
        est_completion = wait + image_time
        factor = self._burn_factor()
        budget_s = req.timeout_s / factor
        if est_completion <= budget_s:
            return None
        reason = "deadline" if est_completion > req.timeout_s else "slo_burn"
        return ShedError(
            f"estimated completion {est_completion:.1f}s exceeds the "
            f"admission budget {budget_s:.1f}s "
            f"(timeout {req.timeout_s:.1f}s / burn factor {factor:.2f}; "
            f"{self._queue.rows} rows queued, "
            f"{self.allocator.n_active} decoding)",
            retry_after_s=min(
                max(1.0, est_completion - budget_s), 60.0
            ),
            reason=reason,
        )

    def _suspend_host(self, req, inflight, partial, reason: str) -> None:
        """Host half of a suspension: strip the request's rows from the
        slot table, fold already-harvested rows into its resume state,
        open the `preempted` span, and re-queue it at the FRONT of its
        own (class, tenant) queue. The caller has dealt with the device
        side (released the slots, or the engine state was rebuilt)."""
        for slot in [s for s, (r, _) in inflight.items() if r is req]:
            inflight.pop(slot)
            self.allocator.free(slot)
        info = partial.pop(req, None)
        if info is not None:
            for idx, toks in enumerate(info["tokens"]):
                if toks is not None:
                    req.resume_tokens[idx] = toks
        req._suspend_reason = reason
        req._preempt_span = req.trace.begin(
            "preempted", reason=reason, pending_rows=req.pending_rows
        )
        with self._cond:
            self._queue.push_front(req)
            self._set_depth_gauges()
            self._cond.notify_all()

    def _reserve_for(self, head) -> int:
        """Extra free slots `head` must leave behind: non-high classes
        cannot dip into the high-class slot reserve."""
        return self.reserve_slots if head.klass > 0 else 0

    def _admission_cap(self, req) -> int:
        return self.max_batch - self._reserve_for(req)

    def _admission_blocked(self, head) -> bool:
        """Would the scheduler's head fail to admit right now? Mirrors
        the admission loop's slot + block-pool gating exactly."""
        if self.allocator.n_free < head.pending_rows + self._reserve_for(head):
            return True
        specs = [s for _, s in head.pending_row_specs()]
        demand_fn = getattr(self.engine, "admission_demand", None)
        headroom_fn = getattr(self.engine, "admission_headroom", None)
        if demand_fn is not None and headroom_fn is not None:
            return demand_fn(specs) > headroom_fn()
        can_admit = getattr(self.engine, "can_admit", None)
        if can_admit is not None:
            return not can_admit(specs)
        return False

    def _maybe_preempt(self, inflight, partial, img_pos) -> None:
        """Chunk-boundary preemption: when the scheduler's chosen head is
        blocked on slots/pages and a STRICTLY lower-class request is
        decoding, release the youngest such victim and re-queue it.

        Keying the decision off the scheduler's OWN next pick (not 'any
        queued high request') is what makes this churn-free: the
        deterministic stride scheduler returns the same head next
        iteration, so the freed capacity goes to exactly the request it
        was reclaimed for. Restarting the victim at image position 0
        regenerates bit-identical tokens (decode RNG is (seed, position)-
        keyed), so preemption trades the victim's latency — never its
        output — for the head's; the paged engine's prefix cache makes
        the victim's eventual re-prefill near-free.
        """
        if not self.preempt or not inflight:
            return
        while inflight:
            if not self._preempt_one(inflight, partial, img_pos):
                return

    def _preempt_one(self, inflight, partial, img_pos) -> bool:
        """Release ONE victim for the blocked head; True if it did (the
        caller loops — a multi-row head may need several slots reclaimed
        at this one boundary). Deliberately scoped to the scheduler's
        head rather than the whole queued backlog of its class: eager
        whole-backlog reclaim measured WORSE under saturation — every
        extra victim's discarded-and-redone decode raises the effective
        load, lengthening boundaries for the high class it meant to
        protect."""
        with self._cond:
            head = self._queue.peek()
            now = time.monotonic()
            if (
                head is None or head.cancelled or head.expired(now)
                or not self._admission_blocked(head)
            ):
                return False
            klass = head.klass
        victims = {
            req for req, _ in inflight.values() if req.klass > klass
        }
        if not victims:
            return False
        if self._burn_factor() > 1.0 and img_pos is not None:
            # burning SLO budget: evict the victim with the LEAST decode
            # progress — the cheapest redo, so the preemption itself
            # wastes the fewest already-spent chunk dispatches while the
            # fleet digs out of its budget hole. Tie-break youngest
            # (the default policy) for determinism.
            def progress(r):
                return sum(
                    int(img_pos[s])
                    for s, (rr, _) in inflight.items() if rr is r
                )

            victim = min(
                victims, key=lambda r: (progress(r), -r.admitted_seq)
            )
        else:
            victim = max(victims, key=lambda r: r.admitted_seq)
        slot_rows = {
            s: idx for s, (r, idx) in inflight.items() if r is victim
        }
        # snapshot generated-so-far tokens (observability + the resume
        # bit-identity pin) BEFORE the slots are released; fakes without
        # `snapshot_rows` use their harvest path
        snap_fn = getattr(self.engine, "snapshot_rows", self.engine.harvest)
        slots = list(slot_rows)
        toks = snap_fn(slots)
        resumable = getattr(self.engine, "supports_resume", False)
        for slot, row_toks in zip(slots, toks):
            pos = int(img_pos[slot]) if img_pos is not None else len(row_toks)
            prefix = np.asarray(row_toks[:pos])
            victim.preempt_snapshots[slot_rows[slot]] = prefix
            if resumable:
                # the resume-capable engine re-admits this row at its
                # preempted position instead of position 0 — preemption
                # then costs one boundary wait + one re-prefill dispatch,
                # not a whole re-decode (same tokens either way)
                spec = victim.specs[slot_rows[slot]]
                spec.resume_tokens = np.asarray(prefix, np.int32)
                spec.resume_pos = int(pos)
        # the release dispatch may itself fail — let it propagate to the
        # worker's recovery path with the victim still inflight, so the
        # rebuilt-state suspension covers it like everyone else
        self.engine.release(slots)
        victim.preemptions += 1
        self._m_preempt.labels("priority").inc()
        if self.log is not None:
            self.log.event(
                "preempt",
                trace_id=victim.trace.trace_id or None,
                reason="priority",
                rows=len(slots),
                for_class=head.priority,
                victim_class=victim.priority,
            )
        self._suspend_host(victim, inflight, partial, reason="priority")
        self._set_slots_gauge()
        return True

    def _reap(self, inflight, partial) -> None:
        """Chunk-boundary retirement of cancelled/expired DECODING
        requests: release their slots through the same path preemption
        uses instead of letting a dead request squat until completion."""
        now = time.monotonic()
        doomed: dict = {}
        for slot, (req, _idx) in inflight.items():
            if req.cancelled or req.expired(now):
                doomed.setdefault(req, []).append(slot)
        if not doomed:
            return
        # one release dispatch for the whole boundary; a failure here
        # propagates to the worker's recovery path like any dispatch error
        self.engine.release([s for ss in doomed.values() for s in ss])
        for req, slots in doomed.items():
            for s in slots:
                inflight.pop(s)
                self.allocator.free(s)
            partial.pop(req, None)
            if req.cancelled:
                self._m_cancelled.inc()
                exc: Exception = RequestCancelled(
                    "cancelled mid-decode; slot released at the chunk "
                    "boundary"
                )
            else:
                self._m_timeouts.inc()
                exc = RequestTimeout(
                    f"exceeded {req.timeout_s:.1f}s mid-decode; slot "
                    "released at the chunk boundary"
                )
            req.future.set_exception(exc)
        self._set_slots_gauge()

    def _recover(self, exc, inflight, partial) -> None:
        """Dispatch-failure policy: the donated-state rebuild left a
        clean engine, so every inflight request with retry budget is
        SUSPENDED and re-admitted from scratch (bit-identical tokens —
        the same (seed, position)-keyed determinism preemption relies
        on); requests already retried once fail with the error. Falls
        back to `_fail_all` when nothing is retryable, preserving the
        original fail-fast behavior. Every request in flight for the
        failed dispatch carries its incident id — repeat implication is
        the poison-request signal the HTTP layer turns into a 422."""
        self._mint_incident(list(partial), exc)
        retryable = [r for r in partial if r.dispatch_retries < 1]
        if not retryable:
            self._fail_all(exc, inflight, partial, attributed=True)
            return
        self._last_error_at = time.monotonic()
        self.last_error = exc
        self._m_errors.inc()
        doomed = [r for r in partial if r.dispatch_retries >= 1]
        for req in doomed:
            for slot in [s for s, (r, _) in inflight.items() if r is req]:
                inflight.pop(slot)
                self.allocator.free(slot)
            partial.pop(req, None)
            req.future.set_exception(exc)
        for req in retryable:
            req.dispatch_retries += 1
            self._m_retries.inc()
            self._suspend_host(req, inflight, partial, reason="dispatch_retry")
        if self.log is not None:
            self.log.event(
                "dispatch_retry",
                error=repr(exc),
                retried=len(retryable),
                failed=len(doomed),
            )
        try:  # engine may be wedged; slot release is best-effort
            self.engine.release(range(self.max_batch))
        except Exception:
            pass
        self._set_slots_gauge()

    def _fail_all(self, exc, inflight, partial, attributed=False) -> None:
        """Engine failure: error every live request, free every slot, and
        best-effort reset the engine so the next admission starts clean."""
        if not attributed:
            self._mint_incident(list(partial), exc)
        self._last_error_at = time.monotonic()
        self.last_error = exc
        self._m_errors.inc()
        for req in partial:
            req.future.set_exception(exc)
        for slot in list(inflight):
            self.allocator.free(slot)
        inflight.clear()
        partial.clear()
        try:  # engine may be wedged; slot release is best-effort
            self.engine.release(range(self.max_batch))
        except Exception:
            pass
        self._set_slots_gauge()

    # ------------------------------------------- migration (chunk boundary)

    def migrate_out(self, timeout_s: float = 30.0):
        """Export every queued + in-flight request's decode-state
        checkpoint at the NEXT chunk boundary (`/admin/drain?migrate=1`).
        Admin-thread entry: the worker does the device reads and fails
        each exported request's future with `MigratedError` (the HTTP
        layer maps it to the 409 the fleet router re-dispatches as a
        resume). Returns the list of `RequestCheckpoint`s, or None when
        the worker never reached a boundary inside `timeout_s` (wedged
        engine — nothing was exported)."""
        return self._request_export(destructive=True, timeout_s=timeout_s)

    def peek_checkpoints(self, timeout_s: float = 30.0):
        """Non-destructive flavor (`GET /admin/checkpoints` pull-based
        drain): same chunk-boundary snapshot, but the requests keep
        decoding here — the caller gets a copy of the state, not the
        state itself."""
        return self._request_export(destructive=False, timeout_s=timeout_s)

    def _request_export(self, destructive: bool, timeout_s: float):
        deadline = time.monotonic() + float(timeout_s)
        ev = threading.Event()
        pend = {"event": ev, "out": [], "destructive": bool(destructive)}
        # exports serialize: a concurrent drain and checkpoint-peek must
        # not clobber each other's pending request — the later caller
        # waits out the earlier one's event (bounded by its own timeout)
        while True:
            with self._cond:
                if self._migrate_request is None:
                    self._migrate_request = pend
                    self._cond.notify_all()
                    break
                other = self._migrate_request["event"]
            if not other.wait(max(0.0, deadline - time.monotonic())):
                return None
        if not ev.wait(max(0.0, deadline - time.monotonic())):
            # worker wedged mid-chunk: withdraw the request (if it is
            # still ours — the worker swaps it out under the lock before
            # serving, so a withdrawn export is NEVER half-served) and
            # report failure. The event fires either way, so an exporter
            # serialized behind this one wakes NOW instead of burning
            # its own full deadline on a freed slot.
            with self._cond:
                if self._migrate_request is pend:
                    self._migrate_request = None
                    ev.set()
                    return None
            # the worker claimed it between the timeout and the lock:
            # the export IS happening — wait briefly for the result
            return pend["out"] if ev.wait(5.0) else None
        return pend["out"]

    def _serve_migration(self, inflight, partial) -> None:
        """Worker thread, at a chunk boundary. Destructive: pop every
        queued request, snapshot every in-flight row, fail all their
        futures with `MigratedError` carrying the checkpoints, release
        the slots. Non-destructive: build the same checkpoints and touch
        nothing."""
        from dalle_pytorch_tpu.serving.migrate import (
            MigratedError,
            encode_checkpoint,
        )

        with self._cond:
            # CLAIM the request under the lock: a caller that timed out
            # has withdrawn it (None — this wake is a no-op, never a
            # destructive export nobody asked for), and once claimed the
            # caller's withdraw can't race a half-served export
            pend = self._migrate_request
            self._migrate_request = None
        if pend is None:
            return
        destructive = pend.get("destructive", True)
        queued: List[GenRequest] = []
        if destructive:
            with self._cond:
                now = time.monotonic()
                while True:
                    head = self._viable_head(now)
                    if head is None:
                        break
                    # uncharged pop: a migrated request consumed no
                    # capacity here (same rule as cancel/timeout pops)
                    self._queue.pop(charge=False)
                    queued.append(head)
                self._set_depth_gauges()
        else:
            with self._cond:
                queued = [
                    r for r in self._queue.requests()
                    if not r.cancelled and not r.expired(time.monotonic())
                ]
        live = _unique_requests(req for req, _ in inflight.values())
        cps = self._collect_checkpoints(live + queued, inflight, "drain")
        if not destructive:
            pend["out"] = [cps[r] for r in live + queued]
            pend["event"].set()
            return
        slots = list(inflight)
        if slots:
            try:
                self.engine.release(slots)
            except Exception:
                # the donated-state rebuild left a clean engine; the
                # host-side maps clear below either way
                pass
            for slot in slots:
                inflight.pop(slot)
                self.allocator.free(slot)
        now = time.monotonic()
        for req in live + queued:
            partial.pop(req, None)
            self._close_preempt_span(req, outcome="migrated")
            if req in queued:
                req.trace.end(req._queue_span, outcome="migrated")
                self._observe_queue_stage(req, now)
            self._m_migrated.inc()
            cp = cps[req]
            # encode ONCE here; the HTTP layer's 409 body and the admin
            # bundle both reuse the blob instead of re-serializing the
            # full token payload per consumer on the drain critical path
            try:
                cp.encoded = encode_checkpoint(
                    cp, self.checkpoint_fingerprint
                )
            except Exception:
                cp.encoded = None  # consumers fall back to encoding
            req.future.set_exception(MigratedError(cp))
        if self.log is not None and (live or queued):
            self.log.event(
                "migrate_out",
                requests=len(live) + len(queued),
                inflight=len(live), queued=len(queued),
            )
        self._set_slots_gauge()
        pend["out"] = [cps[r] for r in live + queued]
        pend["event"].set()

    def _collect_checkpoints(self, reqs, inflight, reason: str) -> dict:
        """Worker thread, chunk boundary only: one `RequestCheckpoint`
        per request, from host bookkeeping plus ONE snapshot transfer
        for all in-flight rows (the same `snapshot_rows` fixed-shape
        read preemption uses)."""
        from dalle_pytorch_tpu.serving.migrate import (
            RequestCheckpoint,
            RowCheckpoint,
        )

        img_pos = self._last_img_pos
        wanted = set(id(r) for r in reqs)
        slot_of = {
            (id(r), idx): slot
            for slot, (r, idx) in inflight.items()
        }
        live_slots = [
            s for s, (r, _) in inflight.items() if id(r) in wanted
        ]
        snap: dict = {}
        if live_slots:
            snap_fn = getattr(
                self.engine, "snapshot_rows", self.engine.harvest
            )
            snap = dict(zip(live_slots, snap_fn(live_slots)))
        chunk_index = int(
            getattr(self.engine, "chunk_index", self._chunks_dispatched)
        )
        out: dict = {}
        for req in reqs:
            info = self._partial.get(req)
            rows = []
            for i, spec in enumerate(req.specs):
                done_toks = None
                if info is not None and info["tokens"][i] is not None:
                    done_toks = info["tokens"][i]
                elif i in req.resume_tokens:
                    done_toks = req.resume_tokens[i]
                if done_toks is not None:
                    toks, done = np.asarray(done_toks, np.int32), True
                else:
                    slot = slot_of.get((id(req), i))
                    if slot is not None and slot in snap:
                        pos = (
                            max(0, int(img_pos[slot]))
                            if img_pos is not None else 0
                        )
                        toks = np.asarray(snap[slot][:pos], np.int32)
                    else:  # queued row: at most its last preempt prefix
                        toks = np.asarray(
                            req.preempt_snapshots.get(
                                i, np.zeros(0, np.int32)
                            ),
                            np.int32,
                        )
                    done = False
                rows.append(RowCheckpoint(
                    row_index=i,
                    prompt_ids=np.asarray(spec.text_ids, np.int32),
                    tokens=toks,
                    done=done,
                    seed=int(spec.seed),
                    temperature=float(spec.temperature),
                    top_k=float(spec.top_k),
                ))
            out[req] = RequestCheckpoint(
                rows=rows,
                chunk_index=chunk_index,
                priority=req.priority,
                tenant=req.tenant,
                trace_id=req.trace.trace_id or None,
                site=self.checkpoint_site,
                request_key=req.request_key or (req.trace.trace_id or None),
                reason=reason,
            )
        return out

    def _maybe_beacon(self, inflight) -> None:
        """Crash progress beacon (cadence-guarded by the caller):
        journal every in-flight request's checkpoint to the local spool
        in one atomic rewrite, and keep the wire bundle in memory for
        `GET /admin/checkpoints`. A spool write failure is logged, never
        raised — a full disk must not take down decode."""
        from dalle_pytorch_tpu.serving.migrate import (
            encode_checkpoint,
            to_wire,
        )

        live = _unique_requests(req for req, _ in inflight.values())
        cps = self._collect_checkpoints(live, inflight, "beacon")
        bundle: dict = {}
        wires: dict = {}
        for req, cp in cps.items():
            key = cp.request_key or f"local-{id(req):x}"
            if key in bundle:
                # two CONTENT-identical concurrent requests share the
                # router's fingerprint key; last-wins is safe (the
                # resuming replica validates seeds against the request,
                # so a crossed resume degrades to a counted clean
                # restart) but the loser's crash-resume opportunity is
                # gone — say so once per beacon
                if self.log is not None:
                    self.log.event(
                        "beacon_key_collision", key=key,
                    )
            blob = encode_checkpoint(cp, self.checkpoint_fingerprint)
            bundle[key] = blob
            wires[key] = to_wire(blob)
        self.last_beacon = {
            "ts": time.time(),
            "chunk_index": int(
                getattr(self.engine, "chunk_index", self._chunks_dispatched)
            ),
            "checkpoints": wires,
        }
        try:
            self.spool.write(bundle)
        except Exception as exc:
            if self.log is not None:
                self.log.event("spool_write_failed", error=repr(exc))

    def _complete_restored(self, reqs) -> None:
        """Requests whose EVERY row was restored from a checkpoint:
        resolve with one pixel-decode dispatch each — no slot, no chunk,
        zero re-decoded tokens."""
        for req in reqs:
            toks = np.stack([
                np.asarray(req.resume_tokens[i], np.int32)
                for i in range(req.rows)
            ])
            try:
                pixels = self.engine.decode_pixels(toks)
            except Exception as exc:
                self._last_error_at = time.monotonic()
                self.last_error = exc
                self._m_errors.inc()
                self._mint_incident([req], exc)
                req.future.set_exception(exc)
                continue
            now = time.monotonic()
            self._m_images.inc(req.rows)
            self._m_latency.observe(now - req.enqueued_at)
            req.first_token_at = now
            # restored tokens are this request's first (and only) token
            # event here — observe TTFT like the decode path does, so
            # the TTFT and latency histogram populations stay aligned
            # across rolling drains
            self._m_ttft.observe(now - req.enqueued_at)
            req.future.set_result((toks, pixels))
            self.last_error = None

    def _retire(self, finished, inflight, partial) -> None:  # tracelint: hotloop
        """Harvest finished slots, resolve fully-collected requests, free
        the slots for the next admission wave."""
        t0 = time.monotonic()
        touched = _unique_requests(inflight[s][0] for s in finished)
        hspans = [(req, req.trace.begin("harvest")) for req in touched]
        tokens = self.engine.harvest(finished)
        self.engine.release(finished)
        done: List = []  # (request, stacked rows) completed this boundary
        for slot, row in zip(finished, tokens):
            req, idx = inflight.pop(slot)
            self.allocator.free(slot)
            info = partial[req]
            info["tokens"][idx] = row
            info["remaining"] -= 1
            if info["remaining"] == 0:
                del partial[req]
                done.append((req, np.stack(info["tokens"])))
        done_reqs = {req for req, _ in done}
        # requests with rows still decoding get their harvest span closed
        # now (it covered token collection only); completing requests keep
        # theirs open across the pixel decode below
        for req, sp in hspans:
            if req not in done_reqs:
                req.trace.end(sp, slots=len(finished), partial=True)
        if not done:
            self.stage_seconds.labels("harvest").observe(
                time.monotonic() - t0, exemplar=_first_trace_id(touched)
            )
            return
        # ONE pixel-decode dispatch for every request completing at this
        # boundary (the engine pads to its fixed decode shape internally);
        # per-request decodes would cost a dispatch each — the overhead the
        # micro-batch engine avoids by fusing decode into the sampler
        now = time.monotonic()
        try:
            all_pixels = self.engine.decode_pixels(
                np.concatenate([toks for _, toks in done])
            )
        except Exception as exc:
            # an engine dispatch failure like any other: record it so
            # /healthz goes unhealthy and engine_errors_total moves —
            # but only the completing requests are lost; rows still
            # decoding are untouched
            self._last_error_at = time.monotonic()
            self.last_error = exc
            self._m_errors.inc()
            self._mint_incident([req for req, _ in done], exc)
            # errored harvests still observe the stage so /metrics and the
            # traces keep agreeing on where the time went
            self.stage_seconds.labels("harvest").observe(
                time.monotonic() - t0, exemplar=_first_trace_id(touched)
            )
            for req, sp in hspans:
                if req in done_reqs:
                    req.trace.end(sp, error=repr(exc))
            for req, _ in done:
                req.future.set_exception(exc)
            return
        harvest_s = time.monotonic() - t0
        self.stage_seconds.labels("harvest").observe(
            harvest_s, exemplar=_first_trace_id([req for req, _ in done])
        )
        done_spans = {req: sp for req, sp in hspans if req in done_reqs}
        offset = 0
        for req, toks in done:
            pix = (
                None if all_pixels is None
                else all_pixels[offset : offset + req.rows]
            )
            offset += req.rows
            self._m_images.inc(req.rows)
            self._m_latency.observe(now - req.enqueued_at)
            req.trace.end(
                done_spans.get(req, NULL_SPAN),
                slots=len(finished), rows=req.rows,
            )
            req.future.set_result((toks, pix))
            self.last_error = None  # a full request completed: healthy

    def _set_slots_gauge(self) -> None:
        gauge = getattr(self.engine, "slots_active_gauge", None)
        if gauge is not None:
            gauge(self.allocator.n_active)
