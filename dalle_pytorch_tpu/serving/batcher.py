"""Dynamic micro-batching over a bounded request queue.

The throughput/latency knob of the serving layer: requests from many
concurrent clients accumulate in a bounded queue; a single worker thread
flushes a micro-batch to the engine when EITHER `max_batch` rows are
waiting OR the oldest request has waited `max_delay_ms` — the classic
deadline-or-capacity policy (cf. Vortex/TF-Serving style batchers,
PAPERS.md). One worker means one in-flight sampler dispatch, which is the
right shape for a single accelerator: overlapping dispatches would just
queue inside the backend anyway.

Overload handling is explicit, never implicit:
  * queue full  -> `submit` raises `QueueFullError` immediately
    (backpressure; the HTTP layer maps it to 503 + Retry-After);
  * too old     -> requests that waited past their timeout are failed
    with `RequestTimeout` when they reach the head of the queue, not
    silently dropped;
  * cancelled   -> client-abandoned requests are skipped without costing
    a batch row;
  * engine error-> every request in the failed batch gets the exception
    (fail fast; no wedged clients), and the error is surfaced through
    `last_error` for /healthz;
  * shutdown    -> `shutdown(drain=True)` stops intake, flushes what is
    queued, then joins the worker; `drain=False` fails the queue.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

from dalle_pytorch_tpu.obs.tracing import NULL_SPAN, NULL_TRACE
from dalle_pytorch_tpu.serving.engine import SampleSpec


class QueueFullError(RuntimeError):
    """Bounded queue is at capacity — reject, don't buffer unboundedly."""


class RequestTimeout(RuntimeError):
    """Request spent longer than its timeout queued or in flight."""


class RequestCancelled(RuntimeError):
    """Request was cancelled by the client before execution."""


class ShuttingDownError(RuntimeError):
    """Batcher no longer accepts work."""


class _Future:
    """Minimal thread-safe one-shot result slot.

    Deliberately NOT concurrent.futures.Future: our cancellation is
    queue-level (`GenRequest.cancel` sets a flag; the WORKER later resolves
    the future with `RequestCancelled` when it pops the request), and a
    stdlib Future that has been `.cancel()`ed raises InvalidStateError on
    that late `set_exception` — exactly our flow.
    """

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exception: Optional[BaseException] = None

    def set_result(self, result) -> None:
        self._result = result
        self._event.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exception = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise RequestTimeout("timed out waiting for generation result")
        if self._exception is not None:
            raise self._exception
        return self._result


class GenRequest:
    """One client request: `rows` batch rows that must stay together
    (e.g. num_images samples of one prompt), flushed in a single batch so
    the result arrives whole."""

    def __init__(
        self,
        specs: Sequence[SampleSpec],
        timeout_s: float = 120.0,
        trace=NULL_TRACE,
    ):
        assert specs, "request needs at least one sample row"
        self.specs: List[SampleSpec] = list(specs)
        self.timeout_s = float(timeout_s)
        self.enqueued_at = time.monotonic()
        self.future = _Future()
        self._cancelled = threading.Event()
        # request-scoped trace (obs/tracing.py), minted at HTTP ingress and
        # carried through the worker so stage spans land on one tree; the
        # default NULL_TRACE makes every span call a no-op for callers
        # (benches, tests) that don't trace
        self.trace = trace
        self._queue_span = trace.begin("queue", rows=len(self.specs))
        self._stage_span = NULL_SPAN  # current worker-side stage span
        # when the request's FIRST token existed on the host: the chunk
        # boundary after admission (continuous engine) or batch completion
        # (micro-batch engine — its tokens only materialize at scan end).
        # Benches read it for time-to-first-token percentiles.
        self.first_token_at: Optional[float] = None
        # True when EVERY row of this request admitted via the prefix
        # cache (paged engine, zero prefill dispatches); None when the
        # engine doesn't report admission stats. Rides into the structured
        # request log so per-request traces explain cheap vs full prefills.
        self.prefix_hit: Optional[bool] = None

    @property
    def rows(self) -> int:
        return len(self.specs)

    def cancel(self) -> None:
        """Best-effort: a request already handed to the engine completes."""
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def expired(self, now: float) -> bool:
        return now - self.enqueued_at > self.timeout_s


def _unique_requests(reqs) -> List[GenRequest]:
    """First-seen-order dedup by identity (GenRequest hashes by identity;
    a multi-row request owns several slots but is one trace)."""
    return list(dict.fromkeys(reqs))


def _first_trace_id(reqs) -> Optional[str]:
    """Exemplar for a shared-dispatch observation: the first traced
    request's ID, or None when nothing in the group is traced."""
    for req in reqs:
        if req.trace:
            return req.trace.trace_id
    return None


class MicroBatcher:
    def __init__(
        self,
        engine,
        max_batch: Optional[int] = None,
        max_delay_ms: float = 25.0,
        max_queue_rows: int = 64,
        registry=None,
        name: str = "dalle_serving",
    ):
        """`engine` needs `.generate(list[SampleSpec]) -> (tokens, pixels)`
        and (unless `max_batch` is given) a `.max_batch` attribute — the
        tests drive a fake with exactly that surface."""
        self.engine = engine
        # explicit None check: a caller passing a misconfigured 0 should
        # hit the assert below, not silently get the engine's cap
        self.max_batch = int(
            engine.max_batch if max_batch is None else max_batch
        )
        assert self.max_batch >= 1
        engine_cap = getattr(engine, "max_batch", None)
        assert engine_cap is None or self.max_batch <= engine_cap, (
            f"max_batch={self.max_batch} exceeds the engine's largest "
            f"compiled shape {engine_cap}; every flush would fail"
        )
        assert int(max_queue_rows) >= self.max_batch, (
            f"max_queue_rows={max_queue_rows} < max_batch={self.max_batch}: "
            "a full-size request could never even enqueue"
        )
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.max_queue_rows = int(max_queue_rows)
        self._cond = threading.Condition()
        self._pending: deque = deque()
        self._pending_rows = 0
        self._closed = False
        self._drain = True
        self.last_error: Optional[BaseException] = None
        self._last_error_at: Optional[float] = None

        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._name = name
        p = name
        self._m_depth = registry.gauge(
            f"{p}_queue_depth_rows", "request rows waiting in the batcher queue"
        )
        self._m_rejected = registry.counter(
            f"{p}_rejected_total", "requests rejected because the queue was full"
        )
        self._m_timeouts = registry.counter(
            f"{p}_timeouts_total", "requests failed by per-request timeout"
        )
        self._m_cancelled = registry.counter(
            f"{p}_cancelled_total", "requests cancelled before execution"
        )
        self._m_errors = registry.counter(
            f"{p}_engine_errors_total",
            "generation dispatches (flushed batches / slot chunks) failed "
            "by an engine exception",
        )
        self._m_requests = registry.counter(
            f"{p}_requests_total", "requests accepted into the queue"
        )
        self._m_images = registry.counter(
            f"{p}_images_total", "images generated (batch rows completed)"
        )
        self._m_latency = registry.histogram(
            f"{p}_request_latency_seconds",
            "enqueue-to-result latency per request",
        )
        # per-stage wall time, labeled by stage — the aggregate view of the
        # span tracer's per-request breakdown, so /metrics and
        # /debug/traces agree on where the time went. Observed whether or
        # not tracing is on; exemplars carry the most recent trace ID when
        # it is (render(exemplars=True)).
        self.stage_seconds = registry.histogram_family(
            f"{p}_stage_seconds",
            "wall time per request stage (queue/prefill/chunk/harvest for "
            "the continuous engine; queue/generate for micro-batches; "
            "respond is observed by the HTTP layer)",
            label_name="stage",
        )

        self._post_init()  # batching-mode instruments + subclass state must
        self._worker = threading.Thread(  # exist before the worker runs
            target=self._run, name=f"{name}-batcher", daemon=True
        )
        self._worker.start()

    def _post_init(self) -> None:
        """Register the flush-path instruments. `ContinuousBatcher`
        overrides this with its slot-path instruments instead, so a
        continuous server's /metrics never exposes permanently-empty
        micro-batch series (an occupancy dashboard reading them would see
        'no batches ever flushed' against a busy server)."""
        registry, p = self.registry, self._name
        self._m_batches = registry.counter(
            f"{p}_batches_total", "micro-batches flushed to the engine"
        )
        # one bucket per occupancy up to a render-size cap; bigger batches
        # land in +Inf (the _sum/_count ratio still shows mean occupancy)
        self._m_occupancy = registry.histogram(
            f"{p}_batch_occupancy_rows",
            "real (unpadded) rows per flushed micro-batch",
            buckets=tuple(float(b) for b in range(1, min(self.max_batch, 32) + 1)),
        )
        self._m_batch_seconds = registry.histogram(
            f"{p}_batch_seconds", "engine wall time per flushed micro-batch"
        )
        # per-compiled-shape series: which rung served the batch and how
        # long it took there — occupancy-vs-shape is the padding-waste
        # dashboard (ROADMAP "/metrics per-shape occupancy labels")
        self._m_occupancy_by_shape = registry.histogram_family(
            f"{p}_batch_occupancy_rows_by_shape",
            "real rows per flushed micro-batch, by compiled batch shape",
            label_name="shape",
            buckets=tuple(float(b) for b in range(1, min(self.max_batch, 32) + 1)),
        )
        self._m_batch_seconds_by_shape = registry.histogram_family(
            f"{p}_batch_seconds_by_shape",
            "engine wall time per flushed micro-batch, by compiled batch shape",
            label_name="shape",
        )

    # -------------------------------------------------------------- intake

    def submit(
        self,
        specs: Sequence[SampleSpec],
        timeout_s: float = 120.0,
        trace=NULL_TRACE,
    ) -> GenRequest:
        """Enqueue one request; returns it (result via `req.future.result()`).

        Raises `QueueFullError` (backpressure) or `ShuttingDownError`
        immediately instead of blocking the caller. `trace` (a
        `Trace` from `obs/tracing.py`) rides on the request; the worker
        records stage spans onto it.
        """
        req = GenRequest(specs, timeout_s=timeout_s, trace=trace)
        with self._cond:
            if self._closed:
                raise ShuttingDownError("batcher is shutting down")
            if req.rows > self.max_batch:
                self._m_rejected.inc()
                raise QueueFullError(
                    f"request of {req.rows} rows exceeds max batch "
                    f"{self.max_batch}"
                )
            can_ever = getattr(self.engine, "can_ever_admit", None)
            if can_ever is not None and not can_ever(req.specs):
                # paged engine: the request's worst case exceeds the WHOLE
                # block pool — it would queue forever, so reject now
                self._m_rejected.inc()
                raise QueueFullError(
                    f"request of {req.rows} rows exceeds the engine's KV "
                    "block pool capacity"
                )
            if self._pending_rows + req.rows > self.max_queue_rows:
                self._m_rejected.inc()
                raise QueueFullError(
                    f"queue full ({self._pending_rows}/{self.max_queue_rows} rows)"
                )
            self._pending.append(req)
            self._pending_rows += req.rows
            self._m_requests.inc()
            self._m_depth.set(self._pending_rows)
            self._cond.notify_all()
        return req

    @property
    def queue_depth_rows(self) -> int:
        return self._pending_rows

    def head_age_s(self) -> Optional[float]:
        """Age of the oldest queued request (None when empty) — the
        vitals sampler's queue-staleness signal. Taking the queue lock at
        sampler cadence (~1 Hz) is noise next to the worker's own
        per-wave acquisitions."""
        with self._cond:
            if not self._pending:
                return None
            return time.monotonic() - self._pending[0].enqueued_at

    def state_summary(self) -> dict:
        """Queue-side state for `/debug/state` and stall reports."""
        with self._cond:
            pending = len(self._pending)
            rows = self._pending_rows
            head_age = (
                time.monotonic() - self._pending[0].enqueued_at
                if self._pending else None
            )
            queued_traces = [
                req.trace.trace_id for req in self._pending if req.trace
            ][:16]
        out = {
            "queue_requests": pending,
            "queue_depth_rows": rows,
            "max_queue_rows": self.max_queue_rows,
            "queue_head_age_s": (
                round(head_age, 3) if head_age is not None else None
            ),
            "queued_trace_ids": queued_traces,
            "closed": self._closed,
        }
        if self.last_error is not None:
            out["last_error"] = repr(self.last_error)
        return out

    def error_age_s(self) -> Optional[float]:
        """Seconds since the most recent failed flush; None if the last
        flush succeeded (or none has failed yet). Lets health checks decay
        a transient error instead of latching unhealthy — a health-gated
        router that pulls traffic on 503 would otherwise starve the server
        of the successful batch it needs to clear `last_error`."""
        if self.last_error is None or self._last_error_at is None:
            return None
        return time.monotonic() - self._last_error_at

    # -------------------------------------------------------------- worker

    def _viable_head(self, now: float) -> Optional[GenRequest]:
        """First admissible queued request, WITHOUT popping it — failing
        expired and skipping cancelled ones from the front on the way.
        Caller holds the lock. Shared by the micro-batch assembler and the
        continuous admission loop so timeout/cancel bookkeeping cannot
        drift between the two batchers."""
        while self._pending:
            head = self._pending[0]
            if head.cancelled:
                self._pending.popleft()
                self._pending_rows -= head.rows
                self._m_cancelled.inc()
                head.trace.end(head._queue_span, outcome="cancelled")
                # requests that die queued still observe the queue stage
                # so /metrics and the traces keep agreeing under overload
                self.stage_seconds.labels("queue").observe(
                    now - head.enqueued_at,
                    exemplar=head.trace.trace_id or None,
                )
                head.future.set_exception(RequestCancelled("cancelled"))
                continue
            if head.expired(now):
                self._pending.popleft()
                self._pending_rows -= head.rows
                self._m_timeouts.inc()
                head.trace.end(head._queue_span, outcome="timeout")
                self.stage_seconds.labels("queue").observe(
                    now - head.enqueued_at,
                    exemplar=head.trace.trace_id or None,
                )
                head.future.set_exception(
                    RequestTimeout(
                        f"spent >{head.timeout_s:.1f}s queued; overloaded?"
                    )
                )
                continue
            return head
        return None

    def _pop_ready(self, batch: List[GenRequest]) -> None:
        """Move queued requests into `batch` (capacity permitting), failing
        expired ones and skipping cancelled ones. Caller holds the lock."""
        now = time.monotonic()
        rows = sum(r.rows for r in batch)
        while True:
            head = self._viable_head(now)
            if head is None or rows + head.rows > self.max_batch:
                break
            self._pending.popleft()
            self._pending_rows -= head.rows
            rows += head.rows
            batch.append(head)
        self._m_depth.set(self._pending_rows)

    def _assemble(self) -> Optional[List[GenRequest]]:
        """Block until a batch is ready (deadline-or-capacity), or None at
        shutdown with nothing left to drain."""
        with self._cond:
            while not self._pending:
                if self._closed:
                    return None
                # empty queue: park until submit/shutdown notifies — an
                # idle server burns no CPU. The timed 0.05s waits below
                # apply only while a flush deadline is pending.
                self._cond.wait()
            batch: List[GenRequest] = []
            self._pop_ready(batch)
            if not batch:  # everything queued was expired/cancelled
                return []
            # deadline anchored at the OLDEST accepted request's arrival
            deadline = batch[0].enqueued_at + self.max_delay_s
            while sum(r.rows for r in batch) < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(remaining, 0.05))
                self._pop_ready(batch)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._assemble()
            if batch is None:
                return
            if not batch:
                continue
            self._flush(batch)

    def _flush(self, batch: List[GenRequest]) -> None:  # tracelint: hotloop
        specs: List[SampleSpec] = []
        for req in batch:
            specs.extend(req.specs)
        t0 = time.monotonic()
        for req in batch:
            req.trace.end(req._queue_span)
            self.stage_seconds.labels("queue").observe(
                t0 - req.enqueued_at, exemplar=req.trace.trace_id or None
            )
            req._stage_span = req.trace.begin(
                "generate", rows=req.rows, batch_rows=len(specs)
            )
        try:
            tokens, pixels = self.engine.generate(specs)
        except Exception as exc:  # fail fast: every waiter gets the error
            # timestamp first: readers check last_error then error_age_s
            self._last_error_at = time.monotonic()
            self.last_error = exc
            self._m_errors.inc()
            # errored batches still observe the stage so /metrics and the
            # traces keep agreeing (same contract as the harvest path)
            self.stage_seconds.labels("generate").observe(
                self._last_error_at - t0, exemplar=_first_trace_id(batch)
            )
            for req in batch:
                req.trace.end(req._stage_span, error=repr(exc))
                req.future.set_exception(exc)
            return
        self.last_error = None  # engine recovered: let /healthz go green again
        # counted on success only, so batches/occupancy/images/batch_seconds
        # stay mutually consistent (failures are engine_errors_total)
        self._m_batches.inc()
        self._m_occupancy.observe(len(specs))
        batch_s = time.monotonic() - t0
        self._m_batch_seconds.observe(batch_s)
        pick = getattr(self.engine, "pick_shape", None)
        shape = pick(len(specs)) if pick is not None else len(specs)
        ex = _first_trace_id(batch)
        self._m_occupancy_by_shape.labels(shape).observe(
            len(specs), exemplar=ex
        )
        self._m_batch_seconds_by_shape.labels(shape).observe(
            batch_s, exemplar=ex
        )
        self.stage_seconds.labels("generate").observe(batch_s, exemplar=ex)
        offset = 0
        now = time.monotonic()
        for req in batch:
            toks = tokens[offset : offset + req.rows]
            pix = None if pixels is None else pixels[offset : offset + req.rows]
            offset += req.rows
            self._m_images.inc(req.rows)
            self._m_latency.observe(now - req.enqueued_at)
            req.trace.end(req._stage_span, shape=shape)
            req.first_token_at = now
            req.future.set_result((toks, pix))

    # ------------------------------------------------------------ shutdown

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop intake; `drain=True` flushes queued requests first,
        `drain=False` fails them with `ShuttingDownError`."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._pending:
                    req = self._pending.popleft()
                    req.trace.end(req._queue_span, outcome="shutdown")
                    self.stage_seconds.labels("queue").observe(
                        time.monotonic() - req.enqueued_at,
                        exemplar=req.trace.trace_id or None,
                    )
                    req.future.set_exception(
                        ShuttingDownError("server shutting down")
                    )
                self._pending_rows = 0
                self._m_depth.set(0)
            self._cond.notify_all()
        self._worker.join(timeout=timeout)


class ContinuousBatcher(MicroBatcher):
    """Token-boundary admission over a `ContinuousEngine`'s cache slots.

    Same queue/backpressure surface as `MicroBatcher` (submit / timeout /
    cancel / drain semantics, same instrument names), but the worker never
    assembles flush batches: it runs a persistent loop of

        admit   — pop queued requests into free cache slots, batched: the
                  admission wave prefills in groups of the engine's fixed
                  `prefill_batch`, so R pending rows cost
                  ceil(R / prefill_batch) dispatches, not R (a request's
                  rows admit all-or-nothing so its images stay one
                  retirement unit),
        chunk   — advance every live slot by `engine.chunk_tokens` tokens
                  in one fixed-shape dispatch,
        retire  — at the chunk boundary, harvest rows that completed
                  `image_seq_len` tokens, resolve their requests, and free
                  the slots for the next admission

    so a request arriving mid-decode waits at most one chunk for admission
    instead of a whole `image_seq_len` scan, and batch occupancy backfills
    while other rows are still decoding. Extra observability: per-request
    time-to-first-token histogram, chunk wall-time histogram, and the
    engine's `dalle_serving_slots_active` gauge.
    """

    def __init__(
        self,
        engine,
        max_queue_rows: int = 64,
        registry=None,
        name: str = "dalle_serving",
    ):
        """`engine` needs the slot surface of `ContinuousEngine`
        (`prefill_slot` / `step_chunk` / `harvest` / `release` /
        `decode_pixels` / `image_seq_len` / `max_batch`; batched admission
        additionally uses `prefill_slots` + `prefill_batch` when present)
        — the tests drive a fake with exactly that surface."""
        super().__init__(
            engine,
            max_queue_rows=max_queue_rows,
            registry=registry,
            name=name,
        )

    def _post_init(self) -> None:
        from dalle_pytorch_tpu.serving.engine import SlotAllocator

        self.allocator = SlotAllocator(self.max_batch)
        p = self._name
        self._m_ttft = self.registry.histogram(
            f"{p}_ttft_seconds",
            "enqueue-to-first-token latency per request (chunk-boundary "
            "granularity)",
        )
        self._m_chunk_seconds = self.registry.histogram(
            f"{p}_chunk_seconds", "engine wall time per decode chunk"
        )
        self._m_admitted = self.registry.counter(
            f"{p}_admitted_total", "rows admitted into cache slots"
        )
        # fallback chunk index for span metadata when the engine doesn't
        # keep its own (`ContinuousEngine.chunk_index`; test fakes don't)
        self._chunks_dispatched = 0
        # instance-visible so /debug/state can render the in-flight table;
        # mutated only by the worker thread (readers snapshot, see
        # state_summary)
        self._inflight: dict = {}
        self._partial: dict = {}

    def state_summary(self) -> dict:
        """Queue summary plus the slot → in-flight request table. The
        worker mutates `_inflight` without a lock (it is the only
        writer), so the snapshot copy retries around concurrent resize —
        a point-in-time debug view, not a linearizable read."""
        out = super().state_summary()
        now = time.monotonic()
        snap = {}
        for _ in range(4):
            try:
                snap = dict(self._inflight)
                break
            except RuntimeError:  # resized mid-iteration; retry
                continue
        out["slots_inflight"] = {
            int(slot): {
                "trace_id": req.trace.trace_id if req.trace else None,
                "rows": req.rows,
                "row_index": idx,
                "age_s": round(now - req.enqueued_at, 3),
            }
            for slot, (req, idx) in snap.items()
        }
        out["slots_active"] = self.allocator.n_active
        out["slots_free"] = self.allocator.n_free
        return out

    # ------------------------------------------------------------- worker

    def _run(self) -> None:  # tracelint: hotloop
        inflight = self._inflight  # slot -> (request, row index)
        partial = self._partial  # request -> {"tokens": [rows], "remaining"}
        while True:
            admitted: List = []  # (slot, spec) prefills owed this iteration
            with self._cond:
                while True:
                    head = self._viable_head(time.monotonic())
                    self._m_depth.set(self._pending_rows)
                    if head is not None or inflight:
                        break
                    if self._closed:
                        return
                    # idle: no queued work, no live slots — park until
                    # submit/shutdown notifies (no busy-poll)
                    self._cond.wait()
                # all-or-nothing admission in arrival order (no starvation:
                # a wide request blocks later narrow ones until slots free).
                # Paged engines gate on free KV blocks too: block
                # exhaustion keeps the request queued (backpressure) until
                # releases return pages, exactly like slot exhaustion. The
                # check covers the WHOLE wave popped so far, not each
                # request in isolation — pages are only reserved at
                # prefill, so two requests that fit alone could jointly
                # overrun the pool and break the allocator's reservation
                # invariant mid-decode.
                can_admit = getattr(self.engine, "can_admit", None)
                demand_fn = getattr(self.engine, "admission_demand", None)
                headroom_fn = getattr(
                    self.engine, "admission_headroom", None
                )
                incremental = (
                    demand_fn is not None and headroom_fn is not None
                )
                # headroom is fixed while this worker holds the queue
                # (pages move only at prefill/release, on this thread),
                # so each head's demand is summed ONCE against a per-wave
                # snapshot instead of re-deriving the whole wave's demand
                # on every pop; engines exposing only `can_admit` get the
                # equivalent union check
                budget = headroom_fn() if incremental else 0
                wave_demand = 0
                wave_specs: List = []
                while head is not None and self.allocator.n_free >= head.rows:
                    if incremental:
                        head_demand = demand_fn(head.specs)
                        if wave_demand + head_demand > budget:
                            break
                        wave_demand += head_demand
                    elif can_admit is not None and not can_admit(
                        wave_specs + list(head.specs)
                    ):
                        break
                    self._pending.popleft()
                    self._pending_rows -= head.rows
                    wave_specs.extend(head.specs)
                    partial[head] = {
                        "tokens": [None] * head.rows,
                        "remaining": head.rows,
                    }
                    for i, spec in enumerate(head.specs):
                        slot = self.allocator.alloc()
                        inflight[slot] = (head, i)
                        admitted.append((slot, spec))
                    self._m_admitted.inc(head.rows)
                    t_admit = time.monotonic()
                    head.trace.end(head._queue_span)
                    self.stage_seconds.labels("queue").observe(
                        t_admit - head.enqueued_at,
                        exemplar=head.trace.trace_id or None,
                    )
                    head._stage_span = head.trace.begin("prefill")
                    head = self._viable_head(time.monotonic())
                self._m_depth.set(self._pending_rows)

            # which engine dispatch is in flight, so a failure still
            # observes the stage's wall time into stage_seconds — /metrics
            # and the (abandoned) trace spans must agree on error paths too
            stage_name = None
            stage_t0 = 0.0
            try:
                if admitted:
                    # batched admission: the whole wave goes in groups of
                    # the engine's fixed prefill batch — ceil(R /
                    # prefill_batch) dispatches instead of R (engines
                    # without the batched surface, e.g. test fakes, fall
                    # back to per-row prefill)
                    tp0 = time.monotonic()
                    stage_name, stage_t0 = "prefill", tp0
                    dispatches = 0
                    # paged-engine admission stats (prefix-cache hits admit
                    # with zero prefill dispatches): aggregated over the
                    # wave's splits for span metadata + per-request flags
                    hit_slots: set = set()
                    blocks_reused = suffix_tokens = 0
                    have_stats = False
                    prefill_slots = getattr(self.engine, "prefill_slots", None)
                    if prefill_slots is not None:
                        pb = max(
                            1, int(getattr(self.engine, "prefill_batch", 1))
                        )
                        # The wave was budgeted against ONE headroom
                        # snapshot but dispatches in prefill_batch splits;
                        # pin its prefix-cache hit entries across ALL
                        # splits so an earlier split's eviction cascade
                        # can't demote a later split's budgeted hit and
                        # overdraw the block-pool reservation
                        wave_guard = getattr(
                            self.engine, "protect_admission_wave", None
                        )
                        wave_keys = (
                            wave_guard(admitted)
                            if wave_guard is not None
                            else None
                        )
                        try:
                            for i in range(0, len(admitted), pb):
                                prefill_slots(admitted[i : i + pb])
                                st = getattr(
                                    self.engine, "last_admission_stats", None
                                )
                                if st is not None:
                                    have_stats = True
                                    dispatches += st.get("dispatches", 1)
                                    hit_slots.update(st.get("hit_slots", ()))
                                    blocks_reused += st.get(
                                        "prefix_blocks_reused", 0
                                    )
                                    suffix_tokens += st.get(
                                        "suffix_tokens_computed", 0
                                    )
                                else:
                                    dispatches += 1
                        finally:
                            if wave_keys:
                                self.engine.unprotect_admission_wave(
                                    wave_keys
                                )
                    else:
                        for slot, spec in admitted:
                            self.engine.prefill_slot(slot, spec)
                            dispatches += 1
                    prefill_s = time.monotonic() - tp0
                    stage_name = None
                    wave_reqs = _unique_requests(
                        inflight[slot][0] for slot, _ in admitted
                    )
                    for req in wave_reqs:
                        extra = {}
                        if have_stats:
                            req_slots = [
                                s for s, _ in admitted
                                if inflight[s][0] is req
                            ]
                            req.prefix_hit = all(
                                s in hit_slots for s in req_slots
                            )
                            extra = dict(
                                prefix_blocks_reused=blocks_reused,
                                suffix_tokens_computed=suffix_tokens,
                                prefix_hit=req.prefix_hit,
                            )
                        req.trace.end(
                            req._stage_span,
                            wave_rows=len(admitted),
                            dispatches=dispatches,
                            **extra,
                        )
                    self.stage_seconds.labels("prefill").observe(
                        prefill_s, exemplar=_first_trace_id(wave_reqs)
                    )
                chunk_reqs = _unique_requests(
                    req for req, _ in inflight.values()
                )
                self._chunks_dispatched += 1
                spans = [
                    (
                        req,
                        req.trace.begin(
                            "chunk", slots_active=len(inflight)
                        ),
                    )
                    for req in chunk_reqs
                ]
                t0 = time.monotonic()
                stage_name, stage_t0 = "chunk", t0
                img_pos, _active = self.engine.step_chunk()
                chunk_s = time.monotonic() - t0
                stage_name = None
                chunk_index = getattr(
                    self.engine, "chunk_index", self._chunks_dispatched
                )
                for req, sp in spans:
                    req.trace.end(sp, chunk_index=chunk_index)
                self._m_chunk_seconds.observe(chunk_s)
                self.stage_seconds.labels("chunk").observe(
                    chunk_s, exemplar=_first_trace_id(chunk_reqs)
                )

                now = time.monotonic()
                finished = []
                for slot, (req, _idx) in inflight.items():
                    if req.first_token_at is None and img_pos[slot] > 0:
                        req.first_token_at = now
                        self._m_ttft.observe(now - req.enqueued_at)
                    if img_pos[slot] >= self.engine.image_seq_len:
                        finished.append(slot)
                if finished:
                    # harvest/release are engine dispatches too — a failure
                    # here must fail fast like the chunk path, not kill the
                    # worker thread (which would leave the server accepting
                    # requests nobody will ever serve)
                    self._retire(finished, inflight, partial)
            except Exception as exc:  # fail fast: every live request errors
                if stage_name is not None:
                    self.stage_seconds.labels(stage_name).observe(
                        time.monotonic() - stage_t0,
                        exemplar=_first_trace_id(
                            _unique_requests(
                                req for req, _ in inflight.values()
                            )
                        ),
                    )
                self._fail_all(exc, inflight, partial)
                continue
            self._set_slots_gauge()

    def _fail_all(self, exc, inflight, partial) -> None:
        """Engine failure: error every live request, free every slot, and
        best-effort reset the engine so the next admission starts clean."""
        self._last_error_at = time.monotonic()
        self.last_error = exc
        self._m_errors.inc()
        for req in partial:
            req.future.set_exception(exc)
        for slot in list(inflight):
            self.allocator.free(slot)
        inflight.clear()
        partial.clear()
        try:  # engine may be wedged; slot release is best-effort
            self.engine.release(range(self.max_batch))
        except Exception:
            pass
        self._set_slots_gauge()

    def _retire(self, finished, inflight, partial) -> None:  # tracelint: hotloop
        """Harvest finished slots, resolve fully-collected requests, free
        the slots for the next admission wave."""
        t0 = time.monotonic()
        touched = _unique_requests(inflight[s][0] for s in finished)
        hspans = [(req, req.trace.begin("harvest")) for req in touched]
        tokens = self.engine.harvest(finished)
        self.engine.release(finished)
        done: List = []  # (request, stacked rows) completed this boundary
        for slot, row in zip(finished, tokens):
            req, idx = inflight.pop(slot)
            self.allocator.free(slot)
            info = partial[req]
            info["tokens"][idx] = row
            info["remaining"] -= 1
            if info["remaining"] == 0:
                del partial[req]
                done.append((req, np.stack(info["tokens"])))
        done_reqs = {req for req, _ in done}
        # requests with rows still decoding get their harvest span closed
        # now (it covered token collection only); completing requests keep
        # theirs open across the pixel decode below
        for req, sp in hspans:
            if req not in done_reqs:
                req.trace.end(sp, slots=len(finished), partial=True)
        if not done:
            self.stage_seconds.labels("harvest").observe(
                time.monotonic() - t0, exemplar=_first_trace_id(touched)
            )
            return
        # ONE pixel-decode dispatch for every request completing at this
        # boundary (the engine pads to its fixed decode shape internally);
        # per-request decodes would cost a dispatch each — the overhead the
        # micro-batch engine avoids by fusing decode into the sampler
        now = time.monotonic()
        try:
            all_pixels = self.engine.decode_pixels(
                np.concatenate([toks for _, toks in done])
            )
        except Exception as exc:
            # an engine dispatch failure like any other: record it so
            # /healthz goes unhealthy and engine_errors_total moves —
            # but only the completing requests are lost; rows still
            # decoding are untouched
            self._last_error_at = time.monotonic()
            self.last_error = exc
            self._m_errors.inc()
            # errored harvests still observe the stage so /metrics and the
            # traces keep agreeing on where the time went
            self.stage_seconds.labels("harvest").observe(
                time.monotonic() - t0, exemplar=_first_trace_id(touched)
            )
            for req, sp in hspans:
                if req in done_reqs:
                    req.trace.end(sp, error=repr(exc))
            for req, _ in done:
                req.future.set_exception(exc)
            return
        harvest_s = time.monotonic() - t0
        self.stage_seconds.labels("harvest").observe(
            harvest_s, exemplar=_first_trace_id([req for req, _ in done])
        )
        done_spans = {req: sp for req, sp in hspans if req in done_reqs}
        offset = 0
        for req, toks in done:
            pix = (
                None if all_pixels is None
                else all_pixels[offset : offset + req.rows]
            )
            offset += req.rows
            self._m_images.inc(req.rows)
            self._m_latency.observe(now - req.enqueued_at)
            req.trace.end(
                done_spans.get(req, NULL_SPAN),
                slots=len(finished), rows=req.rows,
            )
            req.future.set_result((toks, pix))
            self.last_error = None  # a full request completed: healthy

    def _set_slots_gauge(self) -> None:
        gauge = getattr(self.engine, "slots_active_gauge", None)
        if gauge is not None:
            gauge(self.allocator.n_active)
