"""Replica supervisor: crash-fast restart with backoff and crash-loop
hold-down.

PR 12's router makes a replica death invisible to clients; this module
makes it SHORT. The supervisor owns one replica subprocess end to end:

  * spawn it, then gate "serving" on a REAL `/healthz` readiness probe —
    a half-booted replica (still loading the checkpoint, still warming
    the compile ladder) holds a closed port, so the fleet router keeps it
    ejected and no traffic arrives before it can serve; the probe flip
    is the same edge that walks the router's half-open trial machinery.
  * on abnormal exit, restart with CAPPED EXPONENTIAL backoff
    (`backoff_base_s * 2^(n-1)`, capped at `backoff_max_s`; the streak
    resets after the child has served healthily for
    `stable_reset_s`) — paired with `serve.py --compile_cache`, the
    restarted replica warms from the persistent cache and rejoins in
    seconds instead of recompiling for minutes.
  * detect CRASH LOOPS: `crash_loop_exits` abnormal exits inside
    `crash_loop_window_s` means the replica is not going to heal by
    restarting (bad checkpoint, poison traffic, broken node) — hold it
    down for `hold_down_s` and emit a structured `crash_loop` log
    event so the fleet can alert instead of watching a restart storm
    (the CLI entry points expose no /metrics — alert on the JSONL log;
    the `dalle_supervisor_*` counters are for embedders that pass a
    registry, like the restart bench).

The loop is deterministic under test: the clock (`time_fn`), the child
factory (`spawn_fn`), and the health probe (`probe_fn`) are injectable
seams; `_on_exit` — the whole restart policy — is a pure-ish function of
(exit code, now, uptime) that tests drive directly to pin the backoff
schedule and the hold-down edge.

Run it: `serve.py --supervise ...` (the supervisor re-execs serve.py
minus the flag as its child) or
`python -m dalle_pytorch_tpu.serving.supervisor --health_url URL -- cmd
args...` for an arbitrary replica command.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, List, Optional


class ReplicaSupervisor:
    """Supervise one replica subprocess; see the module docstring for
    the policy. `run()` blocks until the child exits cleanly, `stop()`
    is requested, or a crash-loop hold-down is interrupted."""

    def __init__(
        self,
        argv: List[str],
        health_url: Optional[str] = None,
        registry=None,
        log=None,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        crash_loop_exits: int = 3,
        crash_loop_window_s: float = 60.0,
        hold_down_s: float = 300.0,
        stable_reset_s: Optional[float] = None,
        ready_timeout_s: float = 900.0,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 2.0,
        time_fn: Callable[[], float] = time.monotonic,
        spawn_fn: Optional[Callable] = None,
        probe_fn: Optional[Callable[[], bool]] = None,
        max_restarts: Optional[int] = None,
        spool_dir=None,
        spool_notify_url: Optional[str] = None,
    ):
        assert argv, "supervisor needs a child command"
        assert backoff_base_s > 0 and backoff_max_s >= backoff_base_s
        assert crash_loop_exits >= 2, (
            "crash_loop_exits < 2 would hold down on the FIRST crash — "
            "use a plain non-restarting runner for that"
        )
        self.argv = list(argv)
        self.health_url = health_url
        self.log = log
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.crash_loop_exits = int(crash_loop_exits)
        self.crash_loop_window_s = float(crash_loop_window_s)
        self.hold_down_s = float(hold_down_s)
        # a child that served healthily this long has broken the streak:
        # the next failure backs off from the base again
        self.stable_reset_s = (
            float(crash_loop_window_s) if stable_reset_s is None
            else float(stable_reset_s)
        )
        self.ready_timeout_s = float(ready_timeout_s)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._now = time_fn
        self._spawn_fn = spawn_fn
        self._probe_fn = probe_fn
        self.max_restarts = max_restarts
        # decode-state migration (serving/migrate.py): the replica's
        # crash-beacon spool directory and the router URL it is handed
        # to once the restarted child is READY — a SIGKILLed replica's
        # in-flight progress then resumes fleet-side instead of being
        # re-decoded from scratch
        self.spool_dir = spool_dir
        self.spool_notify_url = (
            spool_notify_url.rstrip("/") if spool_notify_url else None
        )
        self.spool_handoffs = 0
        self.spool_handoff_errors = 0
        #: the DEAD child's journal, captured between its exit and the
        #: respawn (the only window where nobody writes the file): the
        #: restarted child's own first beacon wholesale-replaces the
        #: journal, so reading after it serves would lose the crash
        #: checkpoints — and clearing after it serves would delete the
        #: NEW child's live progress
        self._pending_spool: dict = {}

        self._stop = threading.Event()
        self.child = None
        self.state = "idle"  # starting|serving|backoff|held_down|stopped
        #: respawns after an abnormal exit (restart #N is the Nth respawn)
        self.restarts = 0
        self.crash_loops = 0
        self.last_exit_code: Optional[int] = None
        self.last_exit_reason: Optional[str] = None
        #: spawn-to-/healthz-200 of the most recent (re)start — the
        #: time-to-rejoin number the restart bench reports
        self.last_ready_s: Optional[float] = None
        self.last_backoff_s: Optional[float] = None
        self._consec_failures = 0
        self._exit_times: deque = deque()

        self._m_restarts = self._m_crash_loops = self._m_ready = None
        if registry is not None:
            self._m_restarts = registry.counter(
                "dalle_supervisor_restarts_total",
                "replica subprocess respawns after an abnormal exit",
            )
            self._m_crash_loops = registry.counter(
                "dalle_supervisor_crash_loops_total",
                "crash-loop hold-downs (N abnormal exits inside the "
                "window; the replica is held out of rotation)",
            )
            self._m_ready = registry.gauge(
                "dalle_supervisor_time_to_ready_seconds",
                "spawn-to-healthy of the most recent replica (re)start",
            )

    # ------------------------------------------------------------- seams

    def _spawn(self):
        if self._spawn_fn is not None:
            return self._spawn_fn()
        return subprocess.Popen(self.argv)

    def _probe(self) -> bool:
        """One readiness probe: /healthz 200. A missing health_url
        degrades to process-aliveness gating (readiness = spawned)."""
        if self._probe_fn is not None:
            return bool(self._probe_fn())
        if self.health_url is None:
            return True
        try:
            with urllib.request.urlopen(
                self.health_url, timeout=self.probe_timeout_s
            ) as resp:
                return resp.status == 200
        except Exception:
            return False

    def _event(self, event: str, **fields) -> None:
        if self.log is not None:
            self.log.event(event, **fields)

    # ------------------------------------------------------------ policy

    def backoff_schedule(self, n: int) -> float:
        """Delay before the nth consecutive restart (1-based): capped
        exponential."""
        assert n >= 1
        return min(
            self.backoff_base_s * (2 ** (n - 1)), self.backoff_max_s
        )

    def _on_exit(self, code: int, now: float, uptime_s: float,
                 was_ready: bool) -> Optional[float]:
        """The whole restart policy, clock-driven and directly testable:
        record one child exit, return the restart delay in seconds — or
        None for a clean exit (the supervisor is done)."""
        self.last_exit_code = code
        self.last_exit_reason = (
            "clean" if code == 0
            else f"signal {-code}" if code < 0
            else f"exit {code}"
        )
        if code == 0:
            return None
        if was_ready and uptime_s >= self.stable_reset_s:
            # a long-healthy child failing is a fresh incident, not the
            # continuation of a boot-failure streak
            self._consec_failures = 0
        self._consec_failures += 1
        self._exit_times.append(now)
        while (
            self._exit_times
            and now - self._exit_times[0] > self.crash_loop_window_s
        ):
            self._exit_times.popleft()
        if len(self._exit_times) >= self.crash_loop_exits:
            self.crash_loops += 1
            if self._m_crash_loops is not None:
                self._m_crash_loops.inc()
            self._event(
                "crash_loop",
                exits=len(self._exit_times),
                window_s=self.crash_loop_window_s,
                hold_down_s=self.hold_down_s,
                last_exit=self.last_exit_reason,
            )
            self._exit_times.clear()
            self.state = "held_down"
            self.last_backoff_s = self.hold_down_s
            return self.hold_down_s
        self.state = "backoff"
        self.last_backoff_s = self.backoff_schedule(self._consec_failures)
        return self.last_backoff_s

    # -------------------------------------------------------------- loop

    def _wait_ready(self, spawned_at: float) -> bool:
        """Poll /healthz until the child answers 200, dies, or the ready
        timeout passes. Returns readiness; sets `last_ready_s`."""
        deadline = spawned_at + self.ready_timeout_s
        while not self._stop.is_set():
            if self.child is not None and self.child.poll() is not None:
                return False  # died while booting
            if self._probe():
                self.last_ready_s = self._now() - spawned_at
                if self._m_ready is not None:
                    self._m_ready.set(self.last_ready_s)
                return True
            if self._now() >= deadline:
                return False
            self._stop.wait(self.probe_interval_s)
        return False

    def run(self) -> int:
        """Supervise until the child exits cleanly or `stop()` — returns
        the child's final exit code (or 0 when stopped)."""
        while not self._stop.is_set():
            self.state = "starting"
            if self.restarts == 0 and self.spool_dir is not None:
                # first boot: a leftover journal is a PREVIOUS process
                # lifetime's state whose clients are long gone — clear
                # it BEFORE the child can serve (not at the ready probe,
                # which may lag the child's first own beacon)
                self._clear_spool()
            spawned_at = self._now()
            self.child = self._spawn()
            self._event(
                "replica_start",
                pid=getattr(self.child, "pid", None),
                restarts=self.restarts,
            )
            was_ready = self._wait_ready(spawned_at)
            if was_ready:
                self.state = "serving"
                self._event(
                    "replica_ready",
                    pid=getattr(self.child, "pid", None),
                    time_to_ready_s=round(self.last_ready_s or 0.0, 3),
                    restarts=self.restarts,
                )
                if self.restarts > 0:
                    # hand the crash-captured journal (read between the
                    # dead child's exit and this respawn — see
                    # _pending_spool) to the fleet router the moment the
                    # RESTARTED child serves again: in-flight requests
                    # the crash interrupted resume from the journaled
                    # checkpoints instead of from scratch
                    self._handoff_spool()
            hung_boot = False
            if not was_ready and not self._stop.is_set() \
                    and self.child.poll() is None:
                # HUNG boot: the child is alive but never answered
                # /healthz inside ready_timeout_s (wedged checkpoint
                # load, dead NFS). Recycle it through the normal
                # abnormal-exit path — without this kill, _wait_exit
                # would block forever and the crash-fast machinery
                # (backoff, crash-loop hold-down) never engages for
                # hung (vs crashed) children.
                hung_boot = True
                self._event(
                    "replica_ready_timeout",
                    pid=getattr(self.child, "pid", None),
                    ready_timeout_s=self.ready_timeout_s,
                )
                self._kill_child()
            code = self._wait_exit()
            now = self._now()
            uptime = now - spawned_at
            if self._stop.is_set():
                break
            if hung_boot and code == 0:
                # a recycled hung boot must count as a FAILURE even when
                # the child honored SIGTERM — exit 0 here would end
                # supervision with the replica never having served
                code = 1
            delay = self._on_exit(code, now, uptime, was_ready)
            self._event(
                "replica_exit",
                code=code, reason=self.last_exit_reason,
                uptime_s=round(uptime, 3), was_ready=was_ready,
                restart_in_s=delay,
                crash_loop=self.state == "held_down",
            )
            if delay is None:
                self.state = "stopped"
                return code
            if self.spool_dir is not None:
                # capture the dead child's journal NOW — the only window
                # where nobody writes the file — and clear it so the
                # restarted child's beacons start fresh; the captured
                # bundle is handed to the router once the restart is
                # ready (new entries merge over older pending ones)
                self._pending_spool.update(self._read_spool())
                self._clear_spool()
            if (
                self.max_restarts is not None
                and self.restarts >= self.max_restarts
            ):
                self.state = "stopped"
                return code
            self._stop.wait(delay)
            if self._stop.is_set():
                break
            self.restarts += 1
            if self._m_restarts is not None:
                self._m_restarts.inc()
        self.state = "stopped"
        return 0

    def _wait_exit(self) -> int:
        """Block until the child exits; interruptible by stop() (which
        terminates the child)."""
        child = self.child
        while not self._stop.is_set():
            code = child.poll()
            if code is not None:
                return code
            # short poll keeps stop() responsive without a second thread
            try:
                return child.wait(timeout=0.2)
            except subprocess.TimeoutExpired:
                continue
            except Exception:
                time.sleep(0.05)
        return child.poll() if child.poll() is not None else 0

    def _kill_child(self, term_timeout_s: float = 15.0) -> None:
        """SIGTERM the child (serve.py drains on it), escalate to
        SIGKILL past the timeout. Best-effort, never raises."""
        child = self.child
        if child is None or child.poll() is not None:
            return
        try:
            child.terminate()
            try:
                child.wait(timeout=term_timeout_s)
            except Exception:
                child.kill()
                child.wait(timeout=5.0)
        except Exception:
            pass

    def stop(self, term_timeout_s: float = 15.0) -> None:
        """Graceful stop: end supervision and take the child down."""
        self._stop.set()
        if self.child is None or self.child.poll() is not None:
            return
        self._kill_child(term_timeout_s)
        self._event("supervisor_stop", exit_code=self.child.poll())

    # ----------------------------------------------------- spool hand-off

    def _read_spool(self):
        """{key: wire} from the replica's crash-beacon journal; {} when
        unarmed/empty. Never raises (a sick spool volume must not stop
        supervision)."""
        if self.spool_dir is None:
            return {}
        try:
            from dalle_pytorch_tpu.serving.migrate import (
                CheckpointSpool,
                to_wire,
            )

            spool = CheckpointSpool(self.spool_dir)
            return {k: to_wire(b) for k, b in spool.read().items()}
        except Exception as exc:
            self._event("spool_read_failed", error=repr(exc))
            return {}

    def _clear_spool(self) -> None:
        if self.spool_dir is None:
            return
        try:
            from dalle_pytorch_tpu.serving.migrate import CheckpointSpool

            CheckpointSpool(self.spool_dir).clear()
        except Exception:
            pass

    def _replica_identity(self) -> Optional[str]:
        """The supervised replica's fleet identity for spool attribution:
        `host-port` derived from the health URL — the same name the
        router derives for a bare replica URL, so `migrated_from` on
        crash-path resumes joins /debug/replicas instead of carrying a
        /healthz URL."""
        if not self.health_url:
            return None
        try:
            from urllib.parse import urlsplit

            parts = urlsplit(self.health_url)
            return f"{parts.hostname}-{parts.port or 80}"
        except Exception:
            return None

    def _post_spool(self, payload: dict) -> None:
        """The one hand-off socket touch (stubbed in tests): POST the
        spool bundle to the router's /admin/spool."""
        req = urllib.request.Request(
            self.spool_notify_url + "/admin/spool",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.probe_timeout_s):
            pass

    def _handoff_spool(self) -> None:
        """Hand the crash-captured journal (`_pending_spool`, read
        between the dead child's exit and the respawn) to the fleet
        router. The capture survives an unreachable router — the next
        ready cycle tries again; it is dropped only after a successful
        POST (each entry resumes at most once)."""
        bundle = dict(self._pending_spool)
        if not bundle or self.spool_notify_url is None:
            if bundle:
                self._event(
                    "spool_handoff_skipped", checkpoints=len(bundle),
                    reason="no --spool_notify router URL",
                )
            return
        try:
            self._post_spool({
                "replica": self._replica_identity(),
                "checkpoints": bundle,
            })
        except Exception as exc:
            self.spool_handoff_errors += 1
            self._event(
                "spool_handoff_failed", checkpoints=len(bundle),
                error=repr(exc),
            )
            return
        self.spool_handoffs += 1
        self._event("spool_handoff", checkpoints=len(bundle))
        self._pending_spool.clear()

    # ------------------------------------------------------------- views

    def detail(self) -> dict:
        return {
            "state": self.state,
            "pid": getattr(self.child, "pid", None),
            "restarts": self.restarts,
            "crash_loops": self.crash_loops,
            "consecutive_failures": self._consec_failures,
            "last_exit_code": self.last_exit_code,
            "last_exit_reason": self.last_exit_reason,
            "last_ready_s": self.last_ready_s,
            "last_backoff_s": self.last_backoff_s,
            "spool_handoffs": self.spool_handoffs,
            "spool_handoff_errors": self.spool_handoff_errors,
        }


def supervise_serve(args, argv: Optional[List[str]]) -> int:
    """`serve.py --supervise`: re-exec serve.py minus the flag as the
    supervised child, health-gated on the replica's own /healthz. Needs
    an explicit --port (the supervisor must know where to probe)."""
    import os

    from dalle_pytorch_tpu.obs.logging import StructuredLog

    raw = list(sys.argv[1:] if argv is None else argv)
    # strip the supervisor-only flags: the child is a plain replica (it
    # keeps --checkpoint_spool — the journal is ITS job; the hand-off
    # is ours)
    child: List[str] = []
    skip = False
    for a in raw:
        if skip:
            skip = False
            continue
        if a == "--supervise" or a.startswith("--spool_notify="):
            continue
        if a == "--spool_notify":
            skip = True
            continue
        child.append(a)
    child_argv = [sys.executable, os.path.abspath(sys.argv[0])] + child
    log = StructuredLog(
        component="dalle.supervisor",
        site=getattr(args, "trace_site", None),
    )
    sup = ReplicaSupervisor(
        child_argv,
        health_url=f"http://{args.host}:{args.port}/healthz",
        log=log,
        spool_dir=getattr(args, "checkpoint_spool", None),
        spool_notify_url=getattr(args, "spool_notify", None),
    )
    return _run_with_signals(sup, "supervisor")


def _run_with_signals(sup: ReplicaSupervisor, tag: str) -> int:
    import signal

    def _stop(signum, frame):
        print(f"[{tag}] signal {signum}: stopping replica", flush=True)
        threading.Thread(target=sup.stop, daemon=True).start()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    code = sup.run()
    print(f"[{tag}] done: {json.dumps(sup.detail())}", flush=True)
    return code


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Supervise a replica command: restart on abnormal "
        "exit with capped exponential backoff, crash-loop hold-down, "
        "readiness gated on /healthz."
    )
    p.add_argument("--health_url", type=str, default=None,
                   help="replica /healthz URL; readiness (and "
                   "time-to-rejoin accounting) gates on it answering 200")
    p.add_argument("--backoff_base_s", type=float, default=0.5)
    p.add_argument("--backoff_max_s", type=float, default=30.0)
    p.add_argument("--crash_loop_exits", type=int, default=3,
                   help="abnormal exits inside the window that trigger "
                   "a hold-down instead of another fast restart")
    p.add_argument("--crash_loop_window_s", type=float, default=60.0)
    p.add_argument("--hold_down_s", type=float, default=300.0)
    p.add_argument("--ready_timeout_s", type=float, default=900.0)
    p.add_argument("--spool_dir", type=str, default=None,
                   help="the replica's --checkpoint_spool directory; "
                   "after a restart reaches ready, its journaled "
                   "decode-state checkpoints are handed to the router")
    p.add_argument("--spool_notify", type=str, default=None, metavar="URL",
                   help="fleet router base URL to POST the spool to "
                   "(/admin/spool) after a restart")
    p.add_argument("--site", type=str, default=None,
                   help="structured-log site identity")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="replica command after `--`, e.g. "
                   "-- python serve.py --dalle_path ... --port 8000")
    args = p.parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("need a replica command after --")

    from dalle_pytorch_tpu.obs.logging import StructuredLog

    sup = ReplicaSupervisor(
        cmd,
        health_url=args.health_url,
        log=StructuredLog(component="dalle.supervisor", site=args.site),
        backoff_base_s=args.backoff_base_s,
        backoff_max_s=args.backoff_max_s,
        crash_loop_exits=args.crash_loop_exits,
        crash_loop_window_s=args.crash_loop_window_s,
        hold_down_s=args.hold_down_s,
        ready_timeout_s=args.ready_timeout_s,
        spool_dir=args.spool_dir,
        spool_notify_url=args.spool_notify,
    )
    return _run_with_signals(sup, "supervisor")


if __name__ == "__main__":
    sys.exit(main())
