"""GenerationEngine: fixed-shape compiled sampling behind a dynamic API.

XLA compiles one program per input shape, so a serving layer must not let
arbitrary request counts reach the sampler — every distinct batch size
would trigger a fresh (expensive, possibly remote) compile. The engine
therefore owns a small ladder of batch shapes (default {1, 4, 8}), rounds
every micro-batch UP to the nearest rung by padding with copies of row 0,
and slices the padding back off. Per-request sampling parameters (seed /
temperature / top-k) ride along as traced arrays
(`models/dalle.py:generate_images_cached_batched`), so the padded rows
cost compute but never another compile, and a request's RNG stream is
independent of which batch it lands in.

`warmup()` runs one dummy batch per rung at startup so the first real
request never pays compilation latency; compile-cache hits/misses are
counted into the shared metrics registry.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np


@dataclass
class SampleSpec:
    """One batch row: a tokenized prompt plus its sampling parameters.

    `top_k` follows the CLI/reference convention: the FRACTION of the
    vocabulary to drop (0.9 keeps the top 10%).
    """

    text_ids: np.ndarray  # [text_seq_len] int32
    seed: int = 0
    temperature: float = 1.0
    top_k: float = 0.9


@dataclass
class EngineStats:
    compiled_shapes: Tuple[int, ...] = ()
    batches: int = 0
    rows_generated: int = 0
    rows_padded: int = 0


class GenerationEngine:
    """Batched text→image generation over a fixed ladder of compiled shapes.

    Parameters
    ----------
    model, variables : the DALLE module and its checkpoint params.
    vae, vae_params : optional pixel decoder. A `DiscreteVAE` is fused into
        the sampler program (tokens AND pixels from one dispatch); any
        other object with a host-side `.decode(tokens)` is applied after
        sampling; None returns tokens only.
    batch_shapes : compiled batch sizes, ascending after dedup. Requests
        larger than the top rung are the batcher's problem (it never
        assembles more rows than `max_batch`).
    cond_scale : classifier-free guidance scale, engine-wide (a per-request
        scale would double the compiled-shape ladder; revisit if needed).
    clip, clip_params : optional CLIP reranker (`models/clip.py:rerank`).
    tokenizer : host-side tokenizer; required for `tokenize()` / reranking.
    registry : MetricsRegistry for compile/warmup counters.
    """

    def __init__(
        self,
        model,
        variables,
        vae=None,
        vae_params=None,
        batch_shapes: Sequence[int] = (1, 4, 8),
        cond_scale: float = 1.0,
        clip=None,
        clip_params=None,
        tokenizer=None,
        registry=None,
        cfg=None,
    ):
        assert batch_shapes, "need at least one compiled batch shape"
        self.model = model
        self.variables = variables
        self.vae = vae
        self.vae_params = vae_params
        self.batch_shapes = tuple(sorted(set(int(b) for b in batch_shapes)))
        assert all(b >= 1 for b in self.batch_shapes)
        self.max_batch = self.batch_shapes[-1]
        self.cond_scale = float(cond_scale)
        self.clip = clip
        self.clip_params = clip_params
        self.tokenizer = tokenizer
        self.cfg = cfg
        self._warm = set()
        self._lock = threading.Lock()  # one sampler dispatch at a time
        self.stats = EngineStats(compiled_shapes=())
        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._compile_miss = registry.counter(
            "dalle_serving_engine_compile_misses_total",
            "sampler dispatches that had to compile a new batch shape",
        )
        self._compile_hit = registry.counter(
            "dalle_serving_engine_compile_hits_total",
            "sampler dispatches served by an already-compiled batch shape",
        )
        self._compile_seconds = registry.histogram(
            "dalle_serving_engine_compile_seconds",
            "wall time of compiling (warmup) dispatches",
        )

    # ------------------------------------------------------------- shapes

    def pick_shape(self, n: int) -> int:
        """Smallest compiled rung that fits n rows."""
        assert 1 <= n <= self.max_batch, (
            f"batch of {n} rows exceeds the engine's max shape "
            f"{self.max_batch}; the batcher must cap at max_batch"
        )
        for b in self.batch_shapes:
            if n <= b:
                return b
        return self.max_batch  # unreachable given the assert

    @property
    def image_seq_len(self) -> int:
        return self.model.image_seq_len

    def _keep_k(self, top_k: float) -> int:
        """Fractional drop threshold -> per-row keep count, matching
        `ops/sampling.py:top_k_filter` exactly so engine results agree with
        the static-parameter sampler's filtering rule."""
        v = self.model.total_tokens
        frac = min(max(float(top_k), 0.0), 1.0)
        return max(int((1.0 - frac) * v), 1)

    # ----------------------------------------------------------- generate

    def tokenize(self, prompt: str) -> np.ndarray:
        assert self.tokenizer is not None, "engine built without a tokenizer"
        ids = self.tokenizer.tokenize(
            prompt, self.model.text_seq_len, truncate_text=True
        )
        return np.asarray(ids[0], dtype=np.int32)

    def warmup(self, shapes: Optional[Sequence[int]] = None) -> None:
        """Compile every batch rung up front (one dummy batch each)."""
        text_seq = self.model.text_seq_len
        for b in shapes or self.batch_shapes:
            dummy = [
                SampleSpec(np.zeros(text_seq, np.int32), seed=i)
                for i in range(b)
            ]
            self.generate(dummy)

    def generate(self, specs: Sequence[SampleSpec]):
        """Run one micro-batch. Returns (tokens [n, image_seq_len] np.int32,
        pixels [n, H, W, 3] float in [0, 1] or None)."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import generate_images_cached_batched
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        n = len(specs)
        shape = self.pick_shape(n)
        pad = shape - n
        rows = list(specs) + [specs[0]] * pad

        text = np.stack([np.asarray(s.text_ids, np.int32) for s in rows])
        assert text.shape == (shape, self.model.text_seq_len), (
            f"prompt rows must be [{self.model.text_seq_len}] token ids, "
            f"got batch {text.shape}"
        )
        seeds = np.asarray([int(s.seed) & 0x7FFFFFFF for s in rows], np.int32)
        temps = np.asarray([s.temperature for s in rows], np.float32)
        keep = np.asarray([self._keep_k(s.top_k) for s in rows], np.int32)

        fused = isinstance(self.vae, DiscreteVAE)
        with self._lock:
            is_warm = shape in self._warm
            (self._compile_hit if is_warm else self._compile_miss).inc()
            t0 = time.perf_counter()
            out = generate_images_cached_batched(
                self.model, self.variables, jnp.asarray(text),
                seeds, temps, keep,
                cond_scale=self.cond_scale,
                vae=self.vae if fused else None,
                vae_params=self.vae_params if fused else None,
            )
            if fused:
                toks, pixels = out
                toks = np.asarray(toks)
                pixels = np.asarray(pixels) * 0.5 + 0.5  # un-normalize
            else:
                toks = np.asarray(out)
                pixels = None
            if not is_warm:
                self._compile_seconds.observe(time.perf_counter() - t0)
                self._warm.add(shape)
                self.stats.compiled_shapes = tuple(sorted(self._warm))
            self.stats.batches += 1
            self.stats.rows_generated += n
            self.stats.rows_padded += pad

        toks = toks[:n]
        if pixels is None and self.vae is not None:
            # pretrained wrappers decode host-side to [0, 1] already;
            # decode only the real rows — padding never leaves the sampler
            pixels = np.asarray(self.vae.decode(toks))
        else:
            pixels = None if pixels is None else pixels[:n]
        if pixels is not None:
            pixels = np.clip(pixels, 0.0, 1.0)
        return toks, pixels

    # ------------------------------------------------------------- rerank

    def rerank(self, prompt: str, images: np.ndarray):
        """Sort one request's images best-first by CLIP similarity.

        Returns (sorted_images, scores, order) where `order` maps the
        sorted position back to the original row index — callers carrying
        parallel arrays (tokens, seeds) must apply it too. Identity with
        zero scores when no CLIP checkpoint is loaded.
        """
        if self.clip is None:
            return (
                images,
                np.zeros(len(images), np.float32),
                np.arange(len(images)),
            )
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.clip import rerank as clip_rerank

        assert self.tokenizer is not None, "reranking needs a tokenizer"
        # mismatches would fail silently (XLA gather clamps OOB indices)
        assert images.shape[1] == self.clip.visual_image_size, (
            f"CLIP checkpoint expects {self.clip.visual_image_size}px images "
            f"but the VAE decodes {images.shape[1]}px"
        )
        assert self.tokenizer.vocab_size <= self.clip.num_text_tokens, (
            f"tokenizer vocab {self.tokenizer.vocab_size} exceeds CLIP "
            f"num_text_tokens {self.clip.num_text_tokens}"
        )
        clip_ids = self.tokenizer.tokenize(
            prompt, self.clip.text_seq_len, truncate_text=True
        )
        sorted_imgs, scores, order = clip_rerank(
            self.clip,
            {"params": self.clip_params},
            jnp.asarray(clip_ids),
            jnp.asarray(images),
            text_mask=jnp.asarray(clip_ids != 0),
        )
        return np.asarray(sorted_imgs), np.asarray(scores), np.asarray(order)


def engine_from_checkpoint(
    dalle_path: str,
    clip_path: Optional[str] = None,
    batch_shapes: Sequence[int] = (1, 4, 8),
    cond_scale: float = 1.0,
    registry=None,
):
    """Build a `GenerationEngine` from a single-file DALLE checkpoint.

    The loading sequence (VAE reconstruction, tokenizer, ring-attention
    downgrade for decode) was lifted from `generate.py`, which now calls
    this instead — CLI and server share one code path by construction.
    """
    from pathlib import Path

    from dalle_pytorch_tpu.training.pipeline import (
        build_tokenizer, dalle_from_config, dvae_from_hparams,
        load_dalle_checkpoint,
    )

    ckpt_path = Path(dalle_path)
    assert ckpt_path.exists(), f"trained DALL-E {ckpt_path} must exist"
    cfg, dalle_params, vae_params, meta, _ = load_dalle_checkpoint(str(ckpt_path))

    assert meta.get("vae_class_name") == "DiscreteVAE" or vae_params is None, (
        "checkpoint was trained with a pretrained VAE wrapper; provide it"
    )
    if vae_params is None:
        from dalle_pytorch_tpu.training.pipeline import build_vae

        vae, vae_params = build_vae(cfg)
    else:
        assert meta.get("vae_hparams"), "checkpoint missing vae_hparams"
        vae = dvae_from_hparams(meta["vae_hparams"])
    fmap = vae.image_size // (2 ** vae.num_layers)

    tokenizer = build_tokenizer(cfg)
    if cfg.model.attn_impl == "ring":
        # ring attention is a training-time layout (sequence sharded over
        # the mesh sp axis); KV-cached decode never runs it, so a
        # ring-trained checkpoint generates with the dense/auto kernel
        cfg.model.attn_impl = "auto"
    model = dalle_from_config(
        cfg, num_image_tokens=vae.num_tokens, image_fmap_size=fmap,
        vocab_size=max(tokenizer.vocab_size, 1),
    )

    clip = clip_params = None
    if clip_path:
        from dalle_pytorch_tpu.training.pipeline import load_clip_checkpoint

        clip, clip_params = load_clip_checkpoint(clip_path)

    return GenerationEngine(
        model=model,
        variables={"params": dalle_params},
        vae=vae,
        vae_params=vae_params,
        batch_shapes=batch_shapes,
        cond_scale=cond_scale,
        clip=clip,
        clip_params=clip_params,
        tokenizer=tokenizer,
        registry=registry,
        cfg=cfg,
    )
