"""GenerationEngine: fixed-shape compiled sampling behind a dynamic API.

XLA compiles one program per input shape, so a serving layer must not let
arbitrary request counts reach the sampler — every distinct batch size
would trigger a fresh (expensive, possibly remote) compile. The engine
therefore owns a small ladder of batch shapes (default {1, 4, 8}), rounds
every micro-batch UP to the nearest rung by padding with copies of row 0,
and slices the padding back off. Per-request sampling parameters (seed /
temperature / top-k) ride along as traced arrays
(`models/dalle.py:generate_images_cached_batched`), so the padded rows
cost compute but never another compile, and a request's RNG stream is
independent of which batch it lands in.

`warmup()` runs one dummy batch per rung at startup so the first real
request never pays compilation latency; compile-cache hits/misses are
counted into the shared metrics registry.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np


@dataclass
class SampleSpec:
    """One batch row: a tokenized prompt plus its sampling parameters.

    `top_k` follows the CLI/reference convention: the FRACTION of the
    vocabulary to drop (0.9 keeps the top 10%).

    `resume_tokens`/`resume_pos` carry a mid-decode resume prefix
    (decode-state migration / preemption): when `resume_pos > 0` and the
    engine supports resume, admission re-prefills the prefix in one
    teacher-forced dispatch and decode continues from `resume_pos`
    instead of position 0. Engines without resume support ignore the
    fields — decode restarts at 0, which regenerates the identical
    tokens ((seed, position)-keyed RNG), just paying the re-decode.
    """

    text_ids: np.ndarray  # [text_seq_len] int32
    seed: int = 0
    temperature: float = 1.0
    top_k: float = 0.9
    resume_tokens: Optional[np.ndarray] = None  # [resume_pos] int32
    resume_pos: int = 0


@dataclass
class EngineStats:
    compiled_shapes: Tuple[int, ...] = ()
    batches: int = 0
    rows_generated: int = 0
    rows_padded: int = 0
    # warmup dispatches count here ONLY (plus the compile hit/miss/seconds
    # metrics) so traffic stats stay pure request accounting
    warmup_batches: int = 0


class GenerationEngine:
    """Batched text→image generation over a fixed ladder of compiled shapes.

    Parameters
    ----------
    model, variables : the DALLE module and its checkpoint params.
    vae, vae_params : optional pixel decoder. A `DiscreteVAE` is fused into
        the sampler program (tokens AND pixels from one dispatch); any
        other object with a host-side `.decode(tokens)` is applied after
        sampling; None returns tokens only.
    batch_shapes : compiled batch sizes, ascending after dedup. Requests
        larger than the top rung are the batcher's problem (it never
        assembles more rows than `max_batch`).
    cond_scale : classifier-free guidance scale, engine-wide (a per-request
        scale would double the compiled-shape ladder; revisit if needed).
    clip, clip_params : optional CLIP reranker (`models/clip.py:rerank`).
    tokenizer : host-side tokenizer; required for `tokenize()` / reranking.
    registry : MetricsRegistry for compile/warmup counters.
    """

    def __init__(
        self,
        model,
        variables,
        vae=None,
        vae_params=None,
        batch_shapes: Sequence[int] = (1, 4, 8),
        cond_scale: float = 1.0,
        clip=None,
        clip_params=None,
        tokenizer=None,
        registry=None,
        cfg=None,
    ):
        assert batch_shapes, "need at least one compiled batch shape"
        self.model = model
        self.variables = variables
        self.vae = vae
        self.vae_params = vae_params
        self.batch_shapes = tuple(sorted(set(int(b) for b in batch_shapes)))
        assert all(b >= 1 for b in self.batch_shapes)
        self.max_batch = self.batch_shapes[-1]
        self.cond_scale = float(cond_scale)
        self.clip = clip
        self.clip_params = clip_params
        self.tokenizer = tokenizer
        self.cfg = cfg
        self._warm = set()
        self._lock = threading.Lock()  # one sampler dispatch at a time
        self.stats = EngineStats(compiled_shapes=())
        # device-telemetry seams (obs/vitals.py), both inert by default:
        # `vitals` is the dispatch clock the sampler thread reads (the
        # shared no-op singleton until an EngineVitals binds itself);
        # `cost_table` opts warmup into per-program cost capture (one
        # extra AOT compile per program) — attach BEFORE warmup()
        from dalle_pytorch_tpu.obs.vitals import NULL_VITALS

        self.vitals = NULL_VITALS
        self.cost_table = None
        # persistent compile cache (utils/compile_cache.py): when a
        # CompileCache is attached BEFORE warmup, every program in the
        # warmup ladder exports its AOT executable into the cache's
        # artifact store (sharing the cost table's one extra compile),
        # so the NEXT boot of this config is warm
        self.compile_cache = None
        # fault-injection seam (serving/faults.py): every dispatch calls
        # `_fault_point(program)`, a no-op until a test/chaos harness sets
        # a FaultInjector here — the injected failure then takes the SAME
        # recovery path (donated-state rebuild, batcher retry/fail-fast)
        # a real XLA error would
        self.faults = None
        if registry is None:
            from dalle_pytorch_tpu.training.metrics import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._compile_miss = registry.counter(
            "dalle_serving_engine_compile_misses_total",
            "sampler dispatches that had to compile a new batch shape",
        )
        self._compile_hit = registry.counter(
            "dalle_serving_engine_compile_hits_total",
            "sampler dispatches served by an already-compiled batch shape",
        )
        self._compile_seconds = registry.histogram(
            "dalle_serving_engine_compile_seconds",
            "wall time of compiling (warmup) dispatches",
        )

    def _fault_point(self, name: str) -> None:
        """Dispatch-site hook for the fault injector (inert when none is
        attached). Sits INSIDE each dispatch's vitals bracket — and inside
        `_replace_state`'s try for the donated ops — so an injected fault
        is indistinguishable from a real dispatch failure downstream."""
        if self.faults is not None:
            self.faults.on_dispatch(name)

    # -------------------------------------------------------------- vitals

    def _capture_cost(self, name: str, fn, *args) -> None:
        """The warmup AOT ladder: lower + compile `fn(*args)` ONCE and
        feed every attached consumer — the `ProgramCostTable` records the
        XLA cost/memory analysis, the `CompileCache` exports the
        serialized executable as the warm-boot artifact. AOT lowering
        wraps the already-jitted model op in an outer `jax.jit` —
        params/state ride as REAL arguments, never closure constants, so
        the lowered HLO matches the dispatched program's traffic.
        Warmup-only by construction (every call site is gated on its
        `_warmup` flag): the `.compile()` here is one extra backend
        compile that must never land on the serving path (and is itself
        a persistent-cache hit on a warm boot). Failures are recorded on
        the consumers, never raised — a backend without cost analysis or
        executable serialization must not break warmup."""
        table, cache = self.cost_table, self.compile_cache
        need_cost = table is not None and not table.has(name)
        need_export = cache is not None and cache.wants(name)
        if not (need_cost or need_export):
            return
        import jax

        # mesh-sharded engines hand their device labels through so the
        # table can attribute per-partition cost where jax exposes it
        # (ProgramCostTable.add; global-row fallback otherwise)
        mesh = getattr(self, "mesh", None)
        devices = (
            [f"{d.platform}:{d.id}" for d in mesh.devices.flat]
            if mesh is not None else None
        )
        try:
            compiled = jax.jit(fn).lower(*args).compile()
        except Exception as exc:
            if need_cost:
                table.record_error(name, exc)
            if need_export:
                cache.record_error(name, exc)
            return
        if need_cost:
            try:
                table.add(name, compiled, devices=devices)
            except Exception as exc:
                table.record_error(name, exc)
        if need_export:
            cache.export(name, compiled)

    def program_ladder(self) -> Tuple[str, ...]:
        """Names of every program `warmup()` compiles — the fixed-shape
        contract surface. The boot fingerprint hashes this list, so an
        engine growing a program invalidates stale warm-cache claims."""
        return tuple(f"generate:{b}" for b in self.batch_shapes)

    def resume_fingerprint(self) -> str:
        """Build identity a decode-state checkpoint must match to resume
        here (serving/migrate.py): `utils/compile_cache.boot_fingerprint`
        over jax version / backend / model config / program ladder, plus
        the model's repr (directly-constructed engines carry cfg=None,
        and two different toy models must not cross-resume). Computed
        once — a checkpoint from any drifted build becomes a counted
        clean-restart, never a corrupt resume."""
        if getattr(self, "_resume_fingerprint", None) is None:
            import jax

            from dalle_pytorch_tpu.utils.compile_cache import boot_fingerprint

            self._resume_fingerprint = boot_fingerprint(
                backend=jax.default_backend(),
                model_config=self.cfg,
                programs=self.program_ladder(),
                extra={"model": repr(self.model)},
            )
        return self._resume_fingerprint

    def state_dump(self) -> dict:
        """Host-side engine state for `/debug/state` and stall reports.
        Lock-free reads of host counters — a stalled engine holds its
        dispatch lock, and the dump must still render."""
        return {
            "engine": type(self).__name__,
            "batch_shapes": list(self.batch_shapes),
            "compiled_shapes": list(self.stats.compiled_shapes),
            "batches": self.stats.batches,
            "rows_generated": self.stats.rows_generated,
            "warmup_batches": self.stats.warmup_batches,
        }

    # ------------------------------------------------------------- shapes

    def pick_shape(self, n: int) -> int:
        """Smallest compiled rung that fits n rows."""
        assert 1 <= n <= self.max_batch, (
            f"batch of {n} rows exceeds the engine's max shape "
            f"{self.max_batch}; the batcher must cap at max_batch"
        )
        for b in self.batch_shapes:
            if n <= b:
                return b
        return self.max_batch  # unreachable given the assert

    @property
    def image_seq_len(self) -> int:
        return self.model.image_seq_len

    def _keep_k(self, top_k: float) -> int:
        """Fractional drop threshold -> per-row keep count, matching
        `ops/sampling.py:top_k_filter` exactly so engine results agree with
        the static-parameter sampler's filtering rule."""
        v = self.model.total_tokens
        frac = min(max(float(top_k), 0.0), 1.0)
        return max(int((1.0 - frac) * v), 1)

    # ----------------------------------------------------------- generate

    def tokenize(self, prompt: str) -> np.ndarray:
        assert self.tokenizer is not None, "engine built without a tokenizer"
        ids = self.tokenizer.tokenize(
            prompt, self.model.text_seq_len, truncate_text=True
        )
        return np.asarray(ids[0], dtype=np.int32)

    def warmup(self, shapes: Optional[Sequence[int]] = None) -> None:
        """Compile every batch rung up front (one dummy batch each).

        Warmup dispatches are tagged so they count only toward the compile
        metrics (hits/misses/seconds) and `stats.warmup_batches` — never
        toward `batches`/`rows_generated`/`rows_padded`, which dashboards
        read as real traffic."""
        text_seq = self.model.text_seq_len
        for b in shapes or self.batch_shapes:
            dummy = [
                SampleSpec(np.zeros(text_seq, np.int32), seed=i)
                for i in range(b)
            ]
            self.generate(dummy, _warmup=True)

    def generate(self, specs: Sequence[SampleSpec], _warmup: bool = False):
        """Run one micro-batch. Returns (tokens [n, image_seq_len] np.int32,
        pixels [n, H, W, 3] float in [0, 1] or None)."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dalle import generate_images_cached_batched
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        n = len(specs)
        shape = self.pick_shape(n)
        pad = shape - n
        rows = list(specs) + [specs[0]] * pad

        text = np.stack([np.asarray(s.text_ids, np.int32) for s in rows])
        assert text.shape == (shape, self.model.text_seq_len), (
            f"prompt rows must be [{self.model.text_seq_len}] token ids, "
            f"got batch {text.shape}"
        )
        seeds = np.asarray([int(s.seed) & 0x7FFFFFFF for s in rows], np.int32)
        temps = np.asarray([s.temperature for s in rows], np.float32)
        keep = np.asarray([self._keep_k(s.top_k) for s in rows], np.int32)

        fused = isinstance(self.vae, DiscreteVAE)
        prog = f"generate:{shape}"
        with self._lock:
            is_warm = shape in self._warm
            (self._compile_hit if is_warm else self._compile_miss).inc()
            t0 = time.perf_counter()
            self.vitals.dispatch_begin(prog)
            try:
                self._fault_point(prog)
                out = generate_images_cached_batched(
                    self.model, self.variables, jnp.asarray(text),
                    seeds, temps, keep,
                    cond_scale=self.cond_scale,
                    vae=self.vae if fused else None,
                    vae_params=self.vae_params if fused else None,
                )
                if fused:
                    toks, pixels = out
                    toks = np.asarray(toks)
                    pixels = np.asarray(pixels) * 0.5 + 0.5  # un-normalize
                else:
                    toks = np.asarray(out)
                    pixels = None
            finally:
                wall = time.perf_counter() - t0
                self.vitals.dispatch_end(prog, wall)
            if is_warm and self.cost_table is not None:
                # the np.asarray above synced the dispatch, so this wall
                # is real execution time — MFU-grade. Compiling (cold)
                # dispatches are excluded: their wall is compile latency.
                self.cost_table.record_wall(prog, wall)
            if not is_warm:
                self._compile_seconds.observe(time.perf_counter() - t0)
                self._warm.add(shape)
                self.stats.compiled_shapes = tuple(sorted(self._warm))
            if _warmup:
                # AFTER the dispatch, never before: lowering the sampler
                # inside an outer trace before its closure cache is
                # populated would bake tracers into `_jitted_sampler`'s
                # lru_cache (builders materialize constants at
                # closure-build time)
                self._capture_cost(
                    prog,
                    lambda v, vp, t, s, tm, k: (
                        generate_images_cached_batched(
                            self.model, v, t, s, tm, k,
                            cond_scale=self.cond_scale,
                            vae=self.vae if fused else None, vae_params=vp,
                        )
                    ),
                    self.variables, self.vae_params if fused else None,
                    jnp.asarray(text), seeds, temps, keep,
                )
                self.stats.warmup_batches += 1
            else:
                self.stats.batches += 1
                self.stats.rows_generated += n
                self.stats.rows_padded += pad

        toks = toks[:n]
        if pixels is None and self.vae is not None:
            # pretrained wrappers decode host-side to [0, 1] already;
            # decode only the real rows — padding never leaves the sampler
            pixels = np.asarray(self.vae.decode(toks))
        else:
            pixels = None if pixels is None else pixels[:n]
        if pixels is not None:
            pixels = np.clip(pixels, 0.0, 1.0)
        return toks, pixels

    # ------------------------------------------------------------- rerank

    def rerank(self, prompt: str, images: np.ndarray):
        """Sort one request's images best-first by CLIP similarity.

        Returns (sorted_images, scores, order) where `order` maps the
        sorted position back to the original row index — callers carrying
        parallel arrays (tokens, seeds) must apply it too. Identity with
        zero scores when no CLIP checkpoint is loaded.
        """
        if self.clip is None:
            return (
                images,
                np.zeros(len(images), np.float32),
                np.arange(len(images)),
            )
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.clip import rerank as clip_rerank

        assert self.tokenizer is not None, "reranking needs a tokenizer"
        # mismatches would fail silently (XLA gather clamps OOB indices)
        assert images.shape[1] == self.clip.visual_image_size, (
            f"CLIP checkpoint expects {self.clip.visual_image_size}px images "
            f"but the VAE decodes {images.shape[1]}px"
        )
        assert self.tokenizer.vocab_size <= self.clip.num_text_tokens, (
            f"tokenizer vocab {self.tokenizer.vocab_size} exceeds CLIP "
            f"num_text_tokens {self.clip.num_text_tokens}"
        )
        clip_ids = self.tokenizer.tokenize(
            prompt, self.clip.text_seq_len, truncate_text=True
        )
        sorted_imgs, scores, order = clip_rerank(
            self.clip,
            {"params": self.clip_params},
            jnp.asarray(clip_ids),
            jnp.asarray(images),
            text_mask=jnp.asarray(clip_ids != 0),
        )
        return np.asarray(sorted_imgs), np.asarray(scores), np.asarray(order)


def _pack_prefill_rows(rows, keep_k_of):
    """Host-side packing of (slot, SampleSpec) pairs into the batched
    prefill's dispatch arrays. Pure request-dataclass reads — deliberately
    outside the hotloop-marked engine methods, which must stay free of
    anything TL002 could mistake for a device sync."""
    texts = np.stack([np.asarray(spec.text_ids, np.int32) for _, spec in rows])
    slots = np.asarray([s for s, _ in rows], np.int32)
    seeds = np.asarray(
        [int(spec.seed) & 0x7FFFFFFF for _, spec in rows], np.int32
    )
    temps = np.asarray([spec.temperature for _, spec in rows], np.float32)
    keep = np.asarray([keep_k_of(spec.top_k) for _, spec in rows], np.int32)
    return texts, slots, seeds, temps, keep


class SlotAllocator:
    """Host-side allocator for the continuous engine's fixed cache slots.

    Slots are just integers [0, n_slots); the decode program's batch rows.
    `alloc` hands out the lowest free slot (deterministic, test-friendly)
    and never aliases: a slot stays owned until `free`d. Exhaustion returns
    None — the batcher keeps the request queued until a retirement frees a
    slot. Not thread-safe by itself; the batcher worker is the only caller.
    """

    def __init__(self, n_slots: int):
        assert n_slots >= 1
        self.n_slots = int(n_slots)
        self._free = sorted(range(self.n_slots), reverse=True)
        self._in_use: set = set()

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        slot = self._free.pop()
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        assert slot in self._in_use, f"slot {slot} is not allocated"
        self._in_use.remove(slot)
        self._free.append(slot)
        self._free.sort(reverse=True)

    @property
    def n_active(self) -> int:
        return len(self._in_use)

    @property
    def n_free(self) -> int:
        return len(self._free)


class ContinuousEngine(GenerationEngine):
    """Continuous-batching decode: token-boundary admission over cache slots.

    Where `GenerationEngine.generate` runs a whole `image_seq_len` decode
    scan per micro-batch (a request arriving just after a flush waits an
    entire pass for its first token), this engine keeps ONE persistent
    decode state of `max_batch` cache slots and advances every live slot by
    `chunk_tokens` per jitted dispatch. The batcher admits prompts into
    free slots (one prefill dispatch each) and retires finished rows at
    chunk boundaries, so occupancy backfills mid-flight and time-to-first-
    token is bounded by ~one chunk instead of up to two full passes.

    Fixed-shape discipline is preserved: exactly four compiled programs —
    batched prefill (batch `prefill_batch`, slot indices traced), chunk
    step (batch `max_batch`), slot release, pixel decode (batch
    `max_batch`) — regardless of load. `chunk_tokens` is the latency/throughput knob: smaller chunks
    admit and retire sooner (lower TTFT) but pay more host round trips per
    image. `prefill_batch` is the admission-amortization knob: R pending
    requests at a chunk boundary cost ceil(R / prefill_batch) prefill
    dispatches (padded by repeating a real row — same trade as the
    micro-batch engine's padded rungs) instead of R batch-1 dispatches.

    Classifier-free guidance is engine-wide OFF here (cond_scale=1): a
    guided continuous batch needs a paired null-stream slot per row —
    doubling the decode program — so guided serving stays on the
    micro-batch engine for now.
    """

    def __init__(
        self,
        model,
        variables,
        vae=None,
        vae_params=None,
        max_batch: int = 8,
        chunk_tokens: int = 4,
        prefill_batch: int = 4,
        cond_scale: float = 1.0,
        clip=None,
        clip_params=None,
        tokenizer=None,
        registry=None,
        cfg=None,
        resume_enabled: bool = False,
        preview_enabled: bool = False,
        kv_dtype=None,
        decode_sparsity: str = "causal",
    ):
        assert float(cond_scale) == 1.0, (
            "ContinuousEngine does not support classifier-free guidance yet "
            "(a per-slot null stream would double the decode program); use "
            "the micro-batch GenerationEngine for cond_scale != 1"
        )
        assert int(chunk_tokens) >= 1
        assert decode_sparsity in ("causal", "policy"), (
            f"unknown decode_sparsity {decode_sparsity!r}; "
            "use 'causal' (dense-causal flash, the bit-identity default) "
            "or 'policy' (block-sparse flash from the model's static "
            "attention layouts)"
        )
        self.decode_sparsity = str(decode_sparsity)
        # int8 KV cache (--kv_dtype int8): clone the model so every slot-op
        # builder (they key the jit cache on the model) sees the quantized
        # cache layout; None keeps the bit-identical default path
        if kv_dtype is not None and getattr(model, "kv_dtype", None) is None:
            model = model.clone(kv_dtype=str(kv_dtype))
        # block-sparse decode (--decode_sparsity policy): bake the tile
        # width into the model clone (same builder-cache reasoning as
        # kv_dtype — and the boot fingerprint hashes the model repr, so a
        # sparse boot never resumes a causal compile cache); the bitmaps
        # themselves stay TRACED data, built per dispatch by the policy
        if (
            self.decode_sparsity == "policy"
            and getattr(model, "decode_sparse_block", None) is None
        ):
            from dalle_pytorch_tpu.models.attention import (
                DECODE_SPARSE_BLOCK,
            )

            model = model.clone(decode_sparse_block=DECODE_SPARSE_BLOCK)
        super().__init__(
            model=model,
            variables=variables,
            vae=vae,
            vae_params=vae_params,
            batch_shapes=(int(max_batch),),
            cond_scale=1.0,
            clip=clip,
            clip_params=clip_params,
            tokenizer=tokenizer,
            registry=registry,
            cfg=cfg,
        )
        # decode-state resume (serving/migrate.py): one extra compiled
        # program (teacher-forced re-prefill of prompt + generated
        # prefix) that admits a migrated/preempted row at its OWN
        # position instead of 0. Opt-in: the ladder, warmup and boot
        # fingerprint grow the `resume` program only when enabled.
        self.resume_enabled = bool(resume_enabled)
        # progressive previews (serving/streaming.py): one extra compiled
        # fill+decode program — undecoded grid positions filled with the
        # mean-codebook token, then the standard pixel decode — shared by
        # every streaming request. Opt-in like `resume`: the ladder,
        # warmup and boot fingerprint grow the `preview` program only
        # when enabled (serving boots enable it by default).
        self.preview_enabled = bool(preview_enabled)
        self.chunk_tokens = int(chunk_tokens)
        # admission never spans more slots than exist; 1 degrades to the
        # per-row admission of PR 2
        self.prefill_batch = max(1, min(int(prefill_batch), self.max_batch))
        #: host-side tile-liveness policy (None on the causal path): turns
        #: the model's static attention layouts into per-slot KV-tile
        #: bitmaps the chunk/prefill dispatches carry as traced data
        self._sparsity = None
        if self.decode_sparsity == "policy":
            from dalle_pytorch_tpu.serving.sparsity import (
                DecodeSparsityPolicy,
            )

            self._sparsity = DecodeSparsityPolicy(
                self.model, self.chunk_tokens, self.max_batch
            )
        self._state = self._fresh_state()
        self._m_slots = self.registry.gauge(
            "dalle_serving_slots_active",
            "continuous-engine cache slots currently decoding",
        )
        self._m_chunks = self.registry.counter(
            "dalle_serving_chunks_total",
            "decode chunk dispatches by the continuous engine",
        )
        self._m_prefills = self.registry.counter(
            "dalle_serving_prefills_total",
            "prompts prefilled into cache slots",
        )
        self._m_prefill_dispatches = self.registry.counter(
            "dalle_serving_prefill_dispatches_total",
            "batched prefill dispatches (each admits up to prefill_batch "
            "rows in one fixed-shape program)",
        )
        self._m_kv_bytes_slot = self.registry.gauge(
            "dalle_serving_kv_bytes_per_slot",
            "HBM bytes of KV cache (K/V + quantization scales) backing one "
            "decode slot — pool-sizing honesty: pages alone undercount the "
            "capacity win when --kv_dtype int8 shrinks each page",
        )
        self._m_kv_bytes_slot.set(self.kv_bytes_per_slot())
        self._m_kv_tiles_read = self.registry.counter(
            "dalle_serving_kv_tiles_read_total",
            "KV tiles the block-sparse decode kernel read (per chunk "
            "dispatch, summed over live rows and layers; zero on "
            "--decode_sparsity causal)",
        )
        self._m_kv_tiles_skipped = self.registry.counter(
            "dalle_serving_kv_tiles_skipped_total",
            "KV tiles the sparsity policy skipped that the length skip "
            "alone would have read — the policy's own DMA/compute savings",
        )
        self._decode_pixels_jit = None
        self._preview_jit = None
        self._preview_fill = None
        #: monotonic chunk-dispatch index (non-warmup), read by the
        #: batcher as span metadata so a trace's chunk spans can be lined
        #: up against engine-side dispatch accounting
        self.chunk_index = 0

    # --------------------------------------------------------- slot ops
    # All device work is serialized under the inherited engine lock; the
    # continuous batcher's single worker thread is the only caller.

    def _fresh_state(self):
        """Clean empty slot state — the subclass hook the paged engine
        overrides (rebuilding its host-side page tables alongside)."""
        from dalle_pytorch_tpu.models.dalle import init_slot_state

        # host mirrors of (img_pos, active), updated at every admission/
        # chunk/release: the sparsity policy derives each dispatch's tile
        # bitmaps from them without an extra device sync (the paged
        # subclass keeps the same pair for its allocator)
        self._host_pos = np.zeros(self.max_batch, np.int64)
        self._host_active = np.zeros(self.max_batch, bool)
        return init_slot_state(self.model, self.max_batch)

    def _kv_cache_bytes(self) -> int:
        """Total bytes of the K/V leaves (values + quantization scales)
        in the live decode state."""
        import jax

        total = 0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            self._state["cache"]
        )[0]:
            key = ""
            for p in reversed(path):
                k = getattr(p, "key", None)
                if k is not None:
                    key = str(k)
                    break
            if key in ("k", "v", "k_scale", "v_scale"):
                total += int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
        return total

    def kv_bytes_per_slot(self) -> int:
        """K/V (+ scale) bytes backing ONE decode slot. int8 pages cut
        this ~2x vs fp32 (the per-position fp32 scale adds 4 bytes per
        dim_head values), which is the slots-per-HBM-byte win the
        `dalle_serving_kv_bytes_per_slot` gauge makes visible."""
        return self._kv_cache_bytes() // self.max_batch

    def _replace_state(self, op, fault_tag: Optional[str] = None) -> None:
        """Run one state-transforming dispatch. The slot ops DONATE the
        state buffers (models/dalle.py), so on failure the old state is
        unusable — rebuild a clean empty one rather than bricking the
        engine (the batcher fails or retries the in-flight requests
        either way). `fault_tag` names the dispatch for the fault-
        injection seam; injected faults raise inside this try so they
        exercise the SAME rebuild path. Caller holds the lock."""
        try:
            if fault_tag is not None:
                self._fault_point(fault_tag)
            self._state = op(self._state)
        except BaseException:
            self._state = self._fresh_state()
            raise

    def _prefill_bitmap_kw(self) -> dict:
        """`block_bitmap=` kwarg for one prefill-shaped dispatch (empty on
        the causal path) — shared by the slotted/paged dispatch seams and
        their warmup cost captures so all four lower the same program."""
        if self._sparsity is None:
            return {}
        return {
            "block_bitmap": self._sparsity.prefill_bitmaps(
                self.prefill_batch
            )
        }

    def _chunk_bitmap_kw(self) -> dict:
        """`block_bitmap=` kwarg for one chunk dispatch, derived from the
        host position/liveness mirrors as of the chunk start."""
        if self._sparsity is None:
            return {}
        return {
            "block_bitmap": self._sparsity.chunk_bitmaps(
                self._host_pos, self._host_active
            )
        }

    def _prefill_op(self, s, texts, slots, seeds, temps, keep):
        """One batched-prefill dispatch over state `s` (subclass hook —
        the sharded engine runs its sharding-pinned program here)."""
        from dalle_pytorch_tpu.models.dalle import prefill_into_slots

        return prefill_into_slots(
            self.model, self.variables, s, texts, slots, seeds, temps,
            keep, **self._prefill_bitmap_kw(),
        )

    def _release_op(self, s, mask):
        """One slot-release dispatch (same subclass seam)."""
        from dalle_pytorch_tpu.models.dalle import release_slots

        return release_slots(self.model, s, mask)

    def prefill_slots(  # tracelint: hotloop
        self,
        assignments: Sequence[Tuple[int, SampleSpec]],
        _warmup: bool = False,
    ) -> None:
        """Admit up to `prefill_batch` (slot, prompt) pairs in ONE
        fixed-shape dispatch. Short batches pad by repeating the first
        pair — the duplicate rows re-write the same slot with identical
        content (see `models/dalle.py:prefill_into_slots`), so every
        admission, single or batched, runs the SAME compiled program."""
        n = len(assignments)
        assert 1 <= n <= self.prefill_batch, (
            f"{n} assignments exceed prefill_batch={self.prefill_batch}; "
            "the batcher must split admission waves"
        )
        rows = list(assignments) + [assignments[0]] * (self.prefill_batch - n)
        texts, slots, seeds, temps, keep = _pack_prefill_rows(
            rows, self._keep_k
        )
        assert texts.shape == (self.prefill_batch, self.model.text_seq_len), (
            f"prompt rows must be [{self.model.text_seq_len}] token ids, "
            f"got batch {texts.shape}"
        )
        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("prefill")
            try:
                self._replace_state(lambda s: self._prefill_op(
                    s, texts, slots, seeds, temps, keep,
                ), fault_tag="prefill")
            finally:
                wall = time.perf_counter() - t0
                self.vitals.dispatch_end("prefill", wall)
            for slot, _spec in assignments:
                self._host_pos[int(slot)] = 0
                self._host_active[int(slot)] = True
            if _warmup:
                # after the dispatch (see GenerationEngine.generate: a
                # pre-dispatch lowering would poison the sampler cache)
                from dalle_pytorch_tpu.models.dalle import prefill_into_slots

                spkw = self._prefill_bitmap_kw()
                self._capture_cost(
                    "prefill",
                    lambda v, s, t, sl, se, tm, k: prefill_into_slots(
                        self.model, v, s, t, sl, se, tm, k, **spkw,
                    ),
                    self.variables, self._state, texts, slots, seeds,
                    temps, keep,
                )
            if not _warmup:
                if self.cost_table is not None:
                    # async dispatch: this wall is host-side only, kept
                    # for the watchdog baseline but never exported as MFU
                    self.cost_table.record_wall("prefill", wall, synced=False)
                self._m_prefills.inc(n)
                self._m_prefill_dispatches.inc()

    def prefill_slot(  # tracelint: hotloop
        self, slot: int, spec: SampleSpec, _warmup: bool = False
    ) -> None:
        """Admit one prompt into `slot` — a 1-row `prefill_slots` wave
        (padded to the fixed prefill shape; no extra compiled program)."""
        self.prefill_slots([(slot, spec)], _warmup=_warmup)

    # ---------------------------------------------------- mid-decode resume

    @property
    def supports_resume(self) -> bool:
        """True when `resume_slots` may be called (the batcher's gate:
        without it, resume-prefixed specs fall back to a position-0
        prefill — bit-identical, just re-decoded)."""
        return self.resume_enabled

    def _pack_resume_rows(self, rows):
        """Resume-prefix arrays for one padded wave: [R, image_seq_len]
        token buffer (zeros beyond each prefix) + [R] positions."""
        img_tokens = np.zeros(
            (len(rows), self.image_seq_len), np.int32
        )
        img_pos = np.zeros(len(rows), np.int32)
        for r, (_slot, spec) in enumerate(rows):
            k = min(
                max(0, int(getattr(spec, "resume_pos", 0) or 0)),
                self.image_seq_len - 1,
            )
            toks = getattr(spec, "resume_tokens", None)
            if toks is None:
                k = 0
            else:
                toks = np.asarray(toks, np.int32)
                k = min(k, len(toks))
                img_tokens[r, :k] = toks[:k]
            img_pos[r] = k
        return img_tokens, img_pos

    def _resume_op(self, s, texts, img_tokens, img_pos, slots, seeds,
                   temps, keep):
        """One teacher-forced resume dispatch (subclass seam, like
        `_prefill_op`)."""
        from dalle_pytorch_tpu.models.dalle import resume_into_slots

        return resume_into_slots(
            self.model, self.variables, s, texts, img_tokens, img_pos,
            slots, seeds, temps, keep,
        )

    def resume_slots(  # tracelint: hotloop
        self,
        assignments: Sequence[Tuple[int, SampleSpec]],
        _warmup: bool = False,
    ) -> None:
        """Admit up to `prefill_batch` mid-decode rows — specs carrying
        `resume_tokens`/`resume_pos` — in ONE teacher-forced re-prefill
        dispatch: decode continues from each row's own position instead
        of 0 (`models/dalle.py:resume_into_slots`). Short waves pad by
        repeating the first pair, exactly like `prefill_slots`."""
        assert self.supports_resume, (
            "resume_slots on an engine built without resume_enabled — "
            "the program is not in the warmup ladder and would "
            "cold-compile mid-traffic"
        )
        n = len(assignments)
        assert 1 <= n <= self.prefill_batch, (
            f"{n} assignments exceed prefill_batch={self.prefill_batch}; "
            "the batcher must split admission waves"
        )
        rows = list(assignments) + [assignments[0]] * (self.prefill_batch - n)
        texts, slots, seeds, temps, keep = _pack_prefill_rows(
            rows, self._keep_k
        )
        img_tokens, img_pos = self._pack_resume_rows(rows)
        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("resume")
            try:
                self._replace_state(lambda s: self._resume_op(
                    s, texts, img_tokens, img_pos, slots, seeds, temps,
                    keep,
                ), fault_tag="resume")
            finally:
                wall = time.perf_counter() - t0
                self.vitals.dispatch_end("resume", wall)
            for (slot, _spec), p in zip(assignments, img_pos[:n]):
                self._host_pos[int(slot)] = int(p)
                self._host_active[int(slot)] = True
            if _warmup:
                from dalle_pytorch_tpu.models.dalle import resume_into_slots

                self._capture_cost(
                    "resume",
                    lambda v, s, t, it, ip, sl, se, tm, k: resume_into_slots(
                        self.model, v, s, t, it, ip, sl, se, tm, k,
                    ),
                    self.variables, self._state, texts, img_tokens,
                    img_pos, slots, seeds, temps, keep,
                )
            if not _warmup:
                if self.cost_table is not None:
                    self.cost_table.record_wall("resume", wall, synced=False)
                self._m_prefills.inc(n)
                self._m_prefill_dispatches.inc()

    def _pre_chunk(self) -> None:
        """Subclass hook before the chunk dispatch (the paged engine tops
        up decode pages here)."""

    def _chunk_op(self, s):
        from dalle_pytorch_tpu.models.dalle import decode_image_chunk

        return decode_image_chunk(
            self.model, self.variables, s, self.chunk_tokens,
            **self._chunk_bitmap_kw(),
        )

    def _post_chunk(self, pos, act) -> None:
        """Mirror the chunk snapshot host-side — the sparsity policy (and
        the paged allocator, which extends this) read positions without
        another device sync."""
        self._host_pos[: len(pos)] = pos
        self._host_active[: len(act)] = np.asarray(act, bool)

    def step_chunk(self, _warmup: bool = False):  # tracelint: hotloop
        """Advance all live slots by `chunk_tokens`; returns the post-chunk
        (img_pos, active) host snapshot the batcher retires against."""
        import jax

        self._pre_chunk()
        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("chunk")
            try:
                self._replace_state(self._chunk_op, fault_tag="chunk")
                if not _warmup:
                    self._m_chunks.inc()
                    self.chunk_index += 1
                    self.stats.batches += 1
                    if self._sparsity is not None:
                        # mirrors are still the chunk-START snapshot here
                        # (post_chunk runs below), i.e. exactly what the
                        # dispatch's bitmap was derived from
                        read, skipped = self._sparsity.count_tiles(
                            self._host_pos, self._host_active
                        )
                        self._m_kv_tiles_read.inc(read)
                        self._m_kv_tiles_skipped.inc(skipped)
                # the chunk boundary IS the designed sync point: retirement
                # decisions need the positions on the host, and fusing both
                # small arrays into one transfer keeps it to a single round trip
                pos, act = jax.device_get(  # tracelint: disable=TL002 -- chunk-boundary snapshot is the one designed sync of the decode loop (single fused transfer)
                    (self._state["img_pos"], self._state["active"])
                )
            finally:
                wall = time.perf_counter() - t0
                self.vitals.dispatch_end("chunk", wall)
            if _warmup:
                # after the dispatch (see GenerationEngine.generate: a
                # pre-dispatch lowering would poison the sampler cache)
                self._capture_chunk_cost()
            elif self.cost_table is not None:
                # the device_get above synced the chunk program, so this
                # wall is MFU-grade execution time
                self.cost_table.record_wall("chunk", wall)
        self._post_chunk(pos, act)
        return pos, act

    def _capture_chunk_cost(self) -> None:
        """Warmup-time cost capture of the chunk program (subclass hook —
        the paged engine lowers its paged variant). Caller holds the
        lock."""
        from dalle_pytorch_tpu.models.dalle import decode_image_chunk

        spkw = self._chunk_bitmap_kw()
        self._capture_cost(
            "chunk",
            lambda v, s: decode_image_chunk(
                self.model, v, s, self.chunk_tokens, **spkw,
            ),
            self.variables, self._state,
        )

    def _read_token_rows(self, slots: Sequence[int]) -> np.ndarray:  # tracelint: hotloop
        """Host copy of `slots`' token rows — the one transfer shared by
        harvest and the preemption snapshot."""
        import jax

        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("harvest")
            try:
                self._fault_point("harvest")
                # one explicit fixed-shape transfer of the whole token buffer,
                # sliced on the host: a device-side gather of just the finished
                # rows would compile one program PER finished-count (1..max_batch)
                # and break the exactly-the-warmup-set compile discipline that
                # tests/test_continuous.py pins with assert_no_recompiles
                toks = jax.device_get(self._state["img_tokens"])  # tracelint: disable=TL002 -- retirement harvest is a designed sync; fixed-shape transfer beats a per-count compiled gather
            finally:
                self.vitals.dispatch_end(
                    "harvest", time.perf_counter() - t0
                )
        return toks[list(slots)].astype(np.int32)

    def harvest(self, slots: Sequence[int]) -> np.ndarray:
        """Finished slots' tokens [len(slots), image_seq_len] (host copy)."""
        toks = self._read_token_rows(slots)
        with self._lock:
            self.stats.rows_generated += len(list(slots))
        return toks

    def snapshot_rows(self, slots: Sequence[int]) -> np.ndarray:
        """`harvest` minus the traffic accounting: the preemption path's
        host copy of generated-so-far tokens. A preempted row is NOT a
        generated row — it will decode again from position 0 on resume —
        so this must not move `rows_generated` (dashboards read that as
        completed work)."""
        return self._read_token_rows(slots)

    def release(self, slots: Sequence[int]) -> None:  # tracelint: hotloop
        """Deactivate `slots` so the chunk step stops touching them — after
        harvest, or wholesale on an error reset (which must not count
        toward `rows_generated`; only harvests do)."""
        mask = np.zeros(self.max_batch, bool)
        mask[list(slots)] = True
        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("release")
            try:
                self._replace_state(
                    lambda s: self._release_op(s, mask), fault_tag="release"
                )
            finally:
                self.vitals.dispatch_end(
                    "release", time.perf_counter() - t0
                )
            self._host_active[mask] = False
            self._host_pos[mask] = 0

    def decode_pixels(self, tokens: np.ndarray) -> Optional[np.ndarray]:  # tracelint: hotloop
        """Pixels [n, H, W, 3] in [0, 1] for harvested token rows, via ONE
        compiled shape (pad to max_batch, slice) — or None without a VAE."""
        if self.vae is None:
            return None
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        n = len(tokens)
        if not isinstance(self.vae, DiscreteVAE):
            # tracelint: disable=TL002 -- pretrained-wrapper decode is host-side by contract; its output leaves the device here by design
            return np.clip(np.asarray(self.vae.decode(tokens)), 0.0, 1.0)
        import jax
        import jax.numpy as jnp

        if self._decode_pixels_jit is None:
            vae, vae_params = self.vae, self.vae_params
            self._decode_pixels_jit = jax.jit(
                lambda t: vae.apply(
                    {"params": vae_params}, t, method=DiscreteVAE.decode
                )
            )
        pad = self.max_batch - (n % self.max_batch or self.max_batch)
        padded = np.concatenate(
            [tokens, np.zeros((pad, tokens.shape[1]), np.int32)]
        )
        outs = []
        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("decode_pixels")
            try:
                self._fault_point("decode_pixels")
                for i in range(0, len(padded), self.max_batch):
                    outs.append(
                        np.asarray(  # tracelint: disable=TL002 -- pixel harvest is the terminal sync of the retire path; rows leave the device here by design
                            self._decode_pixels_jit(
                                jnp.asarray(padded[i : i + self.max_batch])
                            )
                        )
                    )
            finally:
                wall = time.perf_counter() - t0
                self.vitals.dispatch_end("decode_pixels", wall)
            if self.cost_table is not None and len(padded) == self.max_batch:
                # np.asarray synced; single-dispatch calls only, so the
                # wall maps to ONE program execution
                self.cost_table.record_wall("decode_pixels", wall)
        pixels = np.concatenate(outs)[:n] * 0.5 + 0.5
        return np.clip(pixels, 0.0, 1.0)

    # ---------------------------------------------------------- previews

    def preview_fill_token(self) -> int:
        """Codebook index used to fill undecoded grid positions in a
        progressive preview: the entry nearest the mean codebook vector
        (a neutral canvas rather than whatever index 0 happens to look
        like). Host-side, computed once; falls back to 0 when the
        codebook is not readable (pretrained wrappers)."""
        if self._preview_fill is None:
            tok = 0
            try:
                emb = np.asarray(
                    self.vae_params["codebook"]["embedding"], np.float32
                )
                tok = int(np.argmin(
                    np.linalg.norm(emb - emb.mean(axis=0), axis=-1)
                ))
            except Exception:
                pass
            self._preview_fill = tok
        return self._preview_fill

    def _preview_fn(self):
        """Body of the fill+decode program: mask undecoded positions,
        fill with the mean-codebook token, run the standard VAE decode —
        fused so a streaming preview wave pays ONE dispatch (the
        fused-dispatch pattern of the pixel-decode program)."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        vae, vae_params = self.vae, self.vae_params
        fill = self.preview_fill_token()
        seq = self.image_seq_len

        def fn(toks, pos):
            mask = jnp.arange(seq)[None, :] < pos[:, None]
            filled = jnp.where(mask, toks, jnp.int32(fill))
            return vae.apply(
                {"params": vae_params}, filled, method=DiscreteVAE.decode
            )

        return fn

    def preview_pixels(  # tracelint: hotloop
        self, tokens: np.ndarray, positions: np.ndarray
    ) -> Optional[np.ndarray]:
        """Progressive-preview pixels [n, H, W, 3] in [0, 1] for partial
        token rows (`snapshot_rows` output) with per-row decode
        positions: undecoded grid positions are filled with the mean-
        codebook token and the whole grid decodes through ONE compiled
        fill+decode shape (pad to max_batch, slice) shared by every
        streaming request — or None without a VAE. The program must be
        warmed (`preview_enabled`) before serving traffic reaches it."""
        if self.vae is None:
            return None
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int32)
        n = len(tokens)
        if not isinstance(self.vae, DiscreteVAE):
            # pretrained wrappers decode host-side; fill host-side too
            mask = np.arange(tokens.shape[1])[None, :] < positions[:, None]
            filled = np.where(
                mask, tokens, np.int32(self.preview_fill_token())
            ).astype(np.int32)
            # tracelint: disable=TL002 -- pretrained-wrapper decode is host-side by contract; its output leaves the device here by design
            return np.clip(np.asarray(self.vae.decode(filled)), 0.0, 1.0)
        import jax
        import jax.numpy as jnp

        if self._preview_jit is None:
            self._preview_jit = jax.jit(self._preview_fn())
        pad = self.max_batch - (n % self.max_batch or self.max_batch)
        ptoks = np.concatenate(
            [tokens, np.zeros((pad, tokens.shape[1]), np.int32)]
        )
        ppos = np.concatenate([positions, np.zeros(pad, np.int32)])
        outs = []
        with self._lock:
            t0 = time.perf_counter()
            self.vitals.dispatch_begin("preview")
            try:
                self._fault_point("preview")
                for i in range(0, len(ptoks), self.max_batch):
                    outs.append(
                        np.asarray(  # tracelint: disable=TL002 -- preview pixels ship as a host-side stream event; rows leave the device here by design
                            self._preview_jit(
                                jnp.asarray(ptoks[i : i + self.max_batch]),
                                jnp.asarray(ppos[i : i + self.max_batch]),
                            )
                        )
                    )
            finally:
                wall = time.perf_counter() - t0
                self.vitals.dispatch_end("preview", wall)
            if self.cost_table is not None and len(ptoks) == self.max_batch:
                # np.asarray synced; single-dispatch calls only, so the
                # wall maps to ONE program execution
                self.cost_table.record_wall("preview", wall)
        pixels = np.concatenate(outs)[:n] * 0.5 + 0.5
        return np.clip(pixels, 0.0, 1.0)

    def _warmup_preview(self) -> None:
        """Dispatch + AOT-capture the fill+decode program during warmup
        (after the pixel-decode capture, same post-dispatch ordering).
        No-op unless previews are enabled AND the fused decode exists."""
        if not (self.preview_enabled and self._has_fused_pixel_decode()):
            return
        self.preview_pixels(
            np.zeros((1, self.image_seq_len), np.int32),
            np.zeros(1, np.int32),
        )
        self._capture_preview_cost()

    def _capture_preview_cost(self) -> None:
        """Like `_capture_decode_pixels_cost`: the preview jit exists
        only after the warmup dispatch built it."""
        if self._preview_jit is None:
            return
        import jax.numpy as jnp

        self._capture_cost(
            "preview",
            lambda t, p: self._preview_jit(t, p),
            jnp.zeros((self.max_batch, self.image_seq_len), jnp.int32),
            jnp.zeros((self.max_batch,), jnp.int32),
        )

    def slots_active_gauge(self, n: int) -> None:
        self._m_slots.set(n)

    # ----------------------------------------------------------- warmup

    def warmup(self, shapes: Optional[Sequence[int]] = None) -> None:
        """Compile the full fixed-shape program set (batched prefill at
        `prefill_batch` — the one program every admission wave runs —
        chunk, slot release, pixel decode) with dummy traffic, then reset
        the slot state. Counts only toward compile metrics +
        `stats.warmup_batches` (same tagging contract as the micro-batch
        engine). Warming ALL of the steady-state programs — release
        included — is load-bearing: tests/test_continuous.py pins with
        `assert_no_recompiles` that a post-warmup serve cycle compiles
        nothing."""
        t0 = time.perf_counter()
        dummy = SampleSpec(
            np.zeros(self.model.text_seq_len, np.int32), seed=0
        )
        self._compile_miss.inc()
        self.prefill_slot(0, dummy, _warmup=True)
        if self.resume_enabled:
            # the resume program warms in slot 1 when there is one; a
            # 1-slot engine recycles slot 0 (same idiom as the paged
            # engine's hit-admit warmup)
            res_slot = 1 if self.max_batch > 1 else 0
            if res_slot == 0:
                self.release([0])
            self.resume_slots(
                [(res_slot, SampleSpec(
                    np.zeros(self.model.text_seq_len, np.int32), seed=0,
                    resume_tokens=np.zeros(1, np.int32), resume_pos=1,
                ))],
                _warmup=True,
            )
        self.step_chunk(_warmup=True)
        self.release([s for s in (0, 1) if s < self.max_batch])
        # cost capture AFTER each program's first dispatch (a pre-dispatch
        # lowering would poison the sampler closure cache with tracers)
        self._capture_release_cost()
        self.decode_pixels(
            np.zeros((1, self.image_seq_len), np.int32)
        )
        self._capture_decode_pixels_cost()
        self._warmup_preview()
        with self._lock:
            # _fresh_state, not init_slot_state directly: subclasses
            # rebuild host-side managers alongside the device state
            self._state = self._fresh_state()
            self.stats.warmup_batches += 1
            self._compile_seconds.observe(time.perf_counter() - t0)
            self._warm.add(self.max_batch)
            self.stats.compiled_shapes = tuple(sorted(self._warm))

    def _capture_release_cost(self) -> None:
        from dalle_pytorch_tpu.models.dalle import release_slots

        mask = np.zeros(self.max_batch, bool)
        mask[0] = True
        self._capture_cost(
            "release",
            lambda s, m: release_slots(self.model, s, m),
            self._state, mask,
        )

    def _capture_decode_pixels_cost(self) -> None:
        """The pixel-decode jit exists only after the warmup decode built
        it (and only for the fused DiscreteVAE path). Routed through the
        shared AOT ladder so the compile cache exports this program too."""
        if self._decode_pixels_jit is None:
            return
        import jax.numpy as jnp

        self._capture_cost(
            "decode_pixels",
            lambda t: self._decode_pixels_jit(t),
            jnp.zeros((self.max_batch, self.image_seq_len), jnp.int32),
        )

    def program_ladder(self) -> Tuple[str, ...]:
        out = ["prefill"]
        if self.resume_enabled:
            out.append("resume")
        out += ["chunk", "release"]
        if self._has_fused_pixel_decode():
            out.append("decode_pixels")
            if self.preview_enabled:
                out.append("preview")
        return tuple(out)

    def _has_fused_pixel_decode(self) -> bool:
        """Only a fused DiscreteVAE builds the jitted pixel-decode
        program; pretrained wrappers decode host-side and a VAE-less
        engine returns tokens only — neither compiles anything, so the
        ladder (and the boot fingerprint) must not claim the program."""
        from dalle_pytorch_tpu.models.dvae import DiscreteVAE

        return isinstance(self.vae, DiscreteVAE)

    # -------------------------------------------------------- observability

    def sparsity_detail(self) -> Optional[dict]:
        """Decode-sparsity snapshot for `/healthz` (None on the causal
        path, so the server omits the block entirely — same getattr
        contract as `kv_detail`/`mesh_detail`)."""
        if self._sparsity is None:
            return None
        out = {"mode": "policy"}
        out.update(self._sparsity.detail())
        out["kv_tiles_read"] = int(self._m_kv_tiles_read.value)
        out["kv_tiles_skipped"] = int(self._m_kv_tiles_skipped.value)
        return out

    def state_dump(self) -> dict:
        """Host-side engine state for `/debug/state` and stall reports —
        deliberately lock-free (a stalled engine is holding its dispatch
        lock, and the dump must still render)."""
        out = super().state_dump()
        out.update(
            max_batch=self.max_batch,
            chunk_tokens=self.chunk_tokens,
            prefill_batch=self.prefill_batch,
            chunk_index=self.chunk_index,
            dispatch_inflight=(
                self.vitals.inflight() if self.vitals else None
            ),
        )
        return out


class PagedContinuousEngine(ContinuousEngine):
    """Continuous batching over a BLOCK-PAGED KV cache with prefix caching.

    Same serving surface and decode semantics as `ContinuousEngine` (one
    shared chunk-program body — `models/dalle.py:_make_chunk_fn` — keeps
    paged output bit-for-bit identical to slotted, pinned by
    tests/test_paging.py), but K/V lives in a pool of `kv_pages` pages of
    `page_size` tokens with host-owned per-row page tables
    (`serving/paging.py`):

      * HBM follows tokens actually held, not `max_batch` worst-case
        lanes — `kv_pages` can be sized below the slotted footprint and
        concurrency is then bounded by real occupancy (admission reserves
        a row's worst case so lazy per-chunk allocation never deadlocks;
        the batcher keeps requests queued while `can_admit` is false).
      * identical caption prefixes share immutable prefill pages
        (content-hash chain lookup, refcounted, copy-on-write at the
        divergence block), and a FULL-prompt hit admits with ZERO
        transformer dispatches — the cached sidecar (pending logits +
        shift rings) restores the row via one tiny fixed-shape program
        (`admit_cached_prefix`), so repeat prompts cost near-zero TTFT.

    Compiled-program set (all warmed, zero recompiles on a warm server):
    paged batched prefill, sidecar slice, cached-prefix admit, paged
    chunk, slot release, pixel decode. Page tables enter every dispatch as
    traced host data, so no allocation decision ever compiles.
    """

    def __init__(
        self,
        model,
        variables,
        vae=None,
        vae_params=None,
        max_batch: int = 8,
        chunk_tokens: int = 4,
        prefill_batch: int = 4,
        cond_scale: float = 1.0,
        clip=None,
        clip_params=None,
        tokenizer=None,
        registry=None,
        cfg=None,
        page_size: int = 32,
        kv_pages: Optional[int] = None,
        prefix_entries: int = 64,
        resume_enabled: bool = False,
        preview_enabled: bool = False,
        kv_dtype=None,
        decode_sparsity: str = "causal",
    ):
        self.page_size = int(page_size)
        assert self.page_size >= 1
        max_positions = model.total_seq_len + 1
        pages_per_row = -(-max_positions // self.page_size)
        if kv_pages is None:
            # worst case (every slot at full length, nothing shared) plus
            # the garbage page and one row of prefix-cache headroom: the
            # DEFAULT never admits worse than slotted; the HBM win comes
            # from sizing kv_pages down and from prefix sharing
            kv_pages = int(max_batch) * pages_per_row + 1 + pages_per_row
        self.kv_pages = int(kv_pages)
        self.prefix_entries = int(prefix_entries)
        self._text_positions = model.text_seq_len + 1
        super().__init__(
            model=model,
            variables=variables,
            vae=vae,
            vae_params=vae_params,
            max_batch=max_batch,
            chunk_tokens=chunk_tokens,
            prefill_batch=prefill_batch,
            cond_scale=cond_scale,
            clip=clip,
            clip_params=clip_params,
            tokenizer=tokenizer,
            registry=registry,
            cfg=cfg,
            resume_enabled=resume_enabled,
            preview_enabled=preview_enabled,
            kv_dtype=kv_dtype,
            decode_sparsity=decode_sparsity,
        )
        assert self.kv.can_ever_admit(1), (
            f"kv_pages={self.kv_pages} cannot hold a single row "
            f"({self.kv.pages_per_row} pages + the garbage page)"
        )
        self._m_blocks_active = self.registry.gauge(
            "dalle_serving_blocks_active",
            "KV pages currently allocated (tokens actually held, incl. "
            "prefix-cache snapshots)",
        )
        self._m_blocks_free = self.registry.gauge(
            "dalle_serving_blocks_free", "KV pages free in the pool"
        )
        self._m_prefix_hits = self.registry.counter(
            "dalle_serving_prefix_cache_hits_total",
            "admissions served from the prefix cache with zero prefill "
            "dispatches",
        )
        self._m_prefix_misses = self.registry.counter(
            "dalle_serving_prefix_cache_misses_total",
            "admissions that ran a prefill dispatch",
        )
        self._m_prefix_evictions = self.registry.counter(
            "dalle_serving_prefix_cache_evictions_total",
            "prefix-cache entries evicted (LRU)",
        )
        #: per-wave admission stats the batcher reads for span metadata /
        #: per-request prefix_hit flags ({"prefix_hits", "hit_slots",
        #: "prefix_blocks_reused", "suffix_tokens_computed", "dispatches"})
        self.last_admission_stats: Optional[dict] = None
        self._update_block_gauges()

    # ------------------------------------------------------- host plumbing

    def _fresh_state(self):
        """Paged device state + rebuilt host managers, together: after a
        failed donated dispatch the pages buffer is gone, so every page
        table, refcount, and cached prefix referring into it is garbage
        too."""
        from dalle_pytorch_tpu.models.dalle import init_paged_slot_state
        from dalle_pytorch_tpu.serving.paging import PagedKVManager

        self.kv = PagedKVManager(
            n_rows=self.max_batch,
            page_size=self.page_size,
            max_positions=self.model.total_seq_len + 1,
            text_positions=self._text_positions,
            n_pages=self.kv_pages,
            max_entries=self.prefix_entries,
            on_evict=lambda: self._m_prefix_evictions.inc(),
        )
        self._host_pos = np.zeros(self.max_batch, np.int64)
        self._host_active = np.zeros(self.max_batch, bool)
        return init_paged_slot_state(
            self.model, self.max_batch, self.kv_pages, self.page_size
        )

    def _update_block_gauges(self) -> None:
        self._m_blocks_active.set(self.kv.blocks_active)
        self._m_blocks_free.set(self.kv.blocks_free)

    def can_admit(self, specs: Sequence[SampleSpec]) -> bool:
        """Free + evictable pages cover this request's worst case on top
        of live rows' reservations (the batcher keeps it queued
        otherwise — block exhaustion is backpressure, not corruption)."""
        return self.kv.can_admit(
            [np.asarray(s.text_ids, np.int32) for s in specs]
        )

    def admission_headroom(self) -> int:
        """Pages available for new admissions — the batcher snapshots
        this once per wave and debits `admission_demand` per popped head
        (same verdict as a union `can_admit`, without re-deriving earlier
        heads' demand on every pop)."""
        return self.kv.admission_headroom()

    def admission_demand(self, specs: Sequence[SampleSpec]) -> int:
        """Worst-case page demand of one request's rows. Resume rows
        (mid-decode migration) are charged the FULL per-row worst case
        even when their prompt is prefix-cached: `admit_resume`
        allocates fresh pages — the resume dispatch rewrites every page
        it maps with the row's own mid-decode K/V, which must never land
        on content other rows share."""
        total = 0
        for s in specs:
            if self.supports_resume and getattr(s, "resume_pos", 0):
                total += self.kv.pages_per_row
            else:
                total += self.kv.row_demand(
                    np.asarray(s.text_ids, np.int32)
                )
        return total

    def can_ever_admit(self, specs: Sequence[SampleSpec]) -> bool:
        """False when the request could not fit an EMPTY pool — submit
        should reject it outright rather than queue it forever."""
        return self.kv.can_ever_admit(len(specs))

    def kv_page_bytes(self) -> int:
        """Bytes of ONE physical page across all layers (K + V + any
        quantization scales) — what a `dalle_serving_blocks_*` page is
        actually worth in HBM at the engine's kv dtype."""
        return self._kv_cache_bytes() // self.kv_pages

    def kv_bytes_per_slot(self) -> int:
        """Worst-case bytes one row can pin: its full page complement.
        (The pool is shared — prefix hits pin less — but sizing honesty
        wants the bound, not the average.)"""
        return self.kv_page_bytes() * self.kv.pages_per_row

    def kv_detail(self) -> dict:
        """Block-pool + prefix-cache snapshot for /healthz."""
        cache = self.kv.cache
        kv_dt = getattr(self.model, "kv_dtype", None)
        return {
            "layout": "paged",
            "page_size": self.page_size,
            "pages_per_row": self.kv.pages_per_row,
            "dtype": str(kv_dt) if kv_dt is not None else str(
                np.dtype(self.model.dtype).name
            ),
            "bytes_per_page": self.kv_page_bytes(),
            "blocks_total": self.kv.pool.n_pages - 1,
            "blocks_active": self.kv.blocks_active,
            "blocks_free": self.kv.blocks_free,
            "prefix_cache": {
                "entries": len(cache),
                "hits": cache.hits,
                "misses": cache.misses,
                "evictions": cache.evictions,
                # seen-keys Bloom digest for the fleet scraper: the
                # prefix-affinity signal a future placer intersects
                "bloom": cache.bloom_digest(),
            },
        }

    # ------------------------------------------------------------ slot ops
    # The three paged model ops run behind subclass seams (like
    # `_prefill_op`/`_chunk_op`/`_release_op` on the slotted engine) so
    # the sharded paged engine can pin out_shardings on the whole ladder.

    def _paged_prefill_op(self, s, texts, slots, seeds, temps, keep,
                          page_rows, partial_dst):
        from dalle_pytorch_tpu.models.dalle import prefill_into_slots_paged

        return prefill_into_slots_paged(
            self.model, self.variables, s, texts, slots, seeds, temps,
            keep, page_rows, partial_dst, self.page_size,
            **self._prefill_bitmap_kw(),
        )

    def _admit_hit_op(self, s, slot, sidecar, seed, temperature, keep_k,
                      partial_src, partial_dst):
        from dalle_pytorch_tpu.models.dalle import admit_cached_prefix

        return admit_cached_prefix(
            self.model, s, slot, sidecar, seed, temperature, keep_k,
            partial_src, partial_dst, self.page_size,
        )

    def _paged_resume_op(self, s, texts, img_tokens, img_pos, slots,
                         seeds, temps, keep, page_rows):
        from dalle_pytorch_tpu.models.dalle import resume_into_slots_paged

        return resume_into_slots_paged(
            self.model, self.variables, s, texts, img_tokens, img_pos,
            slots, seeds, temps, keep, page_rows, self.page_size,
        )

    def protect_admission_wave(self, assignments) -> set:
        """Pin every full-prompt hit entry of one budgeted admission wave
        against eviction until `unprotect_admission_wave`. The batcher
        budgets the WHOLE wave against one headroom snapshot but
        dispatches it in `prefill_batch`-sized `prefill_slots` splits; an
        earlier split's allocation cascade evicting an entry a later
        split's request was budgeted against (at `pages_per_row - saved`)
        would demote that hit to a full prefill and overdraw the
        reservation by `saved` pages. Returns the keys actually added
        (pass them back verbatim)."""
        if not self.kv.cache.enabled:
            return set()
        keys = []
        for _slot, spec in assignments:
            entry = self.kv.cache.peek_full(
                np.asarray(spec.text_ids, np.int32)
            )
            if entry is not None:
                keys.append(entry.key)
        return self.kv.cache.protect(keys)

    def unprotect_admission_wave(self, keys) -> None:
        self.kv.cache.unprotect(keys)

    def prefill_slots(  # tracelint: hotloop
        self,
        assignments: Sequence[Tuple[int, SampleSpec]],
        _warmup: bool = False,
    ) -> None:
        """Paged admission wave: full-prompt prefix hits admit via the
        cached sidecar (zero prefill dispatches); the rest run ONE batched
        paged prefill, mapping any cached prefix blocks into their page
        tables instead of allocating (the dispatch rewrites shared pages
        with bit-identical content — prefill K/V is batch-composition
        invariant) and registering fresh prompts into the cache."""
        n = len(assignments)
        assert 1 <= n <= self.prefill_batch, (
            f"{n} assignments exceed prefill_batch={self.prefill_batch}; "
            "the batcher must split admission waves"
        )
        stats = {
            "wave_rows": n,
            "prefix_hits": 0,
            "hit_slots": [],
            "prefix_blocks_reused": 0,
            "suffix_tokens_computed": 0,
            "dispatches": 0,
        }
        hits, misses = [], []
        for slot, spec in assignments:
            entry = (
                self.kv.cache.lookup_full(np.asarray(spec.text_ids, np.int32))
                if self.kv.cache.enabled
                else None
            )
            if entry is not None:
                hits.append((slot, spec, entry))
            else:
                misses.append((slot, spec))

        # Hit entries are PROTECTED for the rest of the wave: the batcher
        # budgeted each hit at `pages_per_row - saved`, so another row's
        # allocation cascade evicting the entry mid-wave would demote the
        # hit to a full prefill that consumes `saved` more pages than
        # were charged — the reservation invariant would be short by
        # exactly that, and a later `ensure` would hit the allocator's
        # exhaustion assert mid-decode. A batcher wave larger than
        # `prefill_batch` arrives as several `prefill_slots` calls but was
        # budgeted as ONE wave, so the batcher pins the whole wave's hit
        # entries via `protect_admission_wave` around the splits; this
        # per-split pin (unprotecting only what IT added) covers direct
        # callers. Hits also run BEFORE the miss batch so no dispatch
        # ever reads a page its entry no longer owns; the revalidation
        # below is a backstop for unbudgeted callers racing the
        # protection (it cannot fire for waves admitted through
        # can_admit/admission_headroom and wave-protected end to end).
        added = self.kv.cache.protect(entry.key for _, _, entry in hits)
        t0 = time.perf_counter()
        self.vitals.dispatch_begin("prefill")
        try:
            self._admit_wave(hits, misses, stats, _warmup)
        finally:
            wall = time.perf_counter() - t0
            self.vitals.dispatch_end("prefill", wall)
            self.kv.cache.unprotect(added)
        if not _warmup and self.cost_table is not None:
            self.cost_table.record_wall("prefill", wall, synced=False)

        self.last_admission_stats = stats
        self._update_block_gauges()

    def _admit_wave(self, hits, misses, stats, _warmup) -> None:
        from dalle_pytorch_tpu.models.dalle import (
            admit_cached_prefix,
            prefill_into_slots_paged,
            slice_prefix_sidecar,
        )

        for slot, spec, entry in hits:
            ids = np.asarray(spec.text_ids, np.int32)
            if self.kv.cache.lookup_full(ids) is not entry:
                misses.append((slot, spec))  # evicted mid-wave: full prefill
                continue
            partial_src, pdst = self.kv.admit_hit(slot, entry)
            with self._lock:
                self._replace_state(
                    lambda s, slot=slot, spec=spec, entry=entry,
                    partial_src=partial_src, pdst=pdst: self._admit_hit_op(
                        s, slot, entry.sidecar,
                        int(spec.seed) & 0x7FFFFFFF, spec.temperature,
                        self._keep_k(spec.top_k), partial_src, pdst,
                    ),
                    fault_tag="admit_hit",
                )
                if not _warmup:
                    self._m_prefix_hits.inc()
            if _warmup:
                # after the dispatch (see GenerationEngine.generate: a
                # pre-dispatch lowering would poison the sampler cache)
                self._capture_cost(
                    "admit_hit",
                    lambda s, sl, sc, se, tm, k, src, dst: (
                        admit_cached_prefix(
                            self.model, s, sl, sc, se, tm, k, src, dst,
                            self.page_size,
                        )
                    ),
                    self._state, slot, entry.sidecar,
                    int(spec.seed) & 0x7FFFFFFF, spec.temperature,
                    self._keep_k(spec.top_k), partial_src, pdst,
                )
            self._host_pos[slot] = 0
            self._host_active[slot] = True
            if not _warmup:
                self.kv.cache.hits += 1
            stats["prefix_hits"] += 1
            stats["hit_slots"].append(slot)
            stats["prefix_blocks_reused"] += self.kv.n_full_blocks

        if misses:
            rows = list(misses) + [misses[0]] * (self.prefill_batch - len(misses))
            texts, slots, seeds, temps, keep = _pack_prefill_rows(
                rows, self._keep_k
            )
            assert texts.shape == (
                self.prefill_batch, self.model.text_seq_len,
            ), f"prompt rows must be [{self.model.text_seq_len}] token ids"
            page_rows = np.zeros(
                (self.prefill_batch, self.kv.n_text_pages), np.int32
            )
            partial_dst = np.zeros(self.prefill_batch, np.int32)
            pending = []  # (prefill row index, registration token)
            reg_seen = set()  # same prompt twice in ONE wave registers once
            # wave-local {chain hash: page}: rows admitted later in this
            # wave map earlier rows' pages for identical leading blocks
            # instead of allocating twins (which the registration index
            # could not content-address)
            wave_blocks: dict = {}
            for i, (slot, spec) in enumerate(misses):
                ids = np.asarray(spec.text_ids, np.int32)
                ids_key = ids.tobytes()
                row_pages, pdst, shared_n, token = self.kv.admit_miss(
                    slot, ids, register=ids_key not in reg_seen,
                    pending_blocks=wave_blocks,
                )
                reg_seen.add(ids_key)
                page_rows[i] = row_pages
                partial_dst[i] = pdst
                if token is not None:
                    pending.append((i, token))
                stats["prefix_blocks_reused"] += shared_n
                stats["suffix_tokens_computed"] += (
                    self._text_positions - shared_n * self.page_size
                )
            # padding rows rewrite row 0's pages with identical content;
            # their snapshot write goes to the garbage page
            for i in range(len(misses), self.prefill_batch):
                page_rows[i] = page_rows[0]

            sidecars = {}

            def op(s):
                new_s, sidecar = self._paged_prefill_op(
                    s, texts, slots, seeds, temps, keep, page_rows,
                    partial_dst,
                )
                sidecars["wave"] = sidecar
                return new_s

            with self._lock:
                # on failure _replace_state rebuilds state AND (via
                # _fresh_state) the kv manager, so the half-done host
                # mappings above are discarded wholesale
                self._replace_state(op, fault_tag="prefill")
                if not _warmup:
                    self._m_prefills.inc(len(misses))
                    self._m_prefill_dispatches.inc()
                    self._m_prefix_misses.inc(len(misses))
            if _warmup:
                # after the dispatch (see GenerationEngine.generate: a
                # pre-dispatch lowering would poison the sampler cache)
                spkw = self._prefill_bitmap_kw()
                self._capture_cost(
                    "prefill",
                    lambda v, s, t, sl, se, tm, k, pr, pd: (
                        prefill_into_slots_paged(
                            self.model, v, s, t, sl, se, tm, k, pr, pd,
                            self.page_size, **spkw,
                        )
                    ),
                    self.variables, self._state, texts, slots, seeds,
                    temps, keep, page_rows, partial_dst,
                )
            for i, token in pending:
                self.kv.finish_register(
                    token,
                    slice_prefix_sidecar(self.model, sidecars["wave"], i),
                )
            for slot, _spec in misses:
                self._host_pos[slot] = 0
                self._host_active[slot] = True
            if not _warmup:
                self.kv.cache.misses += len(misses)
            stats["dispatches"] += 1

    def resume_slots(  # tracelint: hotloop
        self,
        assignments: Sequence[Tuple[int, SampleSpec]],
        _warmup: bool = False,
    ) -> None:
        """Paged mid-decode admission: fresh pages cover each row's
        prompt + generated prefix (`PagedKVManager.admit_resume` — no
        prefix sharing, see `admission_demand`), then ONE teacher-forced
        `resume_into_slots_paged` dispatch writes them; blocks beyond
        the prefix stay on the garbage page until `ensure` maps them
        ahead of decode as usual."""
        assert self.supports_resume, (
            "resume_slots on an engine built without resume_enabled — "
            "the program is not in the warmup ladder and would "
            "cold-compile mid-traffic"
        )
        n = len(assignments)
        assert 1 <= n <= self.prefill_batch, (
            f"{n} assignments exceed prefill_batch={self.prefill_batch}; "
            "the batcher must split admission waves"
        )
        rows = list(assignments) + [assignments[0]] * (self.prefill_batch - n)
        texts, slots, seeds, temps, keep = _pack_prefill_rows(
            rows, self._keep_k
        )
        img_tokens, img_pos = self._pack_resume_rows(rows)
        page_rows = np.zeros(
            (self.prefill_batch, self.kv.pages_per_row), np.int32
        )
        mapped: set = set()
        for r, (slot, _spec) in enumerate(rows):
            if slot in mapped:  # padding repeats a real (slot, spec) pair
                page_rows[r] = page_rows[0]
                continue
            mapped.add(slot)
            self.kv.admit_resume(
                slot, self._text_positions + int(img_pos[r])
            )
            page_rows[r] = self.kv.table[slot]
        t0 = time.perf_counter()
        self.vitals.dispatch_begin("resume")
        try:
            from dalle_pytorch_tpu.models.dalle import resume_into_slots_paged

            with self._lock:
                # on failure _replace_state rebuilds state AND (via
                # _fresh_state) the kv manager, discarding the mappings
                self._replace_state(lambda s: self._paged_resume_op(
                    s, texts, img_tokens, img_pos, slots, seeds, temps,
                    keep, page_rows,
                ), fault_tag="resume")
                if _warmup:
                    self._capture_cost(
                        "resume",
                        lambda v, s, t, it, ip, sl, se, tm, k, pr: (
                            resume_into_slots_paged(
                                self.model, v, s, t, it, ip, sl, se, tm,
                                k, pr, self.page_size,
                            )
                        ),
                        self.variables, self._state, texts, img_tokens,
                        img_pos, slots, seeds, temps, keep, page_rows,
                    )
        finally:
            wall = time.perf_counter() - t0
            self.vitals.dispatch_end("resume", wall)
        for (slot, _spec), pos in zip(assignments, img_pos[:n]):
            self._host_pos[slot] = int(pos)
            self._host_active[slot] = True
        if not _warmup:
            if self.cost_table is not None:
                self.cost_table.record_wall("resume", wall, synced=False)
            self._m_prefills.inc(n)
            self._m_prefill_dispatches.inc()
        self._update_block_gauges()

    def _pre_chunk(self) -> None:
        # lazy decode-page allocation: the table must cover every live
        # row's writes for this chunk before the dispatch reads it
        # (reserved at admission, so this cannot fail mid-decode)
        for slot in range(self.max_batch):
            if self._host_active[slot]:
                end = min(
                    self._text_positions
                    + int(self._host_pos[slot])
                    + self.chunk_tokens,
                    self.kv.max_positions,
                )
                self.kv.ensure(slot, -(-end // self.page_size))

    def _chunk_op(self, s):
        from dalle_pytorch_tpu.models.dalle import decode_image_chunk_paged

        return decode_image_chunk_paged(
            self.model, self.variables, s, self.chunk_tokens,
            self.kv.table, **self._chunk_bitmap_kw(),
        )

    def _post_chunk(self, pos, act) -> None:
        super()._post_chunk(pos, act)
        self._update_block_gauges()

    def release(self, slots: Sequence[int]) -> None:  # tracelint: hotloop
        # snapshot BEFORE the base release clears the host mirrors: pages
        # must be freed exactly for the rows that were live
        was_active = {int(s): bool(self._host_active[int(s)]) for s in slots}
        super().release(slots)
        for s in slots:
            s = int(s)
            if was_active[s]:
                self.kv.release(s)
        self._update_block_gauges()

    # ------------------------------------------------------------- warmup

    def warmup(self, shapes: Optional[Sequence[int]] = None) -> None:
        """Compile the paged program set: batched prefill (+ the sidecar
        slice its registration runs), the cached-prefix admit, chunk,
        release, pixel decode — then reset device AND host paging state.
        The second dummy wave is a deliberate full-prefix hit so the admit
        program is warm before the first real repeat prompt."""
        t0 = time.perf_counter()
        dummy = SampleSpec(
            np.zeros(self.model.text_seq_len, np.int32), seed=0
        )
        self._compile_miss.inc()
        self.prefill_slots([(0, dummy)], _warmup=True)
        if self.kv.cache.enabled:
            # the hit-admit program warms in slot 1 when there is one; a
            # 1-slot engine recycles slot 0 (released first — a live slot
            # can't be mapped twice)
            hit_slot = 1 if self.max_batch > 1 else 0
            if hit_slot == 0:
                self.release([0])
            self.prefill_slots([(hit_slot, dummy)], _warmup=True)  # prefix hit
        if self.resume_enabled:
            # the resume program warms in the next free slot; small
            # engines recycle slot 0 (released first)
            res_slot = 2 if self.max_batch > 2 else 0
            if res_slot == 0:
                self.release([0])
            self.resume_slots(
                [(res_slot, SampleSpec(
                    np.zeros(self.model.text_seq_len, np.int32), seed=0,
                    resume_tokens=np.zeros(1, np.int32), resume_pos=1,
                ))],
                _warmup=True,
            )
        self.step_chunk(_warmup=True)
        self.release([s for s in (0, 1, 2) if s < self.max_batch])
        # capture after the first release dispatch, like the other
        # programs (pre-dispatch lowering poisons the sampler cache)
        self._capture_release_cost()
        self.decode_pixels(
            np.zeros((1, self.image_seq_len), np.int32)
        )
        self._capture_decode_pixels_cost()
        self._warmup_preview()
        with self._lock:
            self._state = self._fresh_state()
            self.stats.warmup_batches += 1
            self._compile_seconds.observe(time.perf_counter() - t0)
            self._warm.add(self.max_batch)
            self.stats.compiled_shapes = tuple(sorted(self._warm))
        self._update_block_gauges()

    def _capture_chunk_cost(self) -> None:
        from dalle_pytorch_tpu.models.dalle import decode_image_chunk_paged

        spkw = self._chunk_bitmap_kw()
        self._capture_cost(
            "chunk",
            lambda v, s, t: decode_image_chunk_paged(
                self.model, v, s, self.chunk_tokens, t, **spkw,
            ),
            self.variables, self._state, self.kv.table,
        )

    def program_ladder(self) -> Tuple[str, ...]:
        out = ["prefill"]
        if self.kv.cache.enabled:
            out.append("admit_hit")
        if self.resume_enabled:
            out.append("resume")
        out += ["chunk", "release"]
        if self._has_fused_pixel_decode():
            out.append("decode_pixels")
            if self.preview_enabled:
                out.append("preview")
        return tuple(out)

    def state_dump(self) -> dict:
        out = super().state_dump()
        out["kv"] = self.kv.debug_dump()
        return out


def engine_from_checkpoint(
    dalle_path: str,
    clip_path: Optional[str] = None,
    batch_shapes: Sequence[int] = (1, 4, 8),
    cond_scale: float = 1.0,
    registry=None,
    mode: str = "micro",
    chunk_tokens: int = 4,
    prefill_batch: int = 4,
    kv_layout: str = "slot",
    page_size: int = 32,
    kv_pages: Optional[int] = None,
    prefix_entries: int = 64,
    mesh=None,
    resume_enabled: Optional[bool] = None,
    preview_enabled: Optional[bool] = None,
    kv_dtype: Optional[str] = None,
    decode_sparsity: Optional[str] = None,
):
    """Build a serving engine from a single-file DALLE checkpoint.

    `mode="micro"` (default) returns the padded-micro-batch
    `GenerationEngine`; `mode="continuous"` returns a `ContinuousEngine`
    whose slot count is the largest entry of `batch_shapes` —
    `kv_layout="paged"` upgrades it to the block-paged
    `PagedContinuousEngine` (`page_size` tokens per page, `kv_pages` pool
    size or None for the slotted-equivalent worst case, `prefix_entries`
    cached prompts). `mesh` (a `parse_mesh_shape` string/dict, or a ready
    jax Mesh) selects the mesh-sharded `ShardedContinuousEngine`
    (`kv_layout="paged"` upgrades it to `ShardedPagedContinuousEngine`:
    the paged pool head-splits over `tp`, page tables stay host-side).
    `kv_dtype="int8"` stores KV pages quantized with per-(position, head)
    scales; `None`/"model" keeps the model dtype.
    `decode_sparsity="policy"` routes pattern-masked decode rows through
    the block-sparse flash kernel, bitmaps derived host-side from the
    model's static attention layouts (`serving/sparsity.py`);
    `None`/"causal" keeps the bit-identical dense-causal default
    (continuous engines only). The loading
    sequence (VAE reconstruction, tokenizer, ring-attention downgrade for
    decode) was lifted from `generate.py`, which now calls this instead —
    CLI and server share one code path by construction.
    """
    assert mode in ("micro", "continuous"), f"unknown engine mode {mode!r}"
    assert mesh is None or mode == "continuous", (
        "--mesh needs the continuous engine (slot or paged kv layout)"
    )
    assert decode_sparsity in (None, "causal") or mode == "continuous", (
        "--decode_sparsity policy needs the continuous engine (the "
        "micro-batch sampler has no per-slot bitmap plumbing)"
    )
    from pathlib import Path

    from dalle_pytorch_tpu.training.pipeline import (
        build_tokenizer, dalle_from_config, dvae_from_hparams,
        load_dalle_checkpoint,
    )

    ckpt_path = Path(dalle_path)
    assert ckpt_path.exists(), f"trained DALL-E {ckpt_path} must exist"
    cfg, dalle_params, vae_params, meta, _ = load_dalle_checkpoint(str(ckpt_path))

    assert meta.get("vae_class_name") == "DiscreteVAE" or vae_params is None, (
        "checkpoint was trained with a pretrained VAE wrapper; provide it"
    )
    if vae_params is None:
        from dalle_pytorch_tpu.training.pipeline import build_vae

        vae, vae_params = build_vae(cfg)
    else:
        assert meta.get("vae_hparams"), "checkpoint missing vae_hparams"
        vae = dvae_from_hparams(meta["vae_hparams"])
    fmap = vae.image_size // (2 ** vae.num_layers)

    tokenizer = build_tokenizer(cfg)
    if cfg.model.attn_impl == "ring":
        # ring attention is a training-time layout (sequence sharded over
        # the mesh sp axis); KV-cached decode never runs it, so a
        # ring-trained checkpoint generates with the dense/auto kernel
        cfg.model.attn_impl = "auto"
    model = dalle_from_config(
        cfg, num_image_tokens=vae.num_tokens, image_fmap_size=fmap,
        vocab_size=max(tokenizer.vocab_size, 1),
    )
    if kv_dtype not in (None, "model"):
        # quantized KV store: every engine (and the micro path) reads the
        # model field, so one clone here covers all modes uniformly
        model = model.clone(kv_dtype=str(kv_dtype))

    clip = clip_params = None
    if clip_path:
        from dalle_pytorch_tpu.training.pipeline import load_clip_checkpoint

        clip, clip_params = load_clip_checkpoint(clip_path)

    common = dict(
        model=model,
        variables={"params": dalle_params},
        vae=vae,
        vae_params=vae_params,
        cond_scale=cond_scale,
        clip=clip,
        clip_params=clip_params,
        tokenizer=tokenizer,
        registry=registry,
        cfg=cfg,
    )
    if mode == "continuous":
        assert kv_layout in ("slot", "paged"), f"unknown kv_layout {kv_layout!r}"
        cls = PagedContinuousEngine if kv_layout == "paged" else ContinuousEngine
        paged_kw = (
            dict(
                page_size=page_size,
                kv_pages=kv_pages,
                prefix_entries=prefix_entries,
            )
            if kv_layout == "paged"
            else {}
        )
        # decode-state resume (mid-decode migration) defaults ON for
        # serving boots — the sharded engines pin the resume program's
        # out_shardings, so mesh boots keep it too
        paged_kw["resume_enabled"] = (
            True if resume_enabled is None else bool(resume_enabled)
        )
        if mesh is not None:
            from dalle_pytorch_tpu.serving.sharded import (
                ShardedContinuousEngine, ShardedPagedContinuousEngine,
            )

            cls = (
                ShardedPagedContinuousEngine
                if kv_layout == "paged"
                else ShardedContinuousEngine
            )
            try:
                from jax.sharding import Mesh

                is_mesh = isinstance(mesh, Mesh)
            except Exception:  # pragma: no cover - jax always importable here
                is_mesh = False
            paged_kw.update(
                dict(mesh=mesh) if is_mesh else dict(mesh_shape=mesh)
            )
        # progressive-preview decode (streaming) defaults ON for serving
        # boots on every continuous engine — the preview program rides
        # the replicated VAE, so the sharded engine warms it too
        paged_kw["preview_enabled"] = (
            True if preview_enabled is None else bool(preview_enabled)
        )
        paged_kw["decode_sparsity"] = (
            "causal" if decode_sparsity is None else str(decode_sparsity)
        )
        return cls(
            max_batch=max(int(b) for b in batch_shapes),
            chunk_tokens=chunk_tokens,
            prefill_batch=prefill_batch,
            **paged_kw,
            **common,
        )
    return GenerationEngine(batch_shapes=batch_shapes, **common)
