"""Fleet-safe SSE streaming for `POST /generate` (ROADMAP item 1).

The decode loop already advances in `chunk_tokens`-token chunks with a
host snapshot at every boundary; this module turns those boundaries into
a Server-Sent-Events stream the fleet can splice across replicas:

  * `RequestStream` — one bounded, absolutely-sequenced event channel
    per streaming request. The continuous batcher's worker is the ONLY
    writer (progress at every chunk boundary, a progressive preview
    every `preview_every` chunks); the HTTP handler thread that owns the
    client socket is the reader. Events carry the REQUEST-level chunk
    index (min decode position across the request's rows, in chunks), so
    the index is content-addressed, not dispatch-addressed: a preempted
    request that restarts at position 0 on a non-resume engine re-decodes
    bit-identical tokens through chunk indices the stream has already
    emitted, and the monotonic high-water filter silently swallows the
    replay — readers never see a duplicated or regressing chunk event.
  * `StreamRegistry` — request-key → live stream map. A re-dispatched
    request (router failover retry, network blip between router and
    replica) that lands on a replica already decoding the SAME request
    key re-attaches to the live stream instead of submitting a
    duplicate; attachment is generational, so the superseded handler
    notices it was stolen and exits WITHOUT firing the disconnect-cancel.
  * SSE wire codec — `encode_sse` (writer side) and the incremental
    `SSEParser` (the fleet router's splice reads a replica's event
    stream through it, forwarding only events whose chunk index advances
    the client's high water across migration/failover seams).

Pixels ride events as arrays and are PNG/base64-encoded by the reader at
write time: the worker's chunk boundary pays one fixed-shape preview
dispatch (`engine.preview_pixels`), never host-side image encoding.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: SSE comment line the writer emits on idle timeouts so proxies and
#: clients can distinguish "decode is slow" from "connection is dead"
KEEPALIVE = b": keep-alive\n\n"

#: event types a stream can carry; terminal types end the stream
TERMINAL_TYPES = ("result", "error", "migrated")


def encode_sse(etype: str, data: Dict[str, Any],
               seq: Optional[int] = None) -> bytes:
    """One SSE frame: optional `id:` (the absolute event sequence — a
    re-attaching client resumes with `Last-Event-ID`), `event:`, one
    `data:` line of compact JSON, blank-line terminator."""
    lines = []
    if seq is not None:
        lines.append(f"id: {int(seq)}")
    lines.append(f"event: {etype}")
    lines.append(
        "data: " + json.dumps(data, separators=(",", ":"), sort_keys=True)
    )
    return ("\n".join(lines) + "\n\n").encode("utf-8")


class SSEParser:
    """Incremental SSE decoder for the router's stream splice: feed raw
    socket bytes, collect completed `(etype, data, seq)` frames. Comment
    lines (keep-alives) are dropped; `data:` lines accumulate per the
    SSE spec and parse as JSON at frame end. Single-threaded by design —
    one parser per upstream connection, owned by the proxying handler."""

    def __init__(self):
        self._buf = b""
        self._etype: Optional[str] = None
        self._data: List[str] = []
        self._seq: Optional[int] = None

    def feed(self, chunk: bytes) -> List[Tuple[str, dict, Optional[int]]]:
        self._buf += chunk
        out: List[Tuple[str, dict, Optional[int]]] = []
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            text = line.decode("utf-8", errors="replace").rstrip("\r")
            if not text:  # blank line: frame boundary
                if self._etype is not None or self._data:
                    try:
                        data = json.loads("\n".join(self._data) or "{}")
                    except ValueError:
                        data = {"raw": "\n".join(self._data)}
                    out.append((self._etype or "message", data, self._seq))
                self._etype, self._data, self._seq = None, [], None
                continue
            if text.startswith(":"):
                continue  # comment / keep-alive
            field, _, value = text.partition(":")
            value = value[1:] if value.startswith(" ") else value
            if field == "event":
                self._etype = value
            elif field == "data":
                self._data.append(value)
            elif field == "id":
                try:
                    self._seq = int(value)
                except ValueError:
                    self._seq = None
        return out


class RequestStream:  # tracelint: threads
    """Per-request event channel between the batcher worker (writer) and
    the SSE handler thread that owns the client socket (reader).

    Lock discipline: every mutable field is guarded by `_cond`'s lock;
    `emit`/`finish` are called from the worker thread only, reader-side
    methods from whichever handler thread currently holds the attachment
    generation. Events keep ABSOLUTE sequence numbers even after the
    bounded buffer trims its prefix, so a re-attaching reader's
    `Last-Event-ID` cursor stays meaningful across the trim."""

    def __init__(
        self,
        key: Optional[str],
        trace_id: Optional[str] = None,
        max_events: int = 1024,
    ):
        self.key = key
        self.trace_id = trace_id
        self.created_at = time.monotonic()
        self._cond = threading.Condition()
        self._events: List[Tuple[int, str, dict]] = []
        self._base = 0  # absolute seq of _events[0]
        self._dropped = 0
        self.max_events = max(8, int(max_events))
        self._finished = False
        self._gen = 0  # reader attachment generation
        self._orphaned = False  # current reader's socket died
        # monotonic high-water marks: request-level chunk indices already
        # emitted — a non-resume re-decode replays below them silently
        self._progress_chunk = -1
        self._preview_chunk = -1
        self.previews_sent = 0
        self.reattaches = 0
        self.events_emitted = 0
        #: the GenRequest this stream narrates (set by the server at
        #: submit time; the disconnect-cancel path reads it)
        self.request = None

    # ------------------------------------------------------- writer side

    def emit(self, etype: str, **data) -> bool:
        """Append one event (worker thread). Returns False when the
        stream already finished (late boundary after a terminal)."""
        with self._cond:
            if self._finished:
                return False
            self._append(etype, data)
            return True

    def progress(self, chunk: int, **data) -> bool:
        """Chunk-boundary progress, deduplicated: only a chunk index
        ABOVE the high water emits (re-decoded chunks after a restart
        replay silently — readers never see a duplicate)."""
        with self._cond:
            if self._finished or chunk <= self._progress_chunk:
                return False
            self._progress_chunk = int(chunk)
            self._append("progress", dict(data, chunk=int(chunk)))
            return True

    def preview_due(self, chunk: int, every: int) -> bool:
        """Would a preview at `chunk` emit? (worker asks BEFORE paying
        the snapshot + preview dispatch for this request's rows)."""
        with self._cond:
            return (
                not self._finished
                and every > 0
                and chunk > 0
                and chunk % every == 0
                and chunk > self._preview_chunk
            )

    def preview(self, chunk: int, **data) -> bool:
        with self._cond:
            if self._finished or chunk <= self._preview_chunk:
                return False
            self._preview_chunk = int(chunk)
            self.previews_sent += 1
            self._append("preview", dict(data, chunk=int(chunk)))
            return True

    def finish(self, etype: str, **data) -> bool:
        """Terminal event; exactly one wins (the resolving handler and a
        re-attached handler may race here)."""
        with self._cond:
            if self._finished:
                return False
            self._append(etype, data)
            self._finished = True
            return True

    def wake(self) -> None:
        """Nudge the reader without an event (future resolved)."""
        with self._cond:
            self._cond.notify_all()

    def _append(self, etype: str, data: dict) -> None:
        # caller holds the lock
        self._events.append((self._base + len(self._events), etype, data))
        self.events_emitted += 1
        if len(self._events) > self.max_events:
            trim = len(self._events) - self.max_events
            self._events = self._events[trim:]
            self._base += trim
            self._dropped += trim
        self._cond.notify_all()

    # ------------------------------------------------------- reader side

    def attach(self, mark_reattach: bool = True) -> int:
        """Claim the stream for this reader; any previous reader's
        generation is superseded (it exits without cancelling)."""
        with self._cond:
            self._gen += 1
            self._orphaned = False
            if self._gen > 1 and mark_reattach:
                self.reattaches += 1
            self._cond.notify_all()
            return self._gen

    def current(self, gen: int) -> bool:
        with self._cond:
            return gen == self._gen

    def orphan(self, gen: int) -> bool:
        """Reader's socket died. True when it was still the CURRENT
        reader (caller then cancels the request — a superseded reader
        must never cancel the request its successor is streaming)."""
        with self._cond:
            if gen != self._gen:
                return False
            self._orphaned = True
            return True

    @property
    def orphaned(self) -> bool:
        with self._cond:
            return self._orphaned

    @property
    def finished(self) -> bool:
        with self._cond:
            return self._finished

    def next_events(
        self, since: int, timeout: Optional[float] = None
    ) -> Tuple[List[Tuple[int, str, dict]], bool]:
        """Events with seq >= `since` (after the trim floor), blocking up
        to `timeout` for the first one. Returns (events, finished-and-
        drained) — an empty batch with False means keep-alive time."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                start = max(int(since), self._base)
                batch = self._events[start - self._base:]
                drained = self._finished and not batch
                if batch or drained:
                    return list(batch), drained
                if deadline is not None:
                    remain = deadline - time.monotonic()
                    if remain <= 0:
                        return [], False
                    self._cond.wait(remain)
                else:
                    self._cond.wait()

    def end_seq(self) -> int:
        with self._cond:
            return self._base + len(self._events)

    def detail(self) -> dict:
        """healthz / debug snapshot."""
        with self._cond:
            return {
                "key": self.key,
                "trace_id": self.trace_id,
                "events": self.events_emitted,
                "dropped": self._dropped,
                "previews_sent": self.previews_sent,
                "reattaches": self.reattaches,
                "progress_chunk": self._progress_chunk,
                "finished": self._finished,
                "orphaned": self._orphaned,
                "age_s": round(time.monotonic() - self.created_at, 3),
            }


class StreamRegistry:  # tracelint: threads
    """Request-key → live `RequestStream` map (one per server). Keyed by
    the router's content key (`x-dalle-request-key`), the fleet-wide
    join identity — a re-dispatched request re-attaches here instead of
    double-submitting. Bounded: past `max_streams`, finished/orphaned
    streams evict oldest-first; live attached streams are never evicted —
    a registry full of live streams refuses new registrations instead,
    which the server surfaces as backpressure (503)."""

    def __init__(self, max_streams: int = 256, gauge=None):
        self._lock = threading.Lock()
        self._streams: Dict[str, RequestStream] = {}
        self.max_streams = max(1, int(max_streams))
        self._gauge = gauge  # streams_active gauge setter (optional)
        self.total_opened = 0
        self.total_reattached = 0

    def _set_gauge(self) -> None:
        # caller holds the lock
        if self._gauge is not None:
            try:
                self._gauge(len(self._streams))
            except Exception:
                pass

    def register(self, stream: RequestStream) -> bool:
        """Add a fresh stream under its key (anonymous streams — no
        request key — are tracked under a synthetic id so the gauge and
        healthz still see them). False when the registry is full of
        LIVE streams (caller sheds)."""
        key = stream.key or f"anon-{id(stream):x}"
        stream.key = key
        with self._lock:
            self._evict_locked()
            if len(self._streams) >= self.max_streams:
                return False
            self._streams[key] = stream
            self.total_opened += 1
            self._set_gauge()
            return True

    def get(self, key: Optional[str]) -> Optional[RequestStream]:
        if not key:
            return None
        with self._lock:
            return self._streams.get(key)

    def reattach(self, key: Optional[str]) -> Optional[RequestStream]:
        """The live (unfinished) stream for `key`, if any — the caller
        then `attach()`es, stealing the reader generation."""
        if not key:
            return None
        with self._lock:
            st = self._streams.get(key)
            if st is None or st.finished:
                return None
            self.total_reattached += 1
            return st

    def discard(self, stream: RequestStream) -> None:
        with self._lock:
            key = stream.key
            if key is not None and self._streams.get(key) is stream:
                del self._streams[key]
                self._set_gauge()

    def _evict_locked(self) -> None:
        if len(self._streams) < self.max_streams:
            return
        dead = sorted(
            (
                (st.created_at, key)
                for key, st in self._streams.items()
                if st.finished or st.orphaned
            ),
        )
        for _, key in dead:
            if len(self._streams) < self.max_streams:
                break
            del self._streams[key]
        self._set_gauge()

    def active(self) -> int:
        with self._lock:
            return len(self._streams)

    def detail(self, limit: int = 8) -> dict:
        """/healthz streaming block: counts plus the oldest few streams'
        snapshots (bounded so a busy server's health body stays small)."""
        with self._lock:
            streams = sorted(
                self._streams.values(), key=lambda s: s.created_at
            )
            opened, reattached = self.total_opened, self.total_reattached
        return {
            "active": len(streams),
            "opened_total": opened,
            "reattached_total": reattached,
            "max_streams": self.max_streams,
            "streams": [s.detail() for s in streams[:limit]],
        }
