"""Batched text→image generation service.

The serving layer the ROADMAP north star calls for: a dynamic request
queue feeding fixed-shape compiled sampler programs.

  * `engine.py`   — `GenerationEngine`: wraps the KV-cached sampler
    (`models/dalle.py:generate_images_cached_batched`) behind a fixed set
    of compiled batch shapes, pads partial batches, warms up compilation,
    and optionally CLIP-reranks results. `ContinuousEngine` +
    `SlotAllocator`: continuous batching — one persistent decode state of
    `max_batch` cache slots advanced in K-token chunks, prompts admitted
    into free slots at token boundaries in batched prefill waves
    (`models/dalle.py:prefill_into_slots` / `decode_image_chunk`).
  * `sharded.py`  — `ShardedContinuousEngine`: the same continuous
    engine spread over a `make_mesh` device mesh — params per
    `parallel/partition.py`'s rules, the slot KV cache head-split per
    `parallel/serving_partition.py`, the flash-decode kernel
    shard_map-split per head. Same program bodies, same serving
    surface, bit-identical tokens; `serve.py --mesh dp=1,tp=4`.
  * `batcher.py`  — `MicroBatcher`: bounded queue with dynamic
    micro-batching (flush on max-batch or deadline), backpressure via
    queue-full rejection, per-request timeout/cancellation, graceful
    drain. `ContinuousBatcher`: same queue surface, but an
    admit→chunk→retire worker loop over the slot cache, with decode-time
    priority preemption, chunk-boundary cancel/timeout retirement, and
    one bounded retry after a failed dispatch rebuilds engine state.
  * `qos.py`      — priority classes ("high"/"normal"/"low"), the
    `WeightedFairQueue` stride scheduler with per-tenant accounting,
    tenant quotas (`TenantQuotaError` → 429) and deadline-aware
    admission shedding (`ShedError` → 503 + Retry-After).
  * `migrate.py`  — decode-state checkpoints: `RowCheckpoint` /
    `RequestCheckpoint` + the fingerprint-stamped codec, the
    `CheckpointSpool` crash-beacon journal, and `MigratedError` (the
    chunk-boundary export of `drain?migrate=1`). A drained or crashed
    replica's in-flight requests MOVE — completed rows restore verbatim
    on the resuming replica, unfinished rows restart bit-identically —
    instead of being waited out or re-decoded from scratch.
  * `faults.py`   — `FaultInjector`: deterministic fail-Nth / stall-Nth
    / crash-Nth seam on engine dispatches plus compile-cache artifact
    corruption, for recovery-invariant tests and chaos drills (attach
    to `engine.faults` / `CompileCache.faults`).
  * `supervisor.py` — `ReplicaSupervisor`: crash-fast replica restart —
    spawn the serve.py subprocess, gate readiness on its real /healthz,
    restart abnormal exits with capped exponential backoff, hold down
    crash loops (N exits in a window) with a structured `crash_loop`
    event. `serve.py --supervise` or
    `python -m dalle_pytorch_tpu.serving.supervisor -- cmd...`; pair
    with `serve.py --compile_cache` so a restart rejoins in seconds.
  * `router.py`   — `FleetRouter` + `RouterServer`: ONE admission router
    in front of N replicas (`python -m dalle_pytorch_tpu.serving.router`
    / `serve.py --router --replicas ...`): /healthz-probed per-replica
    state (healthy / degraded-deprioritized / ejected) with a rolling
    error-rate circuit breaker, least-outstanding routing with QoS
    spillover and Retry-After class cooldowns, failover retries under a
    success-fraction retry budget (seed pinned at ingress, so
    re-dispatch is bit-identical), optional tail hedging, and graceful
    drain (`POST /admin/drain?replica=` — a rolling restart is a
    zero-error event).
  * `server.py`   — stdlib-only JSON HTTP API: POST /generate,
    GET /healthz (ok / degraded / 503 tiers), GET /metrics (Prometheus
    text format; `?exemplars=1` for OpenMetrics exemplars),
    GET /debug/traces (Perfetto export; `?trace_id=` exact lookup),
    GET /debug/vitals + /debug/programs + /debug/state (device
    telemetry, per-program cost/MFU table, engine-state dump —
    `obs/vitals.py`), POST /debug/profile (on-demand jax.profiler
    capture). Requests are traced end-to-end through the batcher by
    `dalle_pytorch_tpu/obs/` — trace ID minted at ingress, one span per
    stage, one structured JSON log line per completed request.

`serve.py` at the repo root is the CLI entrypoint; `generate.py` drives
the same `GenerationEngine` for one-shot CLI batches, so the two paths
cannot drift.
"""

from dalle_pytorch_tpu.serving.engine import (
    ContinuousEngine,
    GenerationEngine,
    SampleSpec,
    SlotAllocator,
    engine_from_checkpoint,
)
from dalle_pytorch_tpu.serving.sharded import (
    ShardedContinuousEngine,
    build_serving_mesh,
    parse_mesh_shape,
)
from dalle_pytorch_tpu.serving.batcher import (
    ContinuousBatcher,
    MicroBatcher,
    QueueFullError,
    RequestCancelled,
    RequestTimeout,
    ShuttingDownError,
)
from dalle_pytorch_tpu.serving.faults import FaultInjector, InjectedFault
from dalle_pytorch_tpu.serving.migrate import (
    CheckpointCorrupt,
    CheckpointMismatch,
    CheckpointSpool,
    MigratedError,
    RequestCheckpoint,
    RowCheckpoint,
    decode_checkpoint,
    encode_checkpoint,
    from_wire,
    to_wire,
)
from dalle_pytorch_tpu.serving.qos import (
    PRIORITY_CLASSES,
    ShedError,
    TenantQuotaError,
    WeightedFairQueue,
)
from dalle_pytorch_tpu.serving.router import (
    FleetRouter,
    QuarantineTracker,
    RetryBudget,
    RouterServer,
    request_fingerprint,
)
from dalle_pytorch_tpu.serving.server import ServingServer
from dalle_pytorch_tpu.serving.supervisor import ReplicaSupervisor

__all__ = [
    "CheckpointCorrupt",
    "CheckpointMismatch",
    "CheckpointSpool",
    "ContinuousBatcher",
    "ContinuousEngine",
    "FaultInjector",
    "MigratedError",
    "RequestCheckpoint",
    "RowCheckpoint",
    "decode_checkpoint",
    "encode_checkpoint",
    "from_wire",
    "to_wire",
    "GenerationEngine",
    "InjectedFault",
    "PRIORITY_CLASSES",
    "SampleSpec",
    "ShedError",
    "SlotAllocator",
    "TenantQuotaError",
    "WeightedFairQueue",
    "engine_from_checkpoint",
    "FleetRouter",
    "QuarantineTracker",
    "ReplicaSupervisor",
    "RetryBudget",
    "RouterServer",
    "request_fingerprint",
    "MicroBatcher",
    "QueueFullError",
    "RequestCancelled",
    "RequestTimeout",
    "ShuttingDownError",
    "ServingServer",
    "ShardedContinuousEngine",
    "build_serving_mesh",
    "parse_mesh_shape",
]
