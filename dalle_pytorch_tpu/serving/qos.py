"""Priority classes, weighted-fair queuing, and per-tenant accounting.

The batcher's original intake was one FIFO: under overload, whoever floods
first wins, and a single tenant spamming cheap low-value requests starves
everyone (ROADMAP §5). This module is the host-side scheduling core of the
QoS layer:

  * three priority classes — "high" / "normal" / "low" — with weights
    (default 8 / 4 / 1). Scheduling is STRIDE-style weighted fair queuing:
    the next request comes from the non-empty class with the smallest
    `rows_served / weight`, so a backlogged high class gets ~8x the
    admission share of a backlogged low class, but low is never starved
    outright — after at most `sum(weights)/weight[low]` row-admissions the
    low class's ratio is the minimum and it MUST be picked (the starvation
    bound tests/test_qos.py pins via trace timestamps).
  * per-tenant fairness WITHIN a class: each (class, tenant) pair gets its
    own deque, and the class serves the tenant with the least
    rows_served / weight so far — one tenant flooding the low class
    degrades only its own latency, not other low-class tenants'. Tenant
    weights (`tenant_weights={"a": 4, "b": 1}`, `serve.py
    --tenant_weights a=4,b=1`) make that fairness PROPORTIONAL instead
    of equal: a backlogged weight-4 tenant gets ~4x the admission share
    of a backlogged weight-1 tenant in the same class (unlisted tenants
    weigh 1). Weights are shares, not caps — quotas stay the hard bound.
  * per-tenant quotas: `tenant_rows` counts a tenant's queued rows so the
    batcher can 429 a tenant past its share (`TenantQuotaError`).

Everything is plain host state mutated under the batcher's condition lock
(same threading contract as the old deque). `push_front` exists for the
preemption/retry resume path: a suspended request goes back to the FRONT
of its own (class, tenant) deque so it is the next thing its tenant runs,
but it gains no priority over other classes — a preempted low request
stays preemptible.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional, Tuple

#: admission classes, best-first; index = numeric class (lower = better)
PRIORITY_CLASSES = ("high", "normal", "low")

#: relative admission share of a backlogged class (stride scheduling)
DEFAULT_CLASS_WEIGHTS = {"high": 8.0, "normal": 4.0, "low": 1.0}


def priority_class(priority: str) -> int:
    """Numeric class for a priority name; raises ValueError on junk (the
    HTTP layer maps that to 400)."""
    try:
        return PRIORITY_CLASSES.index(priority)
    except ValueError:
        raise ValueError(
            f"unknown priority {priority!r}; expected one of "
            f"{PRIORITY_CLASSES}"
        ) from None


class ShedError(RuntimeError):
    """Admission-time load shed: the cost model says this request's SLO
    cannot be met (503 + Retry-After at the HTTP layer — reject NOW so
    the client can retry elsewhere, instead of queueing it to a certain
    timeout)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 reason: str = "deadline"):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


class TenantQuotaError(RuntimeError):
    """Tenant exceeded its queued-rows quota (429 at the HTTP layer)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class WeightedFairQueue:
    """Per-class, per-tenant request queues with stride-scheduled pops.

    Requests need `.klass` (int index into PRIORITY_CLASSES), `.tenant`
    (str, "" = the shared default tenant), and `.pending_rows` (int —
    rows still to serve; the service-accounting unit). NOT thread-safe:
    the batcher mutates it under its own condition lock, exactly like the
    deque it replaces.
    """

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 tenant_weights: Optional[Dict[str, float]] = None):
        w = dict(DEFAULT_CLASS_WEIGHTS)
        if weights:
            w.update(weights)
        assert all(w.get(c, 0) > 0 for c in PRIORITY_CLASSES), (
            f"every class needs a positive weight, got {w}"
        )
        self.weights = tuple(float(w[c]) for c in PRIORITY_CLASSES)
        # per-tenant admission shares within a class (stride scheduling
        # over rows_served / weight, same math as the class level);
        # tenants not listed weigh 1.0
        self.tenant_weights = {
            str(t): float(v) for t, v in (tenant_weights or {}).items()
        }
        assert all(v > 0 for v in self.tenant_weights.values()), (
            f"tenant weights must be positive, got {self.tenant_weights}"
        )
        # class -> tenant -> deque[request]; OrderedDict keeps tenant
        # iteration deterministic (test-friendly tie-breaks)
        self._queues: Tuple["OrderedDict[str, deque]", ...] = tuple(
            OrderedDict() for _ in PRIORITY_CLASSES
        )
        # stride accounting: rows served per class / per (class, tenant).
        # Never reset while the process lives — ratios, not totals, drive
        # scheduling, so unbounded growth is fine (floats).
        self._class_served = [0.0 for _ in PRIORITY_CLASSES]
        self._tenant_served: List[Dict[str, float]] = [
            {} for _ in PRIORITY_CLASSES
        ]
        self._len = 0
        self._rows = 0
        self._class_rows = [0 for _ in PRIORITY_CLASSES]
        self._tenant_rows: Dict[str, int] = {}

    # ------------------------------------------------------------ intake

    def push(self, req) -> None:
        self._pre_insert(req)
        self._queues[req.klass].setdefault(req.tenant, deque()).append(req)
        self._account(req, +1)

    def push_front(self, req) -> None:
        """Resume path: next in line WITHIN its own (class, tenant) queue
        — no cross-class priority gain."""
        self._pre_insert(req)
        self._queues[req.klass].setdefault(req.tenant, deque()).appendleft(req)
        self._account(req, +1)

    def _pre_insert(self, req) -> None:
        """Reactivation clamp (classic WFQ virtual-time catch-up): a
        class or tenant that sat IDLE must not bank scheduling credit.
        Without this, after a long high-only period a low burst's ratio
        (served/weight) would undercut high's by the whole idle span and
        outrank it for thousands of admissions — priority inverted. The
        clamp also keeps preemption churn-free: a preempted victim
        re-queued into its empty class re-enters at the CURRENT minimum
        ratio, tying — not beating — the blocked head it was evicted
        for, and ties break toward the better class."""
        k = req.klass
        if not any(self._queues[k].values()):
            active = [
                j for j, tenants in enumerate(self._queues)
                if any(tenants.values())
            ]
            if active:
                floor = min(
                    self._class_served[j] / self.weights[j] for j in active
                )
                self._class_served[k] = max(
                    self._class_served[k], floor * self.weights[k]
                )
        q = self._queues[k].get(req.tenant)
        if q is None or not q:
            served = self._tenant_served[k]
            backlogged = [t for t, tq in self._queues[k].items() if tq]
            if backlogged:
                # weighted virtual time, like the class-level clamp: the
                # floor is the minimum served/weight RATIO, and the idle
                # tenant re-enters at that ratio scaled by its own weight
                floor = min(
                    served.get(t, 0.0) / self.tenant_weight(t)
                    for t in backlogged
                )
                served[req.tenant] = max(
                    served.get(req.tenant, 0.0),
                    floor * self.tenant_weight(req.tenant),
                )

    def _account(self, req, sign: int) -> None:
        self._len += sign
        rows = sign * int(req.pending_rows)
        self._rows += rows
        self._class_rows[req.klass] += rows
        t = self._tenant_rows.get(req.tenant, 0) + rows
        if t > 0:
            self._tenant_rows[req.tenant] = t
        else:
            self._tenant_rows.pop(req.tenant, None)

    def tenant_weight(self, tenant: str) -> float:
        """Admission-share weight of one tenant (1.0 unless configured)."""
        return self.tenant_weights.get(tenant, 1.0)

    # --------------------------------------------------------- scheduling

    def _pick(self) -> Optional[Tuple[int, str]]:
        """(class, tenant) the scheduler serves next, or None when empty:
        smallest rows_served/weight class, then its least-served tenant."""
        best = None
        best_ratio = None
        for k, tenants in enumerate(self._queues):
            if not any(tenants.values()):
                continue
            ratio = self._class_served[k] / self.weights[k]
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = k, ratio
        if best is None:
            return None
        served = self._tenant_served[best]
        tenant = min(
            (t for t, q in self._queues[best].items() if q),
            key=lambda t: served.get(t, 0.0) / self.tenant_weight(t),
        )
        return best, tenant

    def peek(self):
        """The request the scheduler would pop next (None when empty).
        Deterministic: repeated peeks without intervening push/pop return
        the same request, so the batcher's peek-validate-pop idiom holds."""
        pick = self._pick()
        if pick is None:
            return None
        k, tenant = pick
        return self._queues[k][tenant][0]

    def pop(self, charge: bool = True):
        """Pop the scheduled head. `charge=False` skips service accounting
        — popping a cancelled/expired request consumed no capacity and
        must not cost its class its fair share."""
        pick = self._pick()
        assert pick is not None, "pop from an empty queue"
        k, tenant = pick
        req = self._queues[k][tenant].popleft()
        self._account(req, -1)
        if charge:
            rows = max(1, int(req.pending_rows))
            self._class_served[k] += rows
            served = self._tenant_served[k]
            served[tenant] = served.get(tenant, 0.0) + rows
        return req

    # ------------------------------------------------------------- views

    def __len__(self) -> int:
        return self._len

    @property
    def rows(self) -> int:
        return self._rows

    def tenant_rows(self, tenant: str) -> int:
        return self._tenant_rows.get(tenant, 0)

    def rows_at_or_better(self, klass: int) -> int:
        """Queued rows in class `klass` or better. The queue-full bound
        competes a new request only against rows its own class must wait
        behind — a low-class flood fills the LOW horizon and 503s itself,
        while high-class arrivals still see a near-empty queue (worst-
        case total memory stays bounded at n_classes x the row bound)."""
        return sum(self._class_rows[: klass + 1])

    def class_depths(self) -> Dict[str, int]:
        """{class name: queued rows} for gauges / healthz / vitals."""
        out = {}
        for k, name in enumerate(PRIORITY_CLASSES):
            out[name] = sum(
                sum(int(r.pending_rows) for r in q)
                for q in self._queues[k].values()
            )
        return out

    def requests(self) -> List:
        """Every queued request, class-major then tenant arrival order —
        a stable snapshot for state dumps and shutdown sweeps."""
        out = []
        for tenants in self._queues:
            for q in tenants.values():
                out.extend(q)
        return out

    def oldest_enqueued_at(self) -> Optional[float]:
        """Earliest `enqueued_at` across everything queued (head-age
        staleness signal for the watchdog; None when empty)."""
        times = [r.enqueued_at for r in self.requests()]
        return min(times) if times else None

    def drain(self) -> Iterable:
        """Pop everything (shutdown drain=False path)."""
        out = self.requests()
        for tenants in self._queues:
            tenants.clear()
        self._len = 0
        self._rows = 0
        self._tenant_rows.clear()
        return out
