"""Decode-state checkpoints: zero-lost-work drain and mid-decode migration.

A replica drain used to wait out every in-flight request (minutes at long
seq-len), and a crash re-decoded every in-flight row from token 0 on
whichever replica the router failed over to. This module promotes the
PR 11 preemption snapshot into a versioned, serializable **decode-state
checkpoint** so in-flight work MOVES instead of dying:

  * `RowCheckpoint` / `RequestCheckpoint` — one request's decode state at
    a chunk boundary: prompt tokens, generated-so-far tokens per row
    (full rows for already-harvested ones), per-row sampling params
    (seed / temperature / top_k), the engine chunk index, QoS identity
    (priority / tenant), and trace context. Decode RNG is
    (seed, position)-keyed, so a checkpoint is sufficient to finish the
    request BIT-IDENTICALLY anywhere the same build runs: completed rows
    are restored verbatim (never re-decoded), unfinished rows re-enter
    admission as a preempt-resume — front-of-class re-queue, and on the
    paged engine the re-prefill is a prefix-cache hit.
  * the codec — `encode_checkpoint` stamps a MAGIC + JSON header
    (format version, **boot fingerprint**, sha256, payload length) onto
    a JSON payload, mirroring `utils/compile_cache.py`'s artifact
    container. `decode_checkpoint` validates all of it: a fingerprint or
    format mismatch raises `CheckpointMismatch` (a snapshot from a
    different build must not resume — the consumer falls back to a clean
    position-0 restart, counted), and a truncated/garbled payload raises
    `CheckpointCorrupt` (same fallback, counted separately). A bad
    checkpoint can never become a corrupt resume, only a cold restart.
  * `CheckpointSpool` — the crash-path progress beacon's bounded on-disk
    journal (`serve.py --checkpoint_spool DIR`): every N chunks the
    batcher rewrites one atomic JSONL file with the current in-flight
    checkpoints, so a SIGKILL loses at most N chunks of bookkeeping. The
    PR 13 supervisor reads the spool after the restarted replica is
    ready and hands it to the fleet router (`POST /admin/spool`), whose
    failover path resumes the affected requests from the journaled state
    instead of from scratch. Reads run through the same
    `FaultInjector.on_artifact_load` seam as compile-cache artifacts, so
    torn-write rejection is chaos-testable.

Wire transport (the `resume` field of POST /generate, the 409 payload of
a migrated request, the spool hand-off) is base64 of the binary blob —
`to_wire` / `from_wire` — so one codec covers HTTP and disk.
"""

from __future__ import annotations

import base64
import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

#: container format — bump on any layout change so an old checkpoint is a
#: clean mismatch, not a parse error
CKPT_FORMAT = 1
CKPT_MAGIC = b"DALLECKPT\n"

#: spool journal filename inside --checkpoint_spool DIR
SPOOL_FILE = "checkpoints.jsonl"


class CheckpointMismatch(ValueError):
    """Checkpoint from a different build (fingerprint/format drift).
    Consumers MUST fall back to a clean position-0 restart — resuming
    decode state across builds is exactly the corruption the fingerprint
    exists to prevent."""


class CheckpointCorrupt(ValueError):
    """Checkpoint failed integrity validation (bad magic, truncated
    payload, checksum mismatch, unparseable body). Same fallback as
    `CheckpointMismatch`, counted separately so a sick spool volume is
    distinguishable from a fleet rollout."""


class MigratedError(RuntimeError):
    """A request's in-flight decode state was exported at a chunk
    boundary by `drain?migrate=1`. Carries the `RequestCheckpoint`; the
    HTTP layer maps it to a 409 whose body holds the encoded checkpoint
    so the fleet router can re-dispatch the SAME request as a resume."""

    def __init__(self, checkpoint: "RequestCheckpoint"):
        super().__init__("request migrated out at a chunk boundary")
        self.checkpoint = checkpoint


@dataclass
class RowCheckpoint:
    """One batch row's decode state at a chunk boundary."""

    row_index: int
    prompt_ids: np.ndarray  # [text_seq_len] int32
    tokens: np.ndarray  # [pos] int32 generated so far (whole row when done)
    done: bool
    seed: int
    temperature: float = 1.0
    top_k: float = 0.9

    @property
    def pos(self) -> int:
        return int(len(self.tokens))


@dataclass
class RequestCheckpoint:
    """One request's rows plus the identity a resume must preserve."""

    rows: List[RowCheckpoint]
    chunk_index: int = 0  # engine chunk index at snapshot (resumed_at_chunk)
    priority: str = "normal"
    tenant: str = ""
    trace_id: Optional[str] = None
    site: Optional[str] = None  # exporting replica (migrated_from)
    request_key: Optional[str] = None  # router content key (x-dalle-request-key)
    reason: str = "drain"  # drain | beacon
    #: encode-once cache (NOT part of the wire payload): the exporting
    #: batcher stamps the encoded blob here so the 409 body and the
    #: admin bundle don't each re-serialize the full token payload
    encoded: Optional[bytes] = None

    def done_tokens(self) -> int:
        """Tokens a resume restores without re-decoding (completed rows
        verbatim; partial rows restart at position 0 — their snapshot is
        the bit-identity oracle, not a shortcut)."""
        return sum(cp.pos for cp in self.rows if cp.done)


def _row_to_json(cp: RowCheckpoint) -> Dict:
    return {
        "row": int(cp.row_index),
        "prompt": np.asarray(cp.prompt_ids, np.int32).tolist(),
        "tokens": np.asarray(cp.tokens, np.int32).tolist(),
        "done": bool(cp.done),
        "seed": int(cp.seed),
        "temperature": float(cp.temperature),
        "top_k": float(cp.top_k),
    }


def _row_from_json(obj: Dict) -> RowCheckpoint:
    return RowCheckpoint(
        row_index=int(obj["row"]),
        prompt_ids=np.asarray(obj["prompt"], np.int32),
        tokens=np.asarray(obj["tokens"], np.int32),
        done=bool(obj["done"]),
        seed=int(obj["seed"]),
        temperature=float(obj.get("temperature", 1.0)),
        top_k=float(obj.get("top_k", 0.9)),
    )


def encode_checkpoint(cp: RequestCheckpoint, fingerprint: str) -> bytes:
    """RequestCheckpoint -> self-validating blob, via the SAME container
    pack the compile cache's AOT artifacts use
    (`utils/compile_cache.pack_artifact`) — one integrity layout, one
    reject taxonomy, one set of fault seams."""
    from dalle_pytorch_tpu.utils.compile_cache import pack_artifact

    payload = json.dumps(
        {
            "rows": [_row_to_json(r) for r in cp.rows],
            "chunk_index": int(cp.chunk_index),
            "priority": cp.priority,
            "tenant": cp.tenant,
            "trace_id": cp.trace_id,
            "site": cp.site,
            "request_key": cp.request_key,
            "reason": cp.reason,
        },
        sort_keys=True,
        separators=(",", ":"),
    ).encode("utf-8")
    return pack_artifact(
        CKPT_MAGIC, fingerprint, payload, format_version=CKPT_FORMAT
    )


def decode_checkpoint(blob: bytes, fingerprint: str) -> RequestCheckpoint:
    """Validate + decode one checkpoint blob against the CONSUMER's boot
    fingerprint (`utils/compile_cache.unpack_artifact` does the shared
    container validation). Raises `CheckpointMismatch` for cross-build
    snapshots (format or fingerprint drift — the "miss" verdict) and
    `CheckpointCorrupt` for integrity failures (the "reject" verdict) —
    callers map both to a clean position-0 restart, never to a
    client-visible error or a resumed corrupt state."""
    from dalle_pytorch_tpu.utils.compile_cache import unpack_artifact

    if not isinstance(blob, (bytes, bytearray)):
        raise CheckpointCorrupt("checkpoint must be bytes")
    status, reason, payload = unpack_artifact(
        bytes(blob), CKPT_MAGIC, fingerprint, format_version=CKPT_FORMAT
    )
    if status == "miss":
        raise CheckpointMismatch(
            f"{reason} (checkpoint from a different build)"
        )
    if status != "hit":
        raise CheckpointCorrupt(str(reason))
    try:
        obj = json.loads(payload)
        rows = [_row_from_json(r) for r in obj["rows"]]
    except Exception as exc:
        raise CheckpointCorrupt(f"unparseable payload: {exc!r}") from None
    return RequestCheckpoint(
        rows=rows,
        chunk_index=int(obj.get("chunk_index", 0)),
        priority=str(obj.get("priority", "normal")),
        tenant=str(obj.get("tenant", "")),
        trace_id=obj.get("trace_id"),
        site=obj.get("site"),
        request_key=obj.get("request_key"),
        reason=str(obj.get("reason", "drain")),
    )


def to_wire(blob: bytes) -> str:
    """Blob -> JSON-safe ASCII (the `resume` request field, 409 bodies,
    spool hand-off lines)."""
    return base64.b64encode(bytes(blob)).decode("ascii")


def from_wire(text) -> bytes:
    """Inverse of `to_wire`; raises `CheckpointCorrupt` on garbage so
    transport damage lands in the same counted reject path as disk
    damage."""
    if not isinstance(text, str):
        raise CheckpointCorrupt("wire checkpoint must be a string")
    try:
        return base64.b64decode(text.encode("ascii"), validate=True)
    except Exception as exc:
        raise CheckpointCorrupt(f"bad base64: {exc!r}") from None


class CheckpointSpool:
    """Bounded atomic on-disk journal of in-flight checkpoints.

    `write(bundle)` REPLACES the journal (tmp + rename — a crash mid-write
    leaves the previous beacon intact, never a torn file) with one JSON
    line per request: `{"key": ..., "blob": <base64>}`. The journal is
    latest-state-only by design: each beacon supersedes the last, so the
    spool's size is bounded by the replica's own in-flight set (plus
    `max_bytes` as the hard cap — oversized bundles drop their LARGEST
    entries first and count them, a half-spool beats no spool).

    `read()` returns `{key: blob}` for every line that survives
    validation; unparseable lines are skipped and counted, and the
    `faults` seam (`FaultInjector.on_artifact_load`, shared with the
    compile cache) can truncate/garble the file on disk first so the
    torn-write path is chaos-testable.
    """

    def __init__(self, directory, max_bytes: int = 8 << 20):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / SPOOL_FILE
        self.max_bytes = int(max_bytes)
        #: fault-injection seam (serving/faults.py corrupt_cache rules)
        self.faults = None
        self.writes = 0
        self.dropped_entries = 0
        self.skipped_lines = 0

    def write(self, bundle: Dict[str, bytes]) -> None:
        lines = []
        total = 0
        # biggest-first drop under the byte cap: keeping many small
        # requests' progress beats keeping one huge one
        for key, blob in sorted(bundle.items(), key=lambda kv: len(kv[1])):
            line = json.dumps(
                {"key": str(key), "blob": to_wire(blob), "ts": time.time()}
            )
            if total + len(line) + 1 > self.max_bytes:
                self.dropped_entries += 1
                continue
            total += len(line) + 1
            lines.append(line)
        tmp = self.path.with_suffix(f".tmp{os.getpid()}")
        tmp.write_bytes(("\n".join(lines) + "\n").encode() if lines else b"")
        os.replace(tmp, self.path)
        self.writes += 1

    def read(self) -> Dict[str, bytes]:
        if self.faults is not None:
            self.faults.on_artifact_load("spool", self.path)
        try:
            raw = self.path.read_text()
        except FileNotFoundError:
            return {}
        out: Dict[str, bytes] = {}
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                obj = json.loads(line)
                out[str(obj["key"])] = from_wire(obj["blob"])
            except Exception:
                # torn tail / bit rot: that ENTRY is lost (its request
                # restarts from scratch); the rest of the spool survives
                self.skipped_lines += 1
        return out

    def clear(self) -> None:
        try:
            self.path.unlink()
        except FileNotFoundError:
            pass

    def detail(self) -> Dict:
        return {
            "path": str(self.path),
            "max_bytes": self.max_bytes,
            "writes": self.writes,
            "dropped_entries": self.dropped_entries,
            "skipped_lines": self.skipped_lines,
        }
