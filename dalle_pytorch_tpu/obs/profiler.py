"""On-demand `jax.profiler` capture for a live server.

`training/metrics.py:ProfilerHook` captures a trace around a chosen
TRAINING step; a serving hotspot shows up on a process that has been up
for days and must not be restarted to attach a profiler. `ProfilerCapture`
is the serving-side answer: `POST /debug/profile?seconds=N` starts a
`jax.profiler` trace, holds it open for N seconds of live traffic, stops
it, and returns the TensorBoard trace directory (`tensorboard --logdir`
or xprof reads it).

Guard rails, because the profiler is process-global state:

  * single-flight — one capture at a time; a second request while one is
    in flight raises `ProfilerBusy` (HTTP 409). Concurrent start_trace
    calls would raise deep inside jax otherwise.
  * root-gated — only the root process of a multi-host deployment
    captures (`jax.process_index() == 0`); non-root raises
    `PermissionError` (HTTP 403) instead of writing trace dirs on every
    host.
  * bounded — `seconds` is clamped to `max_seconds` so a typo can't hold
    the profiler (and its buffer growth) open for an hour.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Optional


class ProfilerBusy(RuntimeError):
    """A capture is already in flight (single-flight contract)."""


class ProfilerCapture:
    def __init__(self, out_dir: str = "profiles", max_seconds: float = 60.0):
        self.out_dir = Path(out_dir)
        self.max_seconds = float(max_seconds)
        self._lock = threading.Lock()  # held for the whole capture
        self.last_dir: Optional[Path] = None
        self.captures = 0

    @property
    def busy(self) -> bool:
        return self._lock.locked()

    # jax touchpoints behind override seams: the HTTP-wiring tests drive
    # the real guard-rail logic with these stubbed (a first real capture
    # in a compile-heavy process pays O(10 s) of one-time profiler
    # initialization — too slow and load-sensitive for the fast tier)

    def _process_index(self) -> int:
        import jax

        return jax.process_index()

    def _start(self, trace_dir: Path) -> None:
        import jax

        jax.profiler.start_trace(str(trace_dir))

    def _stop(self) -> None:
        import jax

        jax.profiler.stop_trace()

    def capture(self, seconds: float) -> Path:
        """Blocking capture: start the trace, sleep `seconds` of live
        traffic, stop, return the trace directory. Raises `ProfilerBusy`
        / `PermissionError` / `ValueError` per the guard rails above."""
        seconds = float(seconds)
        if not seconds > 0:
            raise ValueError(f"seconds must be > 0, got {seconds}")
        seconds = min(seconds, self.max_seconds)
        if self._process_index() != 0:
            raise PermissionError(
                f"profiler capture is root-gated; this is process "
                f"{self._process_index()}"
            )
        if not self._lock.acquire(blocking=False):
            raise ProfilerBusy(
                "a profiler capture is already in flight; retry when it "
                "completes"
            )
        try:
            trace_dir = self.out_dir / (
                f"profile_{time.strftime('%Y%m%d_%H%M%S')}_{self.captures}"
            )
            # counted per attempt, not per success: a failed capture must
            # not let a same-second retry reuse (and mix output into) the
            # failed attempt's directory
            self.captures += 1
            trace_dir.mkdir(parents=True, exist_ok=True)
            self._start(trace_dir)
            try:
                time.sleep(seconds)
            finally:
                self._stop()
            self.last_dir = trace_dir
            return trace_dir
        finally:
            self._lock.release()
